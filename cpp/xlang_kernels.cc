// ray_tpu cross-language kernels — native user functions callable from the
// task plane (reference: the C++/Java user-function surface behind
// ray.cross_language, python/ray/cross_language.py + cpp/src task execution).
//
// ABI (the seam ray_tpu/cross_language.py invokes over ctypes):
//
//   int <symbol>(const uint8_t* in, size_t in_len,
//                uint8_t** out, size_t* out_len);
//     in:  msgpack array of the call's positional args
//     0  -> *out = malloc'd msgpack-encoded result
//     !0 -> *out = malloc'd utf-8 error message
//   void ray_tpu_xlang_free(uint8_t* p);   // caller returns the buffer
//
// Results cross back in the language-agnostic msgpack object format
// (serialization.py format "x"), so non-Python drivers (the C++ client)
// can decode them without pickle.
//
// Build: g++ -O2 -std=c++17 -shared -fPIC -o libxlang_kernels.so cpp/xlang_kernels.cc

#include <cmath>
#include <cstdlib>
#include <cstring>

#include "msgpack_mini.h"

namespace {

uint8_t* dup(const std::string& s, size_t* out_len) {
  uint8_t* p = (uint8_t*)std::malloc(s.size());
  std::memcpy(p, s.data(), s.size());
  *out_len = s.size();
  return p;
}

int fail(const std::string& msg, uint8_t** out, size_t* out_len) {
  *out = dup(msg, out_len);
  return 1;
}

Value parse_args(const uint8_t* in, size_t in_len) {
  Unpacker up(in, in_len);  // decode straight from the caller's buffer
  Value v = up.decode();
  if (v.kind != Value::ARR) throw std::runtime_error("args must be a msgpack array");
  return v;
}

}  // namespace

extern "C" {

void ray_tpu_xlang_free(uint8_t* p) { std::free(p); }

// sum of a numeric array -> number. xlang_sum([[1, 2, 3.5]]) == 6.5
int xlang_sum(const uint8_t* in, size_t in_len, uint8_t** out, size_t* out_len) {
  try {
    Value args = parse_args(in, in_len);
    if (args.arr.size() != 1 || args.arr[0].kind != Value::ARR)
      return fail("xlang_sum expects one array argument", out, out_len);
    // Exact int64 accumulation while the input stays integral (a double
    // would silently round past 2^53); switch to double on the first float.
    int64_t itotal = 0;
    double ftotal = 0;
    bool all_int = true;
    for (const Value& v : args.arr[0].arr) {
      if (v.kind == Value::INT) {
        if (all_int && __builtin_add_overflow(itotal, v.i, &itotal))
          return fail("xlang_sum: int64 overflow", out, out_len);
        if (!all_int) ftotal += (double)v.i;
      } else if (v.kind == Value::FLOAT) {
        if (all_int) { ftotal = (double)itotal; all_int = false; }
        ftotal += v.f;
      } else {
        return fail("xlang_sum: non-numeric element", out, out_len);
      }
    }
    Packer pk;
    if (all_int) pk.integer(itotal);
    else { pk.u8(0xcb); pk.be64([](double d){ uint64_t u; std::memcpy(&u, &d, 8); return u; }(ftotal)); }
    *out = dup(pk.out, out_len);
    return 0;
  } catch (const std::exception& e) {
    return fail(e.what(), out, out_len);
  }
}

// scale a little-endian f32 buffer: [bin, scale] -> bin
int xlang_vector_scale(const uint8_t* in, size_t in_len, uint8_t** out, size_t* out_len) {
  try {
    Value args = parse_args(in, in_len);
    if (args.arr.size() != 2 || args.arr[0].kind != Value::BIN)
      return fail("xlang_vector_scale expects (bytes, scale)", out, out_len);
    const Value& s = args.arr[1];
    if (s.kind != Value::FLOAT && s.kind != Value::INT)
      return fail("xlang_vector_scale: scale must be numeric", out, out_len);
    double scale = s.kind == Value::FLOAT ? s.f : (double)s.i;
    std::string buf = std::move(args.arr[0].s);
    if (buf.size() % 4) return fail("buffer length not a multiple of 4", out, out_len);
    for (size_t i = 0; i < buf.size(); i += 4) {
      float f;
      std::memcpy(&f, buf.data() + i, 4);
      f = (float)(f * scale);
      std::memcpy(&buf[i], &f, 4);
    }
    Packer pk;
    pk.bin(buf);
    *out = dup(pk.out, out_len);
    return 0;
  } catch (const std::exception& e) {
    return fail(e.what(), out, out_len);
  }
}

// n float32s (value = index * 0.5) -> bin. A data PRODUCER for object-
// pipeline tests: its multi-MiB result exercises the plasma result path.
int xlang_make_floats(const uint8_t* in, size_t in_len, uint8_t** out, size_t* out_len) {
  try {
    Value args = parse_args(in, in_len);
    if (args.arr.size() != 1 || args.arr[0].kind != Value::INT)
      return fail("xlang_make_floats expects one int (count)", out, out_len);
    int64_t n = args.arr[0].i;
    if (n < 0 || n > (64LL << 20))
      return fail("xlang_make_floats: count out of range", out, out_len);
    std::string buf((size_t)n * 4, '\0');
    for (int64_t i = 0; i < n; ++i) {
      float f = (float)i * 0.5f;
      std::memcpy(&buf[(size_t)i * 4], &f, 4);
    }
    Packer pk;
    pk.bin(buf);
    *out = dup(pk.out, out_len);
    return 0;
  } catch (const std::exception& e) {
    return fail(e.what(), out, out_len);
  }
}

// word counts of a string -> {word: count}
int xlang_wordcount(const uint8_t* in, size_t in_len, uint8_t** out, size_t* out_len) {
  try {
    Value args = parse_args(in, in_len);
    if (args.arr.size() != 1 || args.arr[0].kind != Value::STR)
      return fail("xlang_wordcount expects one string", out, out_len);
    std::map<std::string, int64_t> counts;
    const std::string& text = args.arr[0].s;
    std::string word;
    for (char c : text) {
      if (c == ' ' || c == '\n' || c == '\t') {
        if (!word.empty()) { counts[word]++; word.clear(); }
      } else {
        word.push_back(c);
      }
    }
    if (!word.empty()) counts[word]++;
    Packer pk;
    pk.map_header((uint32_t)counts.size());
    for (const auto& kv : counts) { pk.str(kv.first); pk.integer(kv.second); }
    *out = dup(pk.out, out_len);
    return 0;
  } catch (const std::exception& e) {
    return fail(e.what(), out, out_len);
  }
}

}  // extern "C"
