// Shared native wire-protocol helpers for the ray_tpu C++ surfaces
// (worker runtime, driver API): length-prefixed msgpack framing, a small
// blocking RPC client with hostname resolution, the framework object codec
// (serialization.py wire format), and id helpers.
#pragma once

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdint>
#include <cstring>
#include <random>
#include <stdexcept>
#include <string>

#include "msgpack_mini.h"

namespace rtpu_wire {

inline void send_all(int fd, const std::string& buf) {
  size_t off = 0;
  while (off < buf.size()) {
    // MSG_NOSIGNAL: a peer that resets mid-write must surface as EPIPE
    // (caught by callers), not a process-killing SIGPIPE.
    ssize_t n = send(fd, buf.data() + off, buf.size() - off, MSG_NOSIGNAL);
    if (n <= 0) throw std::runtime_error("write failed");
    off += (size_t)n;
  }
}

inline bool read_exact(int fd, char* out, size_t n) {
  size_t off = 0;
  while (off < n) {
    ssize_t got = read(fd, out + off, n - off);
    if (got <= 0) return false;
    off += (size_t)got;
  }
  return true;
}

// [4-byte BE length][body]
inline std::string frame(const std::string& body) {
  std::string out;
  uint32_t len = htonl((uint32_t)body.size());
  out.append((const char*)&len, 4);
  out += body;
  return out;
}

// Blocking RPC client: requests are [0, seq, method, payload]; responses
// [1, seq, payload]; [2, ...] is an error; [3, ...] PUSH frames are skipped.
struct RpcClient {
  int fd = -1;
  uint32_t seq = 0;
  std::string host;
  int port = 0;

  RpcClient(const std::string& h, int p) : host(h), port(p) { connect_now(); }
  ~RpcClient() {
    if (fd >= 0) close(fd);
  }
  RpcClient(const RpcClient&) = delete;
  RpcClient& operator=(const RpcClient&) = delete;

  void connect_now() {
    fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) throw std::runtime_error("socket() failed");
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons((uint16_t)port);
    if (inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
      // Not a numeric IP — resolve (daemons may advertise a hostname).
      addrinfo hints{}, *res = nullptr;
      hints.ai_family = AF_INET;
      hints.ai_socktype = SOCK_STREAM;
      if (getaddrinfo(host.c_str(), nullptr, &hints, &res) != 0 || !res)
        throw std::runtime_error("cannot resolve host " + host);
      addr.sin_addr = ((sockaddr_in*)res->ai_addr)->sin_addr;
      freeaddrinfo(res);
    }
    if (connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0)
      throw std::runtime_error("connect to " + host + " failed");
  }

  Value call(const std::string& method, const std::string& payload_body) {
    Packer pk;
    pk.array_header(4);
    pk.integer(0);  // REQUEST
    pk.integer(++seq);
    pk.str(method);
    pk.out += payload_body;
    send_all(fd, frame(pk.out));
    for (;;) {
      char hdr[4];
      if (!read_exact(fd, hdr, 4)) throw std::runtime_error("rpc read failed");
      uint32_t blen = ntohl(*(const uint32_t*)hdr);
      std::string body(blen, '\0');
      if (!read_exact(fd, &body[0], blen)) throw std::runtime_error("rpc read failed");
      Unpacker up(body);
      Value msg = up.decode();
      int64_t mtype = msg.arr.at(0).i;
      if (mtype == 3) continue;  // PUSH frames (log fan-out) are not ours
      if ((uint32_t)msg.arr.at(1).i != seq) continue;
      if (mtype == 2) {
        const Value* detail = msg.arr.at(3).get("error");
        throw std::runtime_error("rpc error from " + method + ": " +
                                 (detail ? detail->s : std::string("?")));
      }
      return msg.arr.at(3);
    }
  }
};

// --------------------------------------------------------------------------
// Framework object codec: [4B BE hlen][msgpack {"p","b","f"}][64-pad][payload]
// (serialization.py wire format; "x" = cross-language msgpack object,
// "xe" = cross-language task error).
// --------------------------------------------------------------------------

static const uint64_t kAlign = 64;

inline std::string encode_x_object(const std::string& payload, const char* fmt) {
  Packer h;
  h.map_header(3);
  h.str("p"); h.integer((int64_t)payload.size());
  h.str("b"); h.array_header(0);
  h.str("f"); h.str(fmt);
  std::string out;
  uint32_t hlen = htonl((uint32_t)h.out.size());
  out.append((const char*)&hlen, 4);
  out += h.out;
  while (out.size() % kAlign) out.push_back('\0');
  out += payload;
  return out;
}

// Decode an inline framework object of the expected format ("x" or "xe").
inline bool decode_x_object(const std::string& blob, const char* want_fmt,
                            Value* out, std::string* err) {
  if (blob.size() < 4) { *err = "object too short"; return false; }
  const uint8_t* d = (const uint8_t*)blob.data();
  uint64_t hlen = ((uint64_t)d[0] << 24) | (d[1] << 16) | (d[2] << 8) | d[3];
  if (4 + hlen > blob.size()) { *err = "bad header length"; return false; }
  Unpacker hu(d + 4, (size_t)hlen);
  Value h = hu.decode();
  const Value* f = h.get("f");
  const Value* p = h.get("p");
  if (!f || f->s != want_fmt || !p) {
    *err = std::string("object is not format-\"") + want_fmt +
           "\" (cross-language msgpack)";
    return false;
  }
  uint64_t pos = (4 + hlen + kAlign - 1) & ~(kAlign - 1);
  if (pos + (uint64_t)p->i > blob.size()) { *err = "payload overruns object"; return false; }
  Unpacker pu(d + pos, (size_t)p->i);
  *out = pu.decode();
  return true;
}

inline std::string random_hex(size_t nbytes) {
  // Every byte drawn from the OS entropy source: a PRNG seeded from one
  // 32-bit random_device draw would give task/job IDs only 32 bits of
  // entropy — birthday collisions at ~90k submissions.
  static const char* digits = "0123456789abcdef";
  static thread_local std::random_device rd;
  std::string out;
  uint32_t pool = 0;
  int avail = 0;
  for (size_t i = 0; i < nbytes; ++i) {
    if (avail == 0) { pool = rd(); avail = 4; }
    uint8_t b = (uint8_t)(pool & 0xff);
    pool >>= 8;
    --avail;
    out.push_back(digits[b >> 4]);
    out.push_back(digits[b & 0x0f]);
  }
  return out;
}

}  // namespace rtpu_wire
