// ray_tpu C++ API example — a native driver submitting tasks to a running
// cluster and receiving owner-routed results (see ray_tpu_api.h).
//
// Build: g++ -O2 -std=c++17 -o api_example cpp/api_example.cc -lpthread
// Usage: api_example RAYLET_HOST RAYLET_PORT KERNELS_SO

#include <cstdio>

#include "ray_tpu_api.h"

int main(int argc, char** argv) {
  if (argc < 4) {
    fprintf(stderr, "usage: %s RAYLET_HOST RAYLET_PORT KERNELS_SO\n", argv[0]);
    return 2;
  }
  try {
    rtpu::Driver driver(argv[1], atoi(argv[2]));
    std::string lib = argv[3];

    // 1. Task(...).Remote(...) -> Get: the reference's C++ driver shape.
    auto sum = driver.Task("xlang_sum", lib);
    rtpu::ObjectRef r1 = sum.Remote(rtpu::List({rtpu::V(1), rtpu::V(2), rtpu::V(3)}));
    Value v1 = driver.Get(r1);
    printf("SUM %lld\n", (long long)v1.i);
    if (v1.i != 6) return 1;

    // 2. Concurrent submissions; results routed back as each completes.
    std::vector<rtpu::ObjectRef> refs;
    for (int i = 0; i < 5; ++i)
      refs.push_back(sum.Remote(rtpu::List({rtpu::V(i), rtpu::V(i)})));
    for (int i = 0; i < 5; ++i) {
      Value v = driver.Get(refs[i]);
      if (v.i != 2 * i) { fprintf(stderr, "bad result %d\n", i); return 1; }
    }
    printf("BATCH_OK\n");

    // 3. String-world round trip (map result).
    Value wc = driver.Get(driver.Task("xlang_wordcount", lib).Remote(rtpu::V("a b a")));
    const Value* a_count = wc.get("a");
    if (!a_count || a_count->i != 2) return 1;
    printf("WORDCOUNT_OK %s\n", value_repr(wc).c_str());

    // 4. Task errors throw typed exceptions.
    try {
      driver.Get(driver.Task("xlang_sum", lib).Remote(rtpu::V("not-an-array")));
      fprintf(stderr, "error did not throw\n");
      return 1;
    } catch (const rtpu::TaskFailed& e) {
      printf("ERROR_OK %s\n", e.what());
    }

    // 5. Object pipeline (native data path): one task PRODUCES 8 MiB
    //    (stored in the node's plasma arena, reported as a ["plasma"]
    //    result), the next consumes it BY REF (the C++ worker reads it
    //    zero-copy through the shm index), and the final plasma-sized
    //    result streams back to this driver over the wire (store_get +
    //    chunk fetches — the driver itself stays shm-free).
    const int64_t N = 2 * 1024 * 1024;  // floats -> 8 MiB
    rtpu::ObjectRef big = driver.Task("xlang_make_floats", lib).Remote(rtpu::V(N));
    rtpu::ObjectRef scaled =
        driver.Task("xlang_vector_scale", lib).Remote(big, rtpu::V(3.0));
    Value v5 = driver.Get(scaled, 120000);
    if (v5.kind != Value::BIN || v5.s.size() != (size_t)N * 4) {
      fprintf(stderr, "pipeline: bad result (%zu bytes)\n", v5.s.size());
      return 1;
    }
    for (int64_t i : {int64_t(0), int64_t(12345), N - 1}) {
      float f;
      std::memcpy(&f, v5.s.data() + (size_t)i * 4, 4);
      if (f != (float)i * 0.5f * 3.0f) {
        fprintf(stderr, "pipeline: wrong value at %lld: %f\n", (long long)i, f);
        return 1;
      }
    }
    printf("PIPELINE_OK %zu bytes\n", v5.s.size());

    // 6. A ref arg whose PRODUCER FAILED: the consumer must fail fast with
    //    the producer's reason (the driver's owner server answers
    //    get_inline with kind="failed"), not stall out a polling budget.
    rtpu::ObjectRef bad = driver.Task("xlang_sum", lib).Remote(rtpu::V("boom"));
    try { driver.Get(bad); } catch (const rtpu::TaskFailed&) {}
    rtpu::ObjectRef chained =
        driver.Task("xlang_vector_scale", lib).Remote(bad, rtpu::V(2.0));
    try {
      driver.Get(chained, 30000);
      fprintf(stderr, "chained-on-failed did not throw\n");
      return 1;
    } catch (const rtpu::TaskFailed& e) {
      if (std::string(e.what()).find("failed") == std::string::npos) {
        fprintf(stderr, "chained-on-failed: unhelpful error: %s\n", e.what());
        return 1;
      }
      printf("FAILED_REF_OK %s\n", e.what());
    }

    printf("CPP_API_PASS\n");
    return 0;
  } catch (const std::exception& e) {
    fprintf(stderr, "CPP_API_FAIL: %s\n", e.what());
    return 1;
  }
}
