// ray_tpu C++ driver API — the user-facing native surface (N22).
//
// The reference's C++ API (cpp/include/ray/api.h) lets a C++ program be a
// first-class driver: `ray::Task(f).Remote(args)` then `ray::Get(ref)`.
// This header is the framework's analog over the real wire protocol:
//
//   rtpu::Driver driver(raylet_host, raylet_port);
//   auto ref = driver.Task("xlang_sum", "/path/libkernels.so")
//                    .Remote(rtpu::List({rtpu::V(1), rtpu::V(2)}));
//   Value out = driver.Get(ref);             // msgpack value, throws on error
//
// The Driver is a true OWNER, not a KV-polling spectator: it runs a small
// owner-side RPC server (a thread), stamps its own address as owner_addr on
// submitted specs, and workers — the native C++ worker runtime
// (ray_tpu_worker.cc) for language="cpp" specs, or Python workers on
// fallback — push `task_done` payloads straight back to it, exactly the
// reference's direct-call result path (owner-routed results, no
// polling). Results are format-"x" msgpack objects; task failures arrive
// as format-"xe" errors (or Python-pickle errors from fallback workers)
// and throw rtpu::TaskFailed from Get.
//
// Header-only; depends on msgpack_mini.h + ray_tpu_wire.h. Linux sockets.

#pragma once

#include <poll.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <stdexcept>
#include <string>
#include <thread>
#include <vector>

#include "ray_tpu_wire.h"

namespace rtpu {

using rtpu_wire::RpcClient;

struct TaskFailed : std::runtime_error {
  explicit TaskFailed(const std::string& m) : std::runtime_error(m) {}
};
struct GetTimeout : std::runtime_error {
  explicit GetTimeout(const std::string& m) : std::runtime_error(m) {}
};
// A repeated Get of a result the owner cache already evicted (count or byte
// bound). Distinct from GetTimeout: the result is definitively gone — the
// caller learns instantly instead of burning its full timeout budget.
struct ResultEvicted : std::runtime_error {
  explicit ResultEvicted(const std::string& m) : std::runtime_error(m) {}
};

// -- Value construction sugar ------------------------------------------------

inline Value V(int64_t v) { Value x; x.kind = Value::INT; x.i = v; return x; }
inline Value V(int v) { return V((int64_t)v); }
inline Value V(double v) { Value x; x.kind = Value::FLOAT; x.f = v; return x; }
inline Value V(bool v) { Value x; x.kind = Value::BOOL; x.b = v; return x; }
inline Value V(const std::string& v) { Value x; x.kind = Value::STR; x.s = v; return x; }
inline Value V(const char* v) { return V(std::string(v)); }
inline Value Bin(const std::string& v) { Value x; x.kind = Value::BIN; x.s = v; return x; }
inline Value List(std::vector<Value> items) {
  Value x;
  x.kind = Value::ARR;
  x.arr = std::move(items);
  return x;
}

struct ObjectRef {
  std::string task_id;  // 48-hex; the return object is task_id + "00000000"
  std::string object_id() const { return task_id + "00000000"; }
};

// One task argument: a plain msgpack value, or a previous task's ObjectRef
// (ships as an ["r", oid, owner] entry; the worker fetches it natively).
struct Arg {
  Value value;
  std::string ref_oid;  // non-empty => ObjectRef arg
  Arg(Value v) : value(std::move(v)) {}          // NOLINT(runtime/explicit)
  Arg(const ObjectRef& r) : ref_oid(r.object_id()) {}  // NOLINT
};

class Driver;

// `driver.Task(symbol, library).Remote(v...)` — the reference's
// `ray::Task(fn).Remote(...)` shape for C-ABI kernel functions. Args are
// msgpack Values or ObjectRefs of earlier tasks.
class TaskHandle {
 public:
  TaskHandle(Driver* d, std::string symbol, std::string library)
      : d_(d), symbol_(std::move(symbol)), library_(std::move(library)) {}

  template <typename... A>
  ObjectRef Remote(A&&... a);

 private:
  Driver* d_;
  std::string symbol_, library_;
};

class Driver {
 public:
  // Connects to a running cluster's raylet. The driver advertises
  // `owner_host` (defaults to the raylet's host — correct whenever driver
  // and raylet share a machine or routable hostname).
  Driver(const std::string& raylet_host, int raylet_port,
         const std::string& owner_host = "")
      : raylet_(new RpcClient(raylet_host, raylet_port)),
        owner_host_(owner_host.empty() ? raylet_host : owner_host) {
    start_owner_server();
    job_id_ = rtpu_wire::random_hex(4);
  }

  ~Driver() {
    stopping_ = true;
    if (wake_fd_ >= 0) {
      char b = 'x';
      (void)!write(wake_fd_, &b, 1);
    }
    if (server_.joinable()) server_.join();
    if (listen_fd_ >= 0) close(listen_fd_);
    if (wake_fd_ >= 0) close(wake_fd_);
    if (wake_rd_ >= 0) close(wake_rd_);
  }

  TaskHandle Task(const std::string& symbol, const std::string& library) {
    return TaskHandle(this, symbol, library);
  }

  // Submit a cross-language task; args are msgpack Values (see V/Bin/List)
  // or ObjectRefs of this driver's earlier tasks.
  ObjectRef Submit(const std::string& library, const std::string& symbol,
                   const std::vector<Arg>& args) {
    std::string task_id = rtpu_wire::random_hex(24);
    Packer p;
    p.map_header(1);
    p.str("spec");
    p.map_header(8);
    p.str("task_id"); p.str(task_id);
    p.str("job_id"); p.str(job_id_);
    p.str("name"); p.str("cpp:" + symbol);
    p.str("function_key"); p.str("cpp!" + library + "!" + symbol);
    p.str("language"); p.str("cpp");
    p.str("args");
    p.array_header((uint32_t)args.size());
    for (const Arg& a : args) {
      if (!a.ref_oid.empty()) {
        // ["r", oid, [host, port]] — this driver is the owner.
        p.array_header(3);
        p.str("r");
        p.str(a.ref_oid);
        p.array_header(2);
        p.str(owner_host_);
        p.integer(owner_port_);
        continue;
      }
      Packer ap;
      pack_value(ap, a.value);
      p.array_header(2);
      p.str("v");
      p.bin(rtpu_wire::encode_x_object(ap.out, "x"));
    }
    p.str("owner_addr");
    p.array_header(2);
    p.str(owner_host_);
    p.integer(owner_port_);
    p.str("resources");
    p.map_header(1);
    p.str("CPU"); p.integer(1);
    std::lock_guard<std::mutex> lk(raylet_mu_);
    Value r = raylet_->call("submit_task", p.out);
    const Value* ok = r.get("ok");
    if (ok && !ok->truthy()) throw std::runtime_error("submit_task rejected");
    return ObjectRef{task_id};
  }

  // Block until the task's result arrives at this owner; decode and return
  // the msgpack value. Throws TaskFailed on task error, GetTimeout on
  // timeout.
  Value Get(const ObjectRef& ref, int timeout_ms = 60000) {
    std::unique_lock<std::mutex> lk(mu_);
    if (!cv_.wait_for(lk, std::chrono::milliseconds(timeout_ms), [&] {
          return done_.count(ref.task_id) > 0 || failed_.count(ref.task_id) > 0 ||
                 evicted_.count(ref.task_id) > 0;
        })) {
      // Distinguish "never arrived" from "arrived and was evicted" even when
      // eviction happened while we waited.
      if (evicted_.count(ref.task_id) > 0)
        throw ResultEvicted("result for task " + ref.task_id.substr(0, 8) +
                            " evicted from owner cache");
      throw GetTimeout("no result for task " + ref.task_id.substr(0, 8));
    }
    if (done_.count(ref.task_id) == 0 && failed_.count(ref.task_id) == 0)
      throw ResultEvicted("result for task " + ref.task_id.substr(0, 8) +
                          " evicted from owner cache");
    // Mark consumed (either outcome): consumed entries are preferred for
    // eviction once the cache bound is hit.
    if (consumed_.insert(ref.task_id).second)
      consumed_order_.push_back(ref.task_id);
    // done_ wins over failed_: a worker can deliver the result and THEN
    // crash before telling the raylet — the late task_failed must not turn
    // an already-delivered success into an error on a repeated Get.
    if (done_.count(ref.task_id) == 0) {
      std::string why = failed_[ref.task_id];
      lk.unlock();
      throw TaskFailed(why);  // raylet-reported worker death (task_failed)
    }
    // Results stay cached so Get is repeatable (ray.get semantics) — up to
    // the kMaxDone entry bound AND the kMaxDoneBytes aggregate byte budget:
    // past either, already-consumed entries are evicted first (then oldest
    // unconsumed) and a repeated Get of an evicted ref throws ResultEvicted
    // immediately (the id is remembered). Abandoned refs cannot grow the
    // owner without bound either way.
    Value payload = done_[ref.task_id];
    lk.unlock();

    const Value* err = payload.get("error");
    if (err && err->kind == Value::BIN) {
      Value einfo;
      std::string derr;
      if (rtpu_wire::decode_x_object(err->s, "xe", &einfo, &derr)) {
        const Value* msg = einfo.get("message");
        throw TaskFailed(msg ? msg->s : "task failed");
      }
      throw TaskFailed("task failed (non-native error payload)");
    }
    const Value* results = payload.get("results");
    if (!results || results->arr.empty())
      throw TaskFailed("task completed with no results");
    const Value& entry = results->arr[0];
    if (entry.arr.size() < 3)
      throw TaskFailed("malformed result entry");
    std::string wire;
    if (entry.arr[1].s == "inline") {
      wire = entry.arr[2].s;
    } else if (entry.arr[1].s == "plasma") {
      // Plasma-sized result: ride the wire through our raylet — store_get
      // pulls it local (if produced elsewhere) and pins it; chunk reads
      // assemble the serialized object; release drops the pin. (Workers
      // read the arena zero-copy; the driver stays shm-free and portable.)
      wire = FetchPlasma(entry.arr[0].s);
      if (wire.size() <= kPlasmaCacheMax) {
        // Repeated Gets should behave like the inline path: rewrite the
        // cached entry in place. Bounded per entry — the kMaxDone FIFO
        // caps count, this caps bytes; larger objects refetch.
        std::lock_guard<std::mutex> lk(mu_);
        auto it = done_.find(ref.task_id);
        if (it != done_.end()) {
          Value* results_mut = nullptr;
          auto rit = it->second.map.find("results");
          if (rit != it->second.map.end()) results_mut = &rit->second;
          if (results_mut) {
            for (Value& e : results_mut->arr) {
              if (e.arr.size() >= 3 && e.arr[0].s == entry.arr[0].s) {
                e.arr[1].s = "inline";
                e.arr[2].kind = Value::BIN;
                e.arr[2].s = wire;
                // The rewrite grew the cached entry: re-charge it against
                // the byte budget (may evict OTHER entries; this one was
                // just touched and `wire` is already copied out).
                done_bytes_ += wire.size();
                cached_bytes_[ref.task_id] += wire.size();
                enforce_bound_locked();
                break;
              }
            }
          }
        }
      }
    } else {
      throw TaskFailed("unknown result location '" + entry.arr[1].s + "'");
    }
    Value out;
    std::string derr;
    if (!rtpu_wire::decode_x_object(wire, "x", &out, &derr))
      throw TaskFailed("result decode failed: " + derr);
    return out;
  }

  std::string FetchPlasma(const std::string& oid) {
    std::lock_guard<std::mutex> lk(raylet_mu_);
    Packer g;
    g.map_header(2);
    g.str("object_id"); g.str(oid);
    g.str("timeout"); g.floating(60.0);
    Value got = raylet_->call("store_get", g.out);
    const Value* sz = got.get("size");
    if (!sz) throw TaskFailed("store_get returned no size for " + oid.substr(0, 12));
    std::string wire;
    wire.reserve((size_t)sz->i);
    const int64_t kChunk = 4 * 1024 * 1024;
    for (int64_t pos = 0; pos < sz->i;) {
      Packer c;
      c.map_header(3);
      c.str("object_id"); c.str(oid);
      c.str("start"); c.integer(pos);
      c.str("length"); c.integer(kChunk);
      Value chunk = raylet_->call("fetch_object_chunk", c.out);
      const Value* data = chunk.get("data");
      if (!data || data->s.empty())
        throw TaskFailed("fetch_object_chunk starved at " + std::to_string(pos));
      wire += data->s;
      pos += (int64_t)data->s.size();
    }
    Packer r;
    r.map_header(1);
    r.str("object_id"); r.str(oid);
    try { raylet_->call("store_release", r.out); } catch (...) {}
    return wire;
  }

 private:
  // Owner-side server: accepts connections from workers and records
  // task_done payloads (the reference's owner-routed result path).
  void start_owner_server() {
    listen_fd_ = socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = 0;
    if (bind(listen_fd_, (sockaddr*)&addr, sizeof(addr)) != 0 ||
        listen(listen_fd_, 16) != 0)
      throw std::runtime_error("owner server listen failed");
    socklen_t alen = sizeof(addr);
    getsockname(listen_fd_, (sockaddr*)&addr, &alen);
    owner_port_ = ntohs(addr.sin_port);
    int pipefd[2];
    if (pipe(pipefd) != 0) throw std::runtime_error("pipe failed");
    wake_rd_ = pipefd[0];
    wake_fd_ = pipefd[1];
    server_ = std::thread([this] { serve(); });
  }

  void serve() {
    std::vector<int> conns;
    std::map<int, std::string> bufs;
    while (!stopping_) {
      std::vector<pollfd> fds;
      fds.push_back({listen_fd_, POLLIN, 0});
      fds.push_back({wake_rd_, POLLIN, 0});
      for (int fd : conns) fds.push_back({fd, POLLIN, 0});
      if (poll(fds.data(), fds.size(), 1000) < 0) {
        if (errno == EINTR) continue;  // a stray signal must not kill Get()
        break;
      }
      if (stopping_) break;
      if (fds[0].revents & POLLIN) {
        int c = accept(listen_fd_, nullptr, nullptr);
        if (c >= 0) { conns.push_back(c); bufs[c] = ""; }
      }
      for (size_t i = 2; i < fds.size(); ++i) {
        if (!(fds[i].revents & (POLLIN | POLLHUP | POLLERR))) continue;
        int fd = fds[i].fd;
        char chunk[65536];
        ssize_t n = read(fd, chunk, sizeof chunk);
        if (n <= 0) {
          close(fd);
          conns.erase(std::find(conns.begin(), conns.end(), fd));
          bufs.erase(fd);
          continue;
        }
        std::string& buf = bufs[fd];
        buf.append(chunk, (size_t)n);
        while (buf.size() >= 4) {
          uint32_t blen = ntohl(*(const uint32_t*)buf.data());
          if (buf.size() < 4 + (size_t)blen) break;
          std::string body = buf.substr(4, blen);
          buf.erase(0, 4 + blen);
          try {
            handle_frame(fd, body);
          } catch (const std::exception&) {
            // Malformed frame: drop it, keep the connection.
          }
        }
      }
    }
  }

  void handle_frame(int fd, const std::string& body) {
    Unpacker up(body);
    Value msg = up.decode();
    int64_t seq = msg.arr.at(1).i;
    const std::string& method = msg.arr.at(2).s;
    Packer resp;
    resp.array_header(4);
    resp.integer(1);  // RESPONSE
    resp.integer(seq);
    resp.str(method);
    if (method == "get_inline") {
      // Serve an owned result to a borrower (the native worker fetching a
      // ref arg of a follow-up task). Non-blocking: the serve thread also
      // processes task_done, so it must never wait on one — a not-yet-done
      // producer answers "missing" and the worker polls.
      const Value* oid = msg.arr.at(3).get("object_id");
      std::string kind = "missing", data, location;
      if (oid && oid->s.size() > 8) {
        const std::string task_id = oid->s.substr(0, oid->s.size() - 8);
        std::lock_guard<std::mutex> lk(mu_);
        auto it = done_.find(task_id);
        if (it != done_.end()) {
          const Value* results = it->second.get("results");
          if (results) {
            for (const Value& entry : results->arr) {
              if (entry.arr.size() >= 3 && entry.arr[0].s == oid->s) {
                if (entry.arr[1].s == "inline") {
                  kind = "inline";
                  data = entry.arr[2].s;
                } else if (entry.arr[1].s == "plasma") {
                  kind = "plasma";
                  location = entry.arr[2].s;
                }
                break;
              }
            }
          }
          if (kind == "missing") {
            // The task completed WITH AN ERROR (results empty, "error"
            // payload): the borrower must see the failure, not poll.
            const Value* errv = it->second.get("error");
            if (errv && errv->kind == Value::BIN) {
              kind = "failed";
              Value einfo;
              std::string derr;
              if (rtpu_wire::decode_x_object(errv->s, "xe", &einfo, &derr)) {
                const Value* m = einfo.get("message");
                data = m ? m->s : "task failed";
              } else {
                data = "task failed";
              }
            }
          }
        } else {
          // A FAILED producer must answer with its failure, not "missing" —
          // a borrower polling for a result that will never exist would
          // stall its full budget and then mislabel the error.
          auto fit = failed_.find(task_id);
          if (fit != failed_.end()) {
            kind = "failed";
            data = fit->second;  // reason rides in "message"
          } else if (evicted_.count(task_id) > 0) {
            // An evicted result will never reappear: tell the borrower now
            // instead of letting it poll out its full budget.
            kind = "failed";
            data = "result evicted from owner cache";
          }
        }
      }
      resp.map_header(kind == "missing" ? 1 : 2);
      resp.str("kind"); resp.str(kind);
      if (kind == "inline") { resp.str("data"); resp.bin(data); }
      else if (kind == "plasma") { resp.str("location"); resp.str(location); }
      else if (kind == "failed") { resp.str("message"); resp.str(data); }
      rtpu_wire::send_all(fd, rtpu_wire::frame(resp.out));
      return;
    }
    resp.map_header(1);
    resp.str("ok");
    resp.boolean(true);
    rtpu_wire::send_all(fd, rtpu_wire::frame(resp.out));
    if (method == "task_done") {
      const Value& payload = msg.arr.at(3);
      const Value* tid = payload.get("task_id");
      if (tid) {
        std::lock_guard<std::mutex> lk(mu_);
        if (done_.emplace(tid->s, payload).second) {
          done_order_.push_back(tid->s);
          const size_t sz = payload_bytes(payload);
          // += not =: a failed-then-done sequence (worker crash raced the
          // delivery) has both maps populated for this id; one eviction
          // erases both, so the charge must cover both or done_bytes_
          // drifts upward permanently.
          cached_bytes_[tid->s] += sz;
          done_bytes_ += sz;
          evicted_.erase(tid->s);  // a re-delivered result is cached again
          enforce_bound_locked();
        }
      }
      cv_.notify_all();
    } else if (method == "task_failed") {
      // The raylet reports worker death (crash/OOM) to the owner; surface
      // it from Get immediately with the reason instead of a blind
      // GetTimeout 60s later.
      const Value& payload = msg.arr.at(3);
      const Value* tid = payload.get("task_id");
      if (tid) {
        const Value* etype = payload.get("error");
        const Value* emsg = payload.get("message");
        std::lock_guard<std::mutex> lk(mu_);
        // Shares done_'s FIFO bound (failures of abandoned refs must not
        // grow the owner forever), and never shadows a delivered result.
        if (done_.count(tid->s) == 0 &&
            failed_.emplace(tid->s,
                            (etype ? etype->s : std::string("TaskFailed")) +
                                (emsg ? ": " + emsg->s : std::string()))
                .second) {
          done_order_.push_back(tid->s);
          const size_t sz = failed_[tid->s].size();
          cached_bytes_[tid->s] += sz;  // see task_done: one eviction, one charge
          done_bytes_ += sz;
          enforce_bound_locked();
        }
      }
      cv_.notify_all();
    }  // other owner RPCs (ping, location queries) are ok-acked above
  }

  // Evict one cached result, preferring entries the caller has already
  // consumed via Get (oldest consumed first); only when every cached entry
  // is still unconsumed does the oldest unconsumed go (>kMaxDone refs
  // outstanding — abandoned refs must not grow the owner without bound).
  // Both deques may hold ids already evicted via the other path; those are
  // skipped lazily, which keeps eviction O(1) amortized — the bound check
  // must therefore count the maps, not done_order_.
  void evict_one_locked() {
    while (!consumed_order_.empty()) {
      const std::string id = consumed_order_.front();
      consumed_order_.pop_front();
      consumed_.erase(id);
      if (done_.erase(id) + failed_.erase(id) > 0) {
        drop_accounting_locked(id);
        return;
      }
    }
    while (!done_order_.empty()) {
      const std::string id = done_order_.front();
      done_order_.pop_front();
      if (done_.erase(id) + failed_.erase(id) > 0) {
        drop_accounting_locked(id);
        return;
      }
    }
  }

  // Shared post-eviction bookkeeping: release the entry's bytes and remember
  // the id so a later Get fails fast with ResultEvicted instead of waiting
  // out its full timeout as GetTimeout.
  void drop_accounting_locked(const std::string& id) {
    auto bit = cached_bytes_.find(id);
    if (bit != cached_bytes_.end()) {
      done_bytes_ -= std::min(done_bytes_, bit->second);
      cached_bytes_.erase(bit);
    }
    if (evicted_.insert(id).second) evicted_order_.push_back(id);
    while (evicted_order_.size() > 2 * kMaxDone) {
      evicted_.erase(evicted_order_.front());
      evicted_order_.pop_front();
    }
  }

  size_t cached_locked() const { return done_.size() + failed_.size(); }

  // Sum of a task_done payload's data bytes (inline result blobs, plasma
  // location strings, error blobs) — what the byte budget charges.
  static size_t payload_bytes(const Value& payload) {
    size_t sz = 0;
    auto rit = payload.map.find("results");
    if (rit != payload.map.end()) {
      for (const Value& e : rit->second.arr)
        if (e.arr.size() >= 3) sz += e.arr[0].s.size() + e.arr[2].s.size();
    }
    auto eit = payload.map.find("error");
    if (eit != payload.map.end()) sz += eit->second.s.size();
    return sz;
  }

  // Bound the cache AND the order deques. Lazy skipping leaves stale ids in
  // the deques (an id evicted via the other deque); in the every-result-
  // consumed workload the fallback loop never runs, so without a hard cap
  // done_order_ would leak one id per task forever. Past 2x the cache bound,
  // force-FIFO-evict (the pre-consumed-tracking behavior).
  void enforce_bound_locked() {
    while (cached_locked() > kMaxDone) evict_one_locked();
    // Aggregate byte budget (ADVICE r5 #1): the per-entry 16 MiB rewrite cap
    // and the 4096-entry bound still admit ~64 GiB resident in a pathological
    // workload; cap total bytes too. Keep at least one entry so the result
    // just delivered (or just rewritten) survives its own insertion.
    while (done_bytes_ > kMaxDoneBytes && cached_locked() > 1) evict_one_locked();
    while (done_order_.size() > 2 * kMaxDone) {
      const std::string id = done_order_.front();
      done_order_.pop_front();
      if (done_.erase(id) + failed_.erase(id) > 0) drop_accounting_locked(id);
    }
    while (consumed_order_.size() > 2 * kMaxDone) {
      consumed_.erase(consumed_order_.front());
      consumed_order_.pop_front();
    }
  }

  std::unique_ptr<RpcClient> raylet_;
  std::mutex raylet_mu_;
  std::string owner_host_;
  std::string job_id_;
  int owner_port_ = 0;
  int listen_fd_ = -1;
  int wake_fd_ = -1;
  int wake_rd_ = -1;
  std::thread server_;
  std::mutex mu_;
  std::condition_variable cv_;
  static const size_t kMaxDone = 4096;
  static const size_t kPlasmaCacheMax = 16 * 1024 * 1024;
  static const size_t kMaxDoneBytes = 256 * 1024 * 1024;
  std::map<std::string, Value> done_;
  std::map<std::string, std::string> failed_;
  std::deque<std::string> done_order_;
  std::set<std::string> consumed_;
  std::deque<std::string> consumed_order_;
  std::map<std::string, size_t> cached_bytes_;  // id -> charged bytes
  size_t done_bytes_ = 0;
  std::set<std::string> evicted_;  // ids dropped from the cache (fast-fail)
  std::deque<std::string> evicted_order_;
  std::atomic<bool> stopping_{false};
};

template <typename... A>
ObjectRef TaskHandle::Remote(A&&... a) {
  std::vector<Arg> args{Arg(std::forward<A>(a))...};
  return d_->Submit(library_, symbol_, args);
}

}  // namespace rtpu
