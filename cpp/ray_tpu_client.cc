// ray_tpu C++ client — minimal native driver for an existing cluster.
//
// The down-payment on the reference's C++ user API (cpp/include/ray/api.h):
// a standalone program that speaks the framework's control plane (the
// length-prefixed msgpack RPC of _private/rpc.py) and data plane (the shm
// arena + lock-free index C APIs in _native/) with NO Python in process:
//
//   1. GCS KV put/get round trip            (control plane)
//   2. node-table listing                   (cluster introspection)
//   3. task submission to a raylet by
//      function-table key + result poll     (task plane)
//   4. zero-copy shared-memory object read
//      via arena_attach + idx_get_pinned    (data plane)
//
// Build:  g++ -O2 -std=c++17 -o ray_tpu_cclient cpp/ray_tpu_client.cc -ldl
// Usage:  ray_tpu_cclient GCS_HOST GCS_PORT RAYLET_HOST RAYLET_PORT \
//             FUNCTION_KEY JOB_ID [NATIVE_DIR ARENA_NAME INDEX_NAME OID_HEX]

#include <arpa/inet.h>
#include <dlfcn.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <memory>
#include <random>
#include <stdexcept>
#include <string>
#include <vector>


#include "ray_tpu_wire.h"

using rtpu_wire::RpcClient;
using rtpu_wire::random_hex;

static std::string from_hex(const std::string& hex) {
  std::string out;
  for (size_t i = 0; i + 1 < hex.size(); i += 2)
    out.push_back((char)strtol(hex.substr(i, 2).c_str(), nullptr, 16));
  return out;
}

int main(int argc, char** argv) {
  if (argc < 7) {
    fprintf(stderr,
            "usage: %s GCS_HOST GCS_PORT RAYLET_HOST RAYLET_PORT "
            "FUNCTION_KEY JOB_ID [NATIVE_DIR ARENA_NAME INDEX_NAME OID_HEX]\n",
            argv[0]);
    return 2;
  }
  try {
    RpcClient gcs(argv[1], atoi(argv[2]));

    // 1. Control plane: KV round trip.
    {
      Packer p;
      p.map_header(3);
      p.str("key"); p.str("cclient:ping");
      p.str("value"); p.bin("hello-from-c");
      p.str("overwrite"); p.boolean(true);
      Value r = gcs.call("kv_put", p.out);
      Packer g;
      g.map_header(1);
      g.str("key"); g.str("cclient:ping");
      Value got = gcs.call("kv_get", g.out);
      const Value* val = got.get("value");
      if (!(r.get("ok") && r.get("ok")->truthy() && val && val->s == "hello-from-c"))
        throw std::runtime_error("KV round trip mismatch");
      printf("KV_OK\n");
    }

    // 2. Cluster introspection: node table.
    {
      Packer p;
      p.map_header(0);
      Value r = gcs.call("get_nodes", p.out);
      const Value* nodes = r.get("nodes");
      printf("NODES %zu\n", nodes ? nodes->map.size() : 0);
    }

    // 3. Task plane: submit a no-arg task by function key; the task writes
    //    its result into the GCS KV, which we poll (a C driver has no
    //    in-process object store to receive owner pushes).
    {
      std::string task_id = random_hex(24);
      RpcClient raylet(argv[3], atoi(argv[4]));
      Packer p;
      p.map_header(1);
      p.str("spec");
      p.map_header(5);
      p.str("task_id"); p.str(task_id);
      p.str("job_id"); p.str(argv[6]);
      p.str("name"); p.str("c_client_task");
      p.str("function_key"); p.str(argv[5]);
      p.str("num_returns"); p.integer(0);
      Value r = raylet.call("submit_task", p.out);
      if (!(r.get("ok") && r.get("ok")->truthy()))
        throw std::runtime_error("submit_task rejected");
      printf("TASK_SUBMITTED %s\n", task_id.c_str());
      // Poll a TASK-ID-namespaced key (the task echoes its own id into the
      // key): a stale value from a previous run cannot satisfy this poll.
      std::string result_key = "cclient:result:" + task_id;
      std::string result;
      for (int attempt = 0; attempt < 300; ++attempt) {
        Packer g;
        g.map_header(1);
        g.str("key"); g.str(result_key);
        Value got = gcs.call("kv_get", g.out);
        if (got.get("found") && got.get("found")->truthy()) {
          result = got.get("value")->s;
          break;
        }
        usleep(100 * 1000);
      }
      if (result.empty()) throw std::runtime_error("task result never appeared");
      printf("TASK_RESULT %s\n", result.c_str());
    }

    // 4. Data plane: zero-copy read of a shared-memory object through the
    //    same C APIs the Python runtime binds (arena_attach/idx_get_pinned).
    if (argc >= 11) {
      std::string dir = argv[7];
      void* arena_lib = dlopen((dir + "/libshm_arena.so").c_str(), RTLD_NOW);
      void* index_lib = dlopen((dir + "/libshm_index.so").c_str(), RTLD_NOW);
      if (!arena_lib || !index_lib)
        throw std::runtime_error("dlopen native libs failed");
      auto arena_attach = (int (*)(const char*))dlsym(arena_lib, "arena_attach");
      auto arena_base = (void* (*)(int))dlsym(arena_lib, "arena_base");
      auto idx_attach = (int (*)(const char*))dlsym(index_lib, "idx_attach");
      auto idx_get_pinned =
          (int (*)(int, const uint8_t*, uint64_t*, uint64_t*, uint32_t*, uint64_t*))
              dlsym(index_lib, "idx_get_pinned");
      auto idx_release = (int (*)(int, uint64_t, uint32_t))dlsym(index_lib, "idx_release");
      if (!arena_attach || !arena_base || !idx_attach || !idx_get_pinned || !idx_release)
        throw std::runtime_error("dlsym native symbols failed");

      int ah = arena_attach(argv[8]);
      int ih = idx_attach(argv[9]);
      if (ah < 0 || ih < 0) throw std::runtime_error("shm attach failed");
      std::string key = from_hex(argv[10]);
      uint64_t off = 0, size = 0, slot = 0;
      uint32_t ver = 0;
      if (!idx_get_pinned(ih, (const uint8_t*)key.data(), &off, &size, &ver, &slot))
        throw std::runtime_error("object not found in shm index");
      const uint8_t* data = (const uint8_t*)arena_base(ah) + off;
      uint64_t checksum = 1469598103934665603ULL;  // FNV-1a over the payload
      for (uint64_t i = 0; i < size; ++i) {
        checksum ^= data[i];
        checksum *= 1099511628211ULL;
      }
      // Cross-language objects (serialization.py format "x") decode right
      // here — no Python, no pickle: [4B BE header_len][msgpack header
      // {p,b,f}][64-aligned msgpack payload].
      if (size >= 4) {
        uint64_t hlen = ((uint64_t)data[0] << 24) | (data[1] << 16) |
                        (data[2] << 8) | data[3];
        if (4 + hlen <= size) {
          try {
            Unpacker hu(data + 4, (size_t)hlen);
            Value h = hu.decode();
            const Value* f = h.get("f");
            const Value* p = h.get("p");
            if (f && f->s == "x" && p) {
              uint64_t pos = (4 + hlen + 63) & ~63ULL;  // _ALIGN = 64
              if (pos + (uint64_t)p->i <= size) {
                Unpacker pu(data + pos, (size_t)p->i);
                printf("XLANG_RESULT %s\n", value_repr(pu.decode()).c_str());
              }
            }
          } catch (const std::exception&) {
            // Not a decodable framework object — raw reads stay valid.
          }
        }
      }
      idx_release(ih, slot, ver);
      printf("SHM_READ %llu %016llx\n", (unsigned long long)size,
             (unsigned long long)checksum);
    }
    printf("C_CLIENT_PASS\n");
    return 0;
  } catch (const std::exception& e) {
    fprintf(stderr, "C_CLIENT_FAIL: %s\n", e.what());
    return 1;
  }
}
