// Minimal msgpack for the ray_tpu native tools (client driver, xlang
// kernels): encoder (maps/arrays/str/bin/uint/int/bool/nil), value type,
// and decoder. String-keyed maps only — the framework's wire shape.
//
// LIFETIME: Unpacker stores raw pointers into the buffer it is constructed
// with — the buffer must outlive the Unpacker (never pass a temporary).
#pragma once

#include <arpa/inet.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <stdexcept>
#include <string>
#include <vector>

// ---------------------------------------------------------------------------
// Minimal msgpack encoder (maps/arrays/str/bin/uint/int/bool/nil).
// ---------------------------------------------------------------------------
struct Packer {
  std::string out;
  void raw(const void* p, size_t n) { out.append((const char*)p, n); }
  void u8(uint8_t b) { out.push_back((char)b); }
  void be16(uint16_t v) { uint16_t x = htons(v); raw(&x, 2); }
  void be32(uint32_t v) { uint32_t x = htonl(v); raw(&x, 4); }
  void be64(uint64_t v) {
    for (int i = 7; i >= 0; --i) u8((v >> (8 * i)) & 0xff);
  }
  void nil() { u8(0xc0); }
  void boolean(bool b) { u8(b ? 0xc3 : 0xc2); }
  void integer(int64_t v) {
    if (v >= 0) {
      if (v < 128) u8((uint8_t)v);
      else if (v <= 0xff) { u8(0xcc); u8((uint8_t)v); }
      else if (v <= 0xffff) { u8(0xcd); be16((uint16_t)v); }
      else if (v <= 0xffffffffLL) { u8(0xce); be32((uint32_t)v); }
      else { u8(0xcf); be64((uint64_t)v); }
    } else {
      if (v >= -32) u8((uint8_t)(0xe0 | (v + 32)));
      else { u8(0xd3); be64((uint64_t)v); }
    }
  }
  void str(const std::string& s) {
    size_t n = s.size();
    if (n < 32) u8(0xa0 | (uint8_t)n);
    else if (n <= 0xff) { u8(0xd9); u8((uint8_t)n); }
    else if (n <= 0xffff) { u8(0xda); be16((uint16_t)n); }
    else { u8(0xdb); be32((uint32_t)n); }
    raw(s.data(), n);
  }
  void bin(const std::string& b) {
    size_t n = b.size();
    if (n <= 0xff) { u8(0xc4); u8((uint8_t)n); }
    else if (n <= 0xffff) { u8(0xc5); be16((uint16_t)n); }
    else { u8(0xc6); be32((uint32_t)n); }
    raw(b.data(), n);
  }
  void floating(double d) {
    uint64_t raw;
    memcpy(&raw, &d, 8);
    u8(0xcb);
    be64(raw);
  }
  void array_header(uint32_t n) {
    if (n < 16) u8(0x90 | (uint8_t)n);
    else if (n <= 0xffff) { u8(0xdc); be16((uint16_t)n); }
    else { u8(0xdd); be32(n); }
  }
  void map_header(uint32_t n) {
    if (n < 16) u8(0x80 | (uint8_t)n);
    else if (n <= 0xffff) { u8(0xde); be16((uint16_t)n); }
    else { u8(0xdf); be32(n); }
  }
};

// ---------------------------------------------------------------------------
// Minimal msgpack value + decoder.
// ---------------------------------------------------------------------------
struct Value {
  enum Kind { NIL, BOOL, INT, FLOAT, STR, BIN, ARR, MAP } kind = NIL;
  bool b = false;
  int64_t i = 0;
  double f = 0;
  std::string s;  // STR and BIN payloads
  std::vector<Value> arr;
  std::map<std::string, Value> map;  // string-keyed maps only (our wire shape)

  const Value* get(const std::string& key) const {
    auto it = map.find(key);
    return it == map.end() ? nullptr : &it->second;
  }
  bool truthy() const {
    switch (kind) {
      case BOOL: return b;
      case INT: return i != 0;
      case NIL: return false;
      default: return true;
    }
  }
};

struct Unpacker {
  const uint8_t* p;
  const uint8_t* end;
  explicit Unpacker(const std::string& buf)
      : p((const uint8_t*)buf.data()), end(p + buf.size()) {}
  Unpacker(const uint8_t* data, size_t len) : p(data), end(data + len) {}
  uint8_t u8() { need(1); return *p++; }
  void need(size_t n) {
    if ((size_t)(end - p) < n) throw std::runtime_error("msgpack truncated");
  }
  uint64_t be(int n) {
    need(n);
    uint64_t v = 0;
    for (int i = 0; i < n; ++i) v = (v << 8) | *p++;
    return v;
  }
  std::string bytes(size_t n) {
    need(n);
    std::string s((const char*)p, n);
    p += n;
    return s;
  }
  Value decode() {
    uint8_t t = u8();
    Value v;
    if (t < 0x80) { v.kind = Value::INT; v.i = t; return v; }
    if (t >= 0xe0) { v.kind = Value::INT; v.i = (int8_t)t; return v; }
    if ((t & 0xf0) == 0x80) return map_body(t & 0x0f);
    if ((t & 0xf0) == 0x90) return arr_body(t & 0x0f);
    if ((t & 0xe0) == 0xa0) { v.kind = Value::STR; v.s = bytes(t & 0x1f); return v; }
    switch (t) {
      case 0xc0: return v;
      case 0xc2: v.kind = Value::BOOL; v.b = false; return v;
      case 0xc3: v.kind = Value::BOOL; v.b = true; return v;
      case 0xc4: v.kind = Value::BIN; v.s = bytes(be(1)); return v;
      case 0xc5: v.kind = Value::BIN; v.s = bytes(be(2)); return v;
      case 0xc6: v.kind = Value::BIN; v.s = bytes(be(4)); return v;
      case 0xca: { v.kind = Value::FLOAT; uint32_t raw = (uint32_t)be(4);
                   float f; memcpy(&f, &raw, 4); v.f = f; return v; }
      case 0xcb: { v.kind = Value::FLOAT; uint64_t raw = be(8);
                   memcpy(&v.f, &raw, 8); return v; }
      case 0xcc: v.kind = Value::INT; v.i = (int64_t)be(1); return v;
      case 0xcd: v.kind = Value::INT; v.i = (int64_t)be(2); return v;
      case 0xce: v.kind = Value::INT; v.i = (int64_t)be(4); return v;
      case 0xcf: {  // uint64: values past INT64_MAX would wrap negative
        uint64_t u = be(8);
        if (u > (uint64_t)INT64_MAX)
          throw std::runtime_error("msgpack uint64 exceeds int64 range");
        v.kind = Value::INT; v.i = (int64_t)u; return v;
      }
      case 0xd0: v.kind = Value::INT; v.i = (int8_t)be(1); return v;
      case 0xd1: v.kind = Value::INT; v.i = (int16_t)be(2); return v;
      case 0xd2: v.kind = Value::INT; v.i = (int32_t)be(4); return v;
      case 0xd3: v.kind = Value::INT; v.i = (int64_t)be(8); return v;
      case 0xd9: v.kind = Value::STR; v.s = bytes(be(1)); return v;
      case 0xda: v.kind = Value::STR; v.s = bytes(be(2)); return v;
      case 0xdb: v.kind = Value::STR; v.s = bytes(be(4)); return v;
      case 0xdc: return arr_body(be(2));
      case 0xdd: return arr_body(be(4));
      case 0xde: return map_body(be(2));
      case 0xdf: return map_body(be(4));
      default: throw std::runtime_error("msgpack type not handled");
    }
  }
  Value arr_body(uint64_t n) {
    Value v; v.kind = Value::ARR;
    for (uint64_t i = 0; i < n; ++i) v.arr.push_back(decode());
    return v;
  }
  Value map_body(uint64_t n) {
    Value v; v.kind = Value::MAP;
    for (uint64_t i = 0; i < n; ++i) {
      Value k = decode();
      if (k.kind != Value::STR)  // loud, not a silent one-entry collapse
        throw std::runtime_error("msgpack map key is not a string");
      v.map[std::move(k.s)] = decode();
    }
    return v;
  }
};


// Re-encode a decoded Value (round trip; map keys re-sort, semantically
// identical on the framework's string-keyed wire).
inline void pack_value(Packer& pk, const Value& v) {
  switch (v.kind) {
    case Value::NIL: pk.nil(); return;
    case Value::BOOL: pk.boolean(v.b); return;
    case Value::INT: pk.integer(v.i); return;
    case Value::FLOAT: pk.floating(v.f); return;
    case Value::STR: pk.str(v.s); return;
    case Value::BIN: pk.bin(v.s); return;
    case Value::ARR:
      pk.array_header((uint32_t)v.arr.size());
      for (const Value& e : v.arr) pack_value(pk, e);
      return;
    case Value::MAP:
      pk.map_header((uint32_t)v.map.size());
      for (const auto& kv : v.map) { pk.str(kv.first); pack_value(pk, kv.second); }
      return;
  }
}

// Debug/print representation (JSON-ish; BIN shown as <N bytes>).
inline std::string value_repr(const Value& v) {
  switch (v.kind) {
    case Value::NIL: return "null";
    case Value::BOOL: return v.b ? "true" : "false";
    case Value::INT: return std::to_string(v.i);
    case Value::FLOAT: {
      char buf[32];
      snprintf(buf, sizeof buf, "%g", v.f);
      return buf;
    }
    case Value::STR: return "\"" + v.s + "\"";
    case Value::BIN: return "<" + std::to_string(v.s.size()) + " bytes>";
    case Value::ARR: {
      std::string out = "[";
      for (size_t i = 0; i < v.arr.size(); ++i)
        out += (i ? "," : "") + value_repr(v.arr[i]);
      return out + "]";
    }
    case Value::MAP: {
      std::string out = "{";
      bool first = true;
      for (const auto& kv : v.map) {
        out += (first ? "" : ",") + ("\"" + kv.first + "\":") + value_repr(kv.second);
        first = false;
      }
      return out + "}";
    }
  }
  return "?";
}
