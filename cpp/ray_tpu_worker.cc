// ray_tpu C++ worker runtime — a native (no-Python) task executor.
//
// Completes the N22 surface past the client driver (ray_tpu_client.cc):
// where the reference ships a full C++ worker runtime (cpp/src/ray/runtime/
// — task execution loop, object store access, core-worker protocol), this
// binary is the framework's native analog: the raylet's worker pool spawns
// it for language="cpp" tasks (see _private/cpp_worker.py and raylet.py),
// it registers back over the real msgpack wire exactly like a Python worker
// (worker_main.py), receives `push_task` dispatches, executes C-ABI
// functions from a shared library (the cross_language contract of
// cpp/xlang_kernels.cc), and reports results straight to the OWNER's core
// worker as format-"x" (msgpack) objects — no pickle anywhere in the path.
//
// Protocol surface (mirrors worker_main.py for normal tasks):
//   server:  push_task {spec}        -> {"ok": true}, execute, then
//            kill_self               -> exit(0)
//            lease_ping / ping       -> {"ok": true}
//   client:  raylet.register_worker {worker_id, address, pid}
//            owner.task_done {task_id, results|error, duration_s}
//            raylet.task_finished {worker_id}
//            raylet.store_contains   (idle-time liveness probe; exit when
//                                     the parent raylet goes away —
//                                     reference: core_worker.cc
//                                     ExitIfParentRayletDies)
//
// v1 limits (documented in PARITY.md): normal tasks only (no actors), args
// must be inline cross-language values ("v" entries — ObjectRef args are
// answered with a typed error), single return, inline results.
//
// Build (automatic, cached): g++ -O2 -std=c++17 -o ray_tpu_cpp_worker
//   cpp/ray_tpu_worker.cc -ldl

#include <arpa/inet.h>
#include <dlfcn.h>
#include <netdb.h>
#include <netinet/in.h>
#include <poll.h>

#include <algorithm>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "ray_tpu_wire.h"

using rtpu_wire::RpcClient;
using rtpu_wire::encode_x_object;
using rtpu_wire::frame;
using rtpu_wire::send_all;

// Decode an inline framework arg; only format-"x" is native-decodable.
static bool decode_arg(const std::string& blob, Value* out, std::string* err) {
  if (!rtpu_wire::decode_x_object(blob, "x", out, err)) {
    // Keep corruption diagnostics ("object too short", "bad header
    // length", ...) verbatim; only the FORMAT mismatch gets the
    // what-to-do-instead message.
    if (err->rfind("object is not format-", 0) == 0)
      *err = "arg is not a cross-language (format-\"x\") object — C++ workers "
             "execute msgpack-plain args only";
    return false;
  }
  return true;
}

// ---------------------------------------------------------------------------
// Kernel execution: the cross_language C ABI (cpp/xlang_kernels.cc).
// ---------------------------------------------------------------------------

typedef int (*kernel_fn)(const uint8_t*, size_t, uint8_t**, size_t*);
typedef void (*free_fn)(uint8_t*);

struct LoadedLib {
  void* handle;
  free_fn freer;
};

static std::map<std::string, LoadedLib> g_libs;

static bool run_kernel(const std::string& library, const std::string& symbol,
                       const std::string& args_msgpack, std::string* result,
                       std::string* err) {
  auto it = g_libs.find(library);
  if (it == g_libs.end()) {
    void* h = dlopen(library.c_str(), RTLD_NOW);
    if (!h) { *err = std::string("dlopen failed: ") + dlerror(); return false; }
    free_fn fr = (free_fn)dlsym(h, "ray_tpu_xlang_free");
    if (!fr) { *err = "library lacks ray_tpu_xlang_free"; return false; }
    it = g_libs.emplace(library, LoadedLib{h, fr}).first;
  }
  kernel_fn fn = (kernel_fn)dlsym(it->second.handle, symbol.c_str());
  if (!fn) { *err = "symbol " + symbol + " not found in " + library; return false; }
  uint8_t* out = nullptr;
  size_t out_len = 0;
  int rc = fn((const uint8_t*)args_msgpack.data(), args_msgpack.size(), &out, &out_len);
  std::string data = out ? std::string((const char*)out, out_len) : std::string();
  if (out) it->second.freer(out);
  if (rc != 0) {
    *err = symbol + " failed (rc=" + std::to_string(rc) + "): " + data;
    return false;
  }
  *result = data;
  return true;
}

// ---------------------------------------------------------------------------
// Worker runtime.
// ---------------------------------------------------------------------------

struct Config {
  std::string worker_id;
  std::string raylet_host;
  int raylet_port = 0;
};

// Parse the minimal JSON shape `["host", port]` from RAY_TPU_RAYLET_ADDR.
static bool parse_addr(const char* json, std::string* host, int* port) {
  if (!json) return false;
  const char* q1 = strchr(json, '"');
  if (!q1) return false;
  const char* q2 = strchr(q1 + 1, '"');
  if (!q2) return false;
  host->assign(q1 + 1, q2 - q1 - 1);
  const char* c = strchr(q2, ',');
  if (!c) return false;
  *port = atoi(c + 1);
  return *port > 0;
}

static std::unique_ptr<RpcClient> g_raylet;
static Config g_cfg;

static RpcClient* owner_client(const std::string& host, int port,
                               std::map<std::string, std::unique_ptr<RpcClient>>& cache) {
  std::string key = host + ":" + std::to_string(port);
  auto it = cache.find(key);
  if (it == cache.end())
    it = cache.emplace(key, std::unique_ptr<RpcClient>(new RpcClient(host, port))).first;
  return it->second.get();
}

// Execute one pushed task spec; report to the owner and the raylet.
static void execute_task(const Value& spec,
                         std::map<std::string, std::unique_ptr<RpcClient>>& owners) {
  const Value* tid = spec.get("task_id");
  const Value* fkey = spec.get("function_key");
  const Value* oaddr = spec.get("owner_addr");
  const Value* name = spec.get("name");
  if (!tid) return;  // nothing to report against
  std::string task_name = name ? name->s : "cpp_task";

  struct timespec t0;
  clock_gettime(CLOCK_MONOTONIC, &t0);

  std::string err;
  std::string result_payload;
  bool ok = true;

  // function_key: "cpp!<library>!<symbol>" (set by core_worker.submit_task).
  std::string library, symbol;
  if (!fkey || fkey->s.rfind("cpp!", 0) != 0) {
    ok = false;
    err = "C++ worker received a non-cpp function key";
  } else {
    size_t bang = fkey->s.rfind('!');
    library = fkey->s.substr(4, bang - 4);
    symbol = fkey->s.substr(bang + 1);
  }

  // Args: inline "v" entries decode natively; "r" refs are a v1 limit.
  if (ok) {
    Packer args_pk;
    const Value* args = spec.get("args");
    uint32_t n = args && args->kind == Value::ARR ? (uint32_t)args->arr.size() : 0;
    args_pk.array_header(n);
    for (uint32_t i = 0; ok && i < n; ++i) {
      const Value& a = args->arr[i];
      if (a.kind != Value::ARR || a.arr.empty()) { ok = false; err = "malformed arg"; break; }
      if (a.arr[0].s == "r") {
        ok = false;
        err = "ObjectRef args are not supported by the C++ worker runtime yet "
              "— pass plain values to cpp_function tasks";
        break;
      }
      Value decoded;
      if (!decode_arg(a.arr[1].s, &decoded, &err)) { ok = false; break; }
      pack_value(args_pk, decoded);
    }
    if (ok) ok = run_kernel(library, symbol, args_pk.out, &result_payload, &err);
  }

  struct timespec t1;
  clock_gettime(CLOCK_MONOTONIC, &t1);
  double dur = (t1.tv_sec - t0.tv_sec) + (t1.tv_nsec - t0.tv_nsec) * 1e-9;

  // task_done payload to the owner.
  Packer done;
  done.map_header(4);
  done.str("task_id"); done.str(tid->s);
  if (ok) {
    done.str("results");
    done.array_header(1);
    done.array_header(4);
    done.str(tid->s + "00000000");  // ObjectID.for_return(task_id, 0)
    done.str("inline");
    done.bin(encode_x_object(result_payload, "x"));
    done.array_header(0);  // no contained refs in plain msgpack data
    done.str("error"); done.nil();
  } else {
    // Format-"xe": serialization.deserialize maps it to a TaskError
    // wrapping CrossLanguageError, so ray_tpu.get raises exactly like a
    // Python task failure.
    Packer ep;
    ep.map_header(2);
    ep.str("message"); ep.str(err);
    ep.str("task_name"); ep.str(task_name);
    done.str("results"); done.array_header(0);
    done.str("error"); done.bin(encode_x_object(ep.out, "xe"));
  }
  done.str("duration_s"); done.floating(dur);

  if (oaddr && oaddr->kind == Value::ARR && oaddr->arr.size() == 2) {
    try {
      RpcClient* owner = owner_client(oaddr->arr[0].s, (int)oaddr->arr[1].i, owners);
      owner->call("task_done", done.out);
    } catch (const std::exception& e) {
      fprintf(stderr, "cpp_worker: task_done to owner failed: %s\n", e.what());
    }
  }
  try {
    Packer fin;
    fin.map_header(1);
    fin.str("worker_id"); fin.str(g_cfg.worker_id);
    g_raylet->call("task_finished", fin.out);
  } catch (const std::exception& e) {
    fprintf(stderr, "cpp_worker: task_finished failed: %s — raylet gone, exiting\n", e.what());
    exit(1);
  }
}

int main() {
  const char* wid = getenv("RAY_TPU_WORKER_ID");
  if (!wid || !parse_addr(getenv("RAY_TPU_RAYLET_ADDR"), &g_cfg.raylet_host,
                          &g_cfg.raylet_port)) {
    fprintf(stderr, "cpp_worker: RAY_TPU_WORKER_ID / RAY_TPU_RAYLET_ADDR missing\n");
    return 2;
  }
  g_cfg.worker_id = wid;
  try {
    // Listen before registering: tasks may be pushed immediately after.
    int lfd = socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = 0;
    if (bind(lfd, (sockaddr*)&addr, sizeof(addr)) != 0 || listen(lfd, 16) != 0)
      throw std::runtime_error("listen failed");
    socklen_t alen = sizeof(addr);
    getsockname(lfd, (sockaddr*)&addr, &alen);
    int port = ntohs(addr.sin_port);

    g_raylet.reset(new RpcClient(g_cfg.raylet_host, g_cfg.raylet_port));
    {
      Packer reg;
      reg.map_header(3);
      reg.str("worker_id"); reg.str(g_cfg.worker_id);
      reg.str("address");
      reg.array_header(2);
      reg.str(g_cfg.raylet_host);  // same host as the raylet (one node)
      reg.integer(port);
      reg.str("pid"); reg.integer((int64_t)getpid());
      Value r = g_raylet->call("register_worker", reg.out);
      const Value* okf = r.get("ok");
      if (okf && !okf->truthy()) return 0;  // retired id — orphan, exit
    }
    printf("CPP_WORKER_READY %s port=%d\n", g_cfg.worker_id.c_str(), port);
    fflush(stdout);

    std::map<std::string, std::unique_ptr<RpcClient>> owners;
    std::vector<int> conns;
    std::map<int, std::string> bufs;  // per-connection receive buffer
    time_t last_probe = time(nullptr);

    for (;;) {
      std::vector<pollfd> fds;
      fds.push_back({lfd, POLLIN, 0});
      for (int fd : conns) fds.push_back({fd, POLLIN, 0});
      int nready = poll(fds.data(), fds.size(), 2000);
      if (nready < 0) {
        if (errno == EINTR) continue;  // stray signal must not kill the worker
        throw std::runtime_error("poll failed");
      }
      // Idle liveness probe: workers exit if the parent raylet dies
      // (reference: core_worker.cc ExitIfParentRayletDies).
      if (time(nullptr) - last_probe >= 2) {
        last_probe = time(nullptr);
        try {
          Packer p;
          p.map_header(1);
          p.str("object_id");
          p.str(std::string(56, '0'));
          g_raylet->call("store_contains", p.out);
        } catch (const std::exception&) {
          fprintf(stderr, "cpp_worker: parent raylet unreachable; exiting\n");
          return 1;
        }
      }
      if (fds[0].revents & POLLIN) {
        int c = accept(lfd, nullptr, nullptr);
        if (c >= 0) { conns.push_back(c); bufs[c] = ""; }
      }
      for (size_t i = 1; i < fds.size(); ++i) {
        if (!(fds[i].revents & (POLLIN | POLLHUP | POLLERR))) continue;
        int fd = fds[i].fd;
        char chunk[65536];
        ssize_t n = read(fd, chunk, sizeof chunk);
        if (n <= 0) {
          close(fd);
          conns.erase(std::find(conns.begin(), conns.end(), fd));
          bufs.erase(fd);
          continue;
        }
        std::string& buf = bufs[fd];
        buf.append(chunk, (size_t)n);
        // Drain complete frames.
        while (buf.size() >= 4) {
          uint32_t blen = ntohl(*(const uint32_t*)buf.data());
          if (buf.size() < 4 + (size_t)blen) break;
          std::string body = buf.substr(4, blen);
          buf.erase(0, 4 + blen);
          // Decode under a narrow catch: one malformed frame from a peer
          // must not kill the worker (the driver's serve() drops these
          // too). Ack/execute failures stay OUTSIDE it — they must keep
          // propagating to the outer handler so the worker dies and the
          // raylet reports task_failed, instead of silently leaking the
          // lease with the owner blocked.
          Value msg;
          int64_t seq;
          const std::string* method;
          try {
            Unpacker up(body);
            msg = up.decode();
            seq = msg.arr.at(1).i;
            method = &msg.arr.at(2).s;
          } catch (const std::exception& e) {
            fprintf(stderr, "cpp_worker: dropped malformed frame: %s\n",
                    e.what());
            continue;
          }
          // Reply first (the Python worker acks push_task before
          // executing too), then run the task synchronously.
          Packer resp;
          resp.array_header(4);
          resp.integer(1);  // RESPONSE
          resp.integer(seq);
          resp.str(*method);
          resp.map_header(1);
          resp.str("ok");
          resp.boolean(true);
          send_all(fd, frame(resp.out));
          if (*method == "push_task") {
            // Bounds-checked: a 3-element frame is malformed, not fatal.
            const Value* spec =
                msg.arr.size() > 3 ? msg.arr[3].get("spec") : nullptr;
            if (spec) execute_task(*spec, owners);
          } else if (*method == "kill_self") {
            return 0;
          }  // lease_ping / unknown: ok-ack above suffices
        }
      }
    }
  } catch (const std::exception& e) {
    fprintf(stderr, "cpp_worker: fatal: %s\n", e.what());
    return 1;
  }
}
