// ray_tpu C++ worker runtime — a native (no-Python) task executor.
//
// Completes the N22 surface past the client driver (ray_tpu_client.cc):
// where the reference ships a full C++ worker runtime (cpp/src/ray/runtime/
// — task execution loop, object store access, core-worker protocol), this
// binary is the framework's native analog: the raylet's worker pool spawns
// it for language="cpp" tasks (see _private/cpp_worker.py and raylet.py),
// it registers back over the real msgpack wire exactly like a Python worker
// (worker_main.py), receives `push_task` dispatches, executes C-ABI
// functions from a shared library (the cross_language contract of
// cpp/xlang_kernels.cc), and reports results straight to the OWNER's core
// worker as format-"x" (msgpack) objects — no pickle anywhere in the path.
//
// Protocol surface (mirrors worker_main.py for normal tasks):
//   server:  push_task {spec}        -> {"ok": true}, execute, then
//            kill_self               -> exit(0)
//            lease_ping / ping       -> {"ok": true}
//   client:  raylet.register_worker {worker_id, address, pid}
//            owner.task_done {task_id, results|error, duration_s}
//            raylet.task_finished {worker_id}
//            raylet.store_contains   (idle-time liveness probe; exit when
//                                     the parent raylet goes away —
//                                     reference: core_worker.cc
//                                     ExitIfParentRayletDies)
//
// Object data path (the reference's native task_executor.cc +
// object_store.cc analog): ObjectRef args resolve NATIVELY — local sealed
// objects read zero-copy through the shm index + arena (the same C APIs
// ctypes uses, compiled in), misses fetch from the OWNER over the wire
// (get_inline; a "plasma" answer routes back through this node's raylet
// store_get, which pulls cross-node), and plasma-sized RESULTS are written
// into the arena (store_create -> memcpy -> store_seal) and reported as
// ["plasma", node_id] entries instead of inline bytes. Only format-"x"
// objects are native-decodable; the owner's router (core_worker.submit_task)
// guarantees that by keeping non-provably-"x" ref args on the Python path.
//
// Remaining limits (documented in PARITY.md): normal tasks only (no
// actors), single return.
//
// Build (automatic, cached): g++ -O2 -std=c++17 -o ray_tpu_cpp_worker
//   cpp/ray_tpu_worker.cc ray_tpu/_native/shm_arena.cc
//   ray_tpu/_native/shm_index.cc -ldl

#include <arpa/inet.h>
#include <dlfcn.h>
#include <netdb.h>
#include <netinet/in.h>
#include <poll.h>

#include <algorithm>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "ray_tpu_wire.h"

using rtpu_wire::RpcClient;
using rtpu_wire::encode_x_object;
using rtpu_wire::frame;
using rtpu_wire::send_all;

// shm arena/index C APIs (ray_tpu/_native/shm_{arena,index}.cc — compiled
// into this binary; the same functions Python drives through ctypes).
extern "C" {
int arena_attach(const char* name);
void* arena_base(int handle);
int arena_close(int handle, int unlink_seg);
int idx_attach(const char* name);
int idx_get_pinned(int handle, const uint8_t* key, uint64_t* offset,
                   uint64_t* size, uint32_t* version, uint64_t* slot);
int idx_release(int handle, uint64_t slot, uint32_t version);
int idx_close(int handle, int unlink_seg);
}

static int g_arena = -1;
static int g_idx = -1;
static std::string g_node_id;
static const size_t kObjectKeyLen = 28;  // ids.py OBJECT_ID_SIZE

static bool hex_to_key(const std::string& hex, uint8_t* key) {
  if (hex.size() != 2 * kObjectKeyLen) return false;
  auto nib = [](char c) -> int {
    if (c >= '0' && c <= '9') return c - '0';
    if (c >= 'a' && c <= 'f') return c - 'a' + 10;
    if (c >= 'A' && c <= 'F') return c - 'A' + 10;
    return -1;
  };
  for (size_t i = 0; i < kObjectKeyLen; ++i) {
    int hi = nib(hex[2 * i]), lo = nib(hex[2 * i + 1]);
    if (hi < 0 || lo < 0) return false;
    key[i] = (uint8_t)((hi << 4) | lo);
  }
  return true;
}

// Decode an inline framework arg; only format-"x" is native-decodable.
static bool decode_arg(const std::string& blob, Value* out, std::string* err) {
  if (!rtpu_wire::decode_x_object(blob, "x", out, err)) {
    // Keep corruption diagnostics ("object too short", "bad header
    // length", ...) verbatim; only the FORMAT mismatch gets the
    // what-to-do-instead message.
    if (err->rfind("object is not format-", 0) == 0)
      *err = "arg is not a cross-language (format-\"x\") object — C++ workers "
             "execute msgpack-plain args only";
    return false;
  }
  return true;
}

// ---------------------------------------------------------------------------
// Kernel execution: the cross_language C ABI (cpp/xlang_kernels.cc).
// ---------------------------------------------------------------------------

typedef int (*kernel_fn)(const uint8_t*, size_t, uint8_t**, size_t*);
typedef void (*free_fn)(uint8_t*);

struct LoadedLib {
  void* handle;
  free_fn freer;
};

static std::map<std::string, LoadedLib> g_libs;

static bool run_kernel(const std::string& library, const std::string& symbol,
                       const std::string& args_msgpack, std::string* result,
                       std::string* err) {
  auto it = g_libs.find(library);
  if (it == g_libs.end()) {
    void* h = dlopen(library.c_str(), RTLD_NOW);
    if (!h) { *err = std::string("dlopen failed: ") + dlerror(); return false; }
    free_fn fr = (free_fn)dlsym(h, "ray_tpu_xlang_free");
    if (!fr) { *err = "library lacks ray_tpu_xlang_free"; return false; }
    it = g_libs.emplace(library, LoadedLib{h, fr}).first;
  }
  kernel_fn fn = (kernel_fn)dlsym(it->second.handle, symbol.c_str());
  if (!fn) { *err = "symbol " + symbol + " not found in " + library; return false; }
  uint8_t* out = nullptr;
  size_t out_len = 0;
  int rc = fn((const uint8_t*)args_msgpack.data(), args_msgpack.size(), &out, &out_len);
  std::string data = out ? std::string((const char*)out, out_len) : std::string();
  if (out) it->second.freer(out);
  if (rc != 0) {
    *err = symbol + " failed (rc=" + std::to_string(rc) + "): " + data;
    return false;
  }
  *result = data;
  return true;
}

// ---------------------------------------------------------------------------
// Worker runtime.
// ---------------------------------------------------------------------------

struct Config {
  std::string worker_id;
  std::string raylet_host;
  int raylet_port = 0;
};

// Parse the minimal JSON shape `["host", port]` from RAY_TPU_RAYLET_ADDR.
static bool parse_addr(const char* json, std::string* host, int* port) {
  if (!json) return false;
  const char* q1 = strchr(json, '"');
  if (!q1) return false;
  const char* q2 = strchr(q1 + 1, '"');
  if (!q2) return false;
  host->assign(q1 + 1, q2 - q1 - 1);
  const char* c = strchr(q2, ',');
  if (!c) return false;
  *port = atoi(c + 1);
  return *port > 0;
}

static std::unique_ptr<RpcClient> g_raylet;
static Config g_cfg;

static RpcClient* owner_client(const std::string& host, int port,
                               std::map<std::string, std::unique_ptr<RpcClient>>& cache) {
  std::string key = host + ":" + std::to_string(port);
  auto it = cache.find(key);
  if (it == cache.end())
    it = cache.emplace(key, std::unique_ptr<RpcClient>(new RpcClient(host, port))).first;
  return it->second.get();
}

// ---------------------------------------------------------------------------
// Object data path (reference: cpp/src/ray/runtime/object/object_store.cc).
// ---------------------------------------------------------------------------

// Fetch an object's serialized wire bytes by id. Fast path: local sealed
// object via the shm index (pin -> copy out -> release). Miss: ask the
// OWNER (get_inline) — inline objects arrive as bytes, plasma answers route
// through this node's raylet store_get, which pulls cross-node if needed.
static bool fetch_object_bytes(const std::string& oid_hex,
                               const std::string& owner_host, int owner_port,
                               std::map<std::string, std::unique_ptr<RpcClient>>& owners,
                               std::string* out, std::string* err) {
  uint8_t key[kObjectKeyLen];
  if (g_arena >= 0 && g_idx >= 0 && hex_to_key(oid_hex, key)) {
    uint64_t offset = 0, size = 0, slot = 0;
    uint32_t version = 0;
    if (idx_get_pinned(g_idx, key, &offset, &size, &version, &slot)) {
      const char* base = (const char*)arena_base(g_arena);
      out->assign(base + offset, size);
      idx_release(g_idx, slot, version);
      return true;
    }
  }
  // Not sealed locally: the owner knows where it lives. Python owners
  // block server-side on wait=true; the C++ driver's owner server answers
  // "missing" for not-yet-done producers (its serve thread must not block),
  // so poll with a bounded budget.
  try {
    RpcClient* owner = owner_client(owner_host, owner_port, owners);
    Packer p;
    p.map_header(2);
    p.str("object_id"); p.str(oid_hex);
    p.str("wait"); p.boolean(true);
    Value resp;
    for (int attempt = 0; ; ++attempt) {
      resp = owner->call("get_inline", p.out);
      const Value* k = resp.get("kind");
      if (!k || k->s != "missing") break;
      if (attempt >= 600) {  // ~60s
        *err = "object " + oid_hex.substr(0, 12) + " never materialized at its owner";
        return false;
      }
      struct timespec ts = {0, 100 * 1000 * 1000};
      nanosleep(&ts, nullptr);
    }
    const Value* kind = resp.get("kind");
    if (kind && kind->s == "inline") {
      const Value* data = resp.get("data");
      if (!data) { *err = "owner get_inline returned no data"; return false; }
      *out = data->s;
      return true;
    }
    if (kind && kind->s == "plasma") {
      // Somewhere in the cluster's plasma tier: store_get on OUR raylet
      // blocks until it is sealed locally (pulling if remote), and pins it.
      Packer q;
      q.map_header(2);
      q.str("object_id"); q.str(oid_hex);
      q.str("timeout"); q.floating(60.0);
      Value got = g_raylet->call("store_get", q.out);
      const Value* off = got.get("offset");
      const Value* sz = got.get("size");
      if (!off || !sz) { *err = "store_get gave no offset/size"; return false; }
      if (g_arena >= 0) {
        const char* base = (const char*)arena_base(g_arena);
        out->assign(base + (uint64_t)off->i, (size_t)sz->i);
      } else {
        // Arena attach failed at startup: degrade to wire chunk reads
        // (exactly the driver's shm-free path), not task failure.
        out->clear();
        out->reserve((size_t)sz->i);
        const int64_t kChunk = 4 * 1024 * 1024;
        for (int64_t pos = 0; pos < sz->i;) {
          Packer c;
          c.map_header(3);
          c.str("object_id"); c.str(oid_hex);
          c.str("start"); c.integer(pos);
          c.str("length"); c.integer(kChunk);
          Value chunk = g_raylet->call("fetch_object_chunk", c.out);
          const Value* data = chunk.get("data");
          if (!data || data->s.empty()) {
            *err = "fetch_object_chunk starved at " + std::to_string(pos);
            return false;
          }
          *out += data->s;
          pos += (int64_t)data->s.size();
        }
      }
      Packer r;
      r.map_header(1);
      r.str("object_id"); r.str(oid_hex);
      try { g_raylet->call("store_release", r.out); } catch (...) {}
      return true;
    }
    if (kind && kind->s == "failed") {
      const Value* msg = resp.get("message");
      *err = "producer of " + oid_hex.substr(0, 12) + " failed: " +
             (msg ? msg->s : "task failed");
      return false;
    }
    *err = "object " + oid_hex.substr(0, 12) + " unavailable (owner says " +
           (kind ? kind->s : "?") + ")";
    return false;
  } catch (const std::exception& e) {
    *err = std::string("object fetch failed: ") + e.what();
    return false;
  }
}

// Write a plasma-sized result into the arena via the raylet's create/seal
// protocol. Returns false (fall back to inline) on any trouble.
static bool store_result_bytes(const std::string& oid_hex, const std::string& bytes,
                               std::string* err) {
  if (g_arena < 0) { *err = "no arena"; return false; }
  try {
    Packer c;
    c.map_header(2);
    c.str("object_id"); c.str(oid_hex);
    c.str("size"); c.integer((int64_t)bytes.size());
    Value resp = g_raylet->call("store_create", c.out);
    const Value* exists = resp.get("exists");
    if (exists && exists->truthy()) {
      const Value* sealed = resp.get("sealed");
      // Sealed: idempotent re-execution, nothing to write. Unsealed: a
      // rival session owns the buffer — don't co-write it.
      if (sealed && sealed->truthy()) return true;
      *err = "rival unsealed create";
      return false;
    }
    const Value* off = resp.get("offset");
    if (!off) { *err = "store_create gave no offset"; return false; }
    std::memcpy((char*)arena_base(g_arena) + (uint64_t)off->i, bytes.data(),
                bytes.size());
    Packer s;
    s.map_header(1);
    s.str("object_id"); s.str(oid_hex);
    g_raylet->call("store_seal", s.out);
    return true;
  } catch (const std::exception& e) {
    *err = e.what();
    return false;
  }
}

// Execute one pushed task spec; report to the owner and the raylet.
static void execute_task(const Value& spec,
                         std::map<std::string, std::unique_ptr<RpcClient>>& owners) {
  const Value* tid = spec.get("task_id");
  const Value* fkey = spec.get("function_key");
  const Value* oaddr = spec.get("owner_addr");
  const Value* name = spec.get("name");
  if (!tid) return;  // nothing to report against
  std::string task_name = name ? name->s : "cpp_task";

  struct timespec t0;
  clock_gettime(CLOCK_MONOTONIC, &t0);

  std::string err;
  std::string result_payload;
  bool ok = true;

  // function_key: "cpp!<library>!<symbol>" (set by core_worker.submit_task).
  std::string library, symbol;
  if (!fkey || fkey->s.rfind("cpp!", 0) != 0) {
    ok = false;
    err = "C++ worker received a non-cpp function key";
  } else {
    size_t bang = fkey->s.rfind('!');
    library = fkey->s.substr(4, bang - 4);
    symbol = fkey->s.substr(bang + 1);
  }

  // Args: inline "v" entries decode in place; "r" refs resolve through the
  // native object path (shm zero-copy locally, owner/raylet fetch
  // otherwise). Both end as format-"x" wire bytes -> msgpack values.
  if (ok) {
    Packer args_pk;
    const Value* args = spec.get("args");
    uint32_t n = args && args->kind == Value::ARR ? (uint32_t)args->arr.size() : 0;
    args_pk.array_header(n);
    for (uint32_t i = 0; ok && i < n; ++i) {
      const Value& a = args->arr[i];
      if (a.kind != Value::ARR || a.arr.empty()) { ok = false; err = "malformed arg"; break; }
      std::string wire_bytes;
      if (a.arr[0].s == "r") {
        // ["r", oid_hex, [owner_host, owner_port]]
        if (a.arr.size() < 3 || a.arr[2].kind != Value::ARR ||
            a.arr[2].arr.size() != 2) {
          ok = false;
          err = "malformed ref arg";
          break;
        }
        if (!fetch_object_bytes(a.arr[1].s, a.arr[2].arr[0].s,
                                (int)a.arr[2].arr[1].i, owners, &wire_bytes,
                                &err)) {
          ok = false;
          break;
        }
      } else {
        wire_bytes = a.arr[1].s;
      }
      Value decoded;
      if (!decode_arg(wire_bytes, &decoded, &err)) { ok = false; break; }
      pack_value(args_pk, decoded);
    }
    if (ok) ok = run_kernel(library, symbol, args_pk.out, &result_payload, &err);
  }

  struct timespec t1;
  clock_gettime(CLOCK_MONOTONIC, &t1);
  double dur = (t1.tv_sec - t0.tv_sec) + (t1.tv_nsec - t0.tv_nsec) * 1e-9;

  // task_done payload to the owner. Plasma-sized results go to the arena
  // (matching core_worker._package_one's 100KB inline cutoff) and ship as
  // ["plasma", node_id]; everything else stays inline.
  Packer done;
  done.map_header(4);
  done.str("task_id"); done.str(tid->s);
  if (ok) {
    const std::string oid = tid->s + "00000000";  // ObjectID.for_return(.., 0)
    std::string wire = encode_x_object(result_payload, "x");
    const char* thr_env = getenv("RAY_TPU_MAX_DIRECT_CALL_OBJECT_SIZE");
    size_t threshold = thr_env ? (size_t)atoll(thr_env) : 100 * 1024;
    bool plasma = false;
    if (wire.size() > threshold && !g_node_id.empty()) {
      std::string serr;
      plasma = store_result_bytes(oid, wire, &serr);
      if (!plasma)
        fprintf(stderr, "cpp_worker: plasma result write failed (%s); "
                "falling back to inline\n", serr.c_str());
    }
    done.str("results");
    done.array_header(1);
    done.array_header(4);
    done.str(oid);
    if (plasma) {
      done.str("plasma");
      done.str(g_node_id);
    } else {
      done.str("inline");
      done.bin(wire);
    }
    done.array_header(0);  // no contained refs in plain msgpack data
    done.str("error"); done.nil();
  } else {
    // Format-"xe": serialization.deserialize maps it to a TaskError
    // wrapping CrossLanguageError, so ray_tpu.get raises exactly like a
    // Python task failure.
    Packer ep;
    ep.map_header(2);
    ep.str("message"); ep.str(err);
    ep.str("task_name"); ep.str(task_name);
    done.str("results"); done.array_header(0);
    done.str("error"); done.bin(encode_x_object(ep.out, "xe"));
  }
  done.str("duration_s"); done.floating(dur);

  if (oaddr && oaddr->kind == Value::ARR && oaddr->arr.size() == 2) {
    try {
      RpcClient* owner = owner_client(oaddr->arr[0].s, (int)oaddr->arr[1].i, owners);
      owner->call("task_done", done.out);
    } catch (const std::exception& e) {
      fprintf(stderr, "cpp_worker: task_done to owner failed: %s\n", e.what());
    }
  }
  try {
    Packer fin;
    fin.map_header(1);
    fin.str("worker_id"); fin.str(g_cfg.worker_id);
    g_raylet->call("task_finished", fin.out);
  } catch (const std::exception& e) {
    fprintf(stderr, "cpp_worker: task_finished failed: %s — raylet gone, exiting\n", e.what());
    exit(1);
  }
}

int main() {
  const char* wid = getenv("RAY_TPU_WORKER_ID");
  if (!wid || !parse_addr(getenv("RAY_TPU_RAYLET_ADDR"), &g_cfg.raylet_host,
                          &g_cfg.raylet_port)) {
    fprintf(stderr, "cpp_worker: RAY_TPU_WORKER_ID / RAY_TPU_RAYLET_ADDR missing\n");
    return 2;
  }
  g_cfg.worker_id = wid;
  // Object data path: attach the node's shm arena + index (zero-copy local
  // reads, plasma result writes). Absence degrades to owner-fetch + inline
  // results, not failure.
  if (const char* arena_name = getenv("RAY_TPU_ARENA_NAME")) {
    g_arena = arena_attach(arena_name);
    g_idx = idx_attach((std::string(arena_name) + "_idx").c_str());
  }
  if (const char* nid = getenv("RAY_TPU_NODE_ID")) g_node_id = nid;
  try {
    // Listen before registering: tasks may be pushed immediately after.
    int lfd = socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = 0;
    if (bind(lfd, (sockaddr*)&addr, sizeof(addr)) != 0 || listen(lfd, 16) != 0)
      throw std::runtime_error("listen failed");
    socklen_t alen = sizeof(addr);
    getsockname(lfd, (sockaddr*)&addr, &alen);
    int port = ntohs(addr.sin_port);

    g_raylet.reset(new RpcClient(g_cfg.raylet_host, g_cfg.raylet_port));
    {
      Packer reg;
      reg.map_header(3);
      reg.str("worker_id"); reg.str(g_cfg.worker_id);
      reg.str("address");
      reg.array_header(2);
      reg.str(g_cfg.raylet_host);  // same host as the raylet (one node)
      reg.integer(port);
      reg.str("pid"); reg.integer((int64_t)getpid());
      Value r = g_raylet->call("register_worker", reg.out);
      const Value* okf = r.get("ok");
      if (okf && !okf->truthy()) return 0;  // retired id — orphan, exit
    }
    printf("CPP_WORKER_READY %s port=%d\n", g_cfg.worker_id.c_str(), port);
    fflush(stdout);

    std::map<std::string, std::unique_ptr<RpcClient>> owners;
    std::vector<int> conns;
    std::map<int, std::string> bufs;  // per-connection receive buffer
    time_t last_probe = time(nullptr);

    for (;;) {
      std::vector<pollfd> fds;
      fds.push_back({lfd, POLLIN, 0});
      for (int fd : conns) fds.push_back({fd, POLLIN, 0});
      int nready = poll(fds.data(), fds.size(), 2000);
      if (nready < 0) {
        if (errno == EINTR) continue;  // stray signal must not kill the worker
        throw std::runtime_error("poll failed");
      }
      // Idle liveness probe: workers exit if the parent raylet dies
      // (reference: core_worker.cc ExitIfParentRayletDies).
      if (time(nullptr) - last_probe >= 2) {
        last_probe = time(nullptr);
        try {
          Packer p;
          p.map_header(1);
          p.str("object_id");
          p.str(std::string(56, '0'));
          g_raylet->call("store_contains", p.out);
        } catch (const std::exception&) {
          fprintf(stderr, "cpp_worker: parent raylet unreachable; exiting\n");
          return 1;
        }
      }
      if (fds[0].revents & POLLIN) {
        int c = accept(lfd, nullptr, nullptr);
        if (c >= 0) { conns.push_back(c); bufs[c] = ""; }
      }
      for (size_t i = 1; i < fds.size(); ++i) {
        if (!(fds[i].revents & (POLLIN | POLLHUP | POLLERR))) continue;
        int fd = fds[i].fd;
        char chunk[65536];
        ssize_t n = read(fd, chunk, sizeof chunk);
        if (n <= 0) {
          close(fd);
          conns.erase(std::find(conns.begin(), conns.end(), fd));
          bufs.erase(fd);
          continue;
        }
        std::string& buf = bufs[fd];
        buf.append(chunk, (size_t)n);
        // Drain complete frames.
        while (buf.size() >= 4) {
          uint32_t blen = ntohl(*(const uint32_t*)buf.data());
          if (buf.size() < 4 + (size_t)blen) break;
          std::string body = buf.substr(4, blen);
          buf.erase(0, 4 + blen);
          // Decode under a narrow catch: one malformed frame from a peer
          // must not kill the worker (the driver's serve() drops these
          // too). Ack/execute failures stay OUTSIDE it — they must keep
          // propagating to the outer handler so the worker dies and the
          // raylet reports task_failed, instead of silently leaking the
          // lease with the owner blocked.
          Value msg;
          int64_t seq;
          const std::string* method;
          try {
            Unpacker up(body);
            msg = up.decode();
            seq = msg.arr.at(1).i;
            method = &msg.arr.at(2).s;
          } catch (const std::exception& e) {
            fprintf(stderr, "cpp_worker: dropped malformed frame: %s\n",
                    e.what());
            continue;
          }
          // Reply first (the Python worker acks push_task before
          // executing too), then run the task synchronously.
          Packer resp;
          resp.array_header(4);
          resp.integer(1);  // RESPONSE
          resp.integer(seq);
          resp.str(*method);
          resp.map_header(1);
          resp.str("ok");
          resp.boolean(true);
          send_all(fd, frame(resp.out));
          if (*method == "push_task") {
            // Bounds-checked: a 3-element frame is malformed, not fatal.
            const Value* spec =
                msg.arr.size() > 3 ? msg.arr[3].get("spec") : nullptr;
            if (spec) execute_task(*spec, owners);
          } else if (*method == "kill_self") {
            return 0;
          }  // lease_ping / unknown: ok-ack above suffices
        }
      }
    }
  } catch (const std::exception& e) {
    fprintf(stderr, "cpp_worker: fatal: %s\n", e.what());
    return 1;
  }
}
