"""Compiled execution graphs (``dag.experimental_compile()``).

Classic ``dag.execute()`` walks the graph and pays the full control plane
per node per call: task-spec encode, ObjectRef allocation, owner
bookkeeping, raylet/actor RPCs. A training step loop or a multi-stage
inference pipeline runs exactly the same static graph millions of times,
so ``experimental_compile()`` does the control-plane work ONCE:

- validates a static DAG of actor-method nodes (one ``InputNode``, every
  stage transitively fed by it, terminals at the root);
- resolves the actor gang through the same per-DAG actor cache classic
  execution uses (``ClassNode.resolve_actor_handle``);
- allocates one shm ``Channel`` per edge (``experimental/channel/``) via
  the raylet's arena bindings;
- installs a resident channel loop on each participating worker
  (``channel_loop_install`` -> ``experimental/channel/resident_loop.py``).

Steady state, ``CompiledDAG.execute(x)`` writes the input channel(s) and
returns a ``CompiledDAGRef`` whose ``get()`` reads the output channel:
zero raylet RPCs, zero task specs, zero ObjectRef allocations per
iteration. With ``RAY_TPU_HOP_TIMING=1`` each iteration leaves a
``path="compiled"`` hop record (driver submit/ship, per-stage recv/exec,
owner recv/wake) so the classic-vs-compiled budget is recorded, not prose.

Robustness is part of the subsystem: ``teardown()`` stops the resident
loops, drains and frees every channel back to the arena; a participating
actor dying mid-loop plants typed-error poison through all downstream
channels so ``get()`` raises ``ActorDiedError`` naming the dead stage
instead of hanging; unconsumed results past ``max_buffered_results``
backpressure ``execute()``; ``get(timeout=...)`` raises GetTimeoutError.
"""

from __future__ import annotations

import logging
import os
import struct
import threading
import time

from ray_tpu._private import serialization
from ray_tpu.dag.dag_node import (
    ClassMethodNode,
    ClassNode,
    DAGNode,
    FunctionNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
    _DAGInputData,
)
from ray_tpu.exceptions import ActorDiedError, GetTimeoutError, TaskError
from ray_tpu.experimental.channel.channel import (
    _OFF_CLOSED,
    KIND_DEVICE,
    KIND_ERROR,
    KIND_VALUE,
    ChannelClosedError,
    ChannelReader,
    ChannelTimeoutError,
    ChannelWriter,
    make_descriptor,
    pack_envelope,
    ring_bytes,
)

logger = logging.getLogger(__name__)

_GET_SLICE_S = 0.1

# Staged-slot markers for device-envelope resolution (_drain_next): a
# resolved slot must be memoized so a get(timeout=) expiring on a LATER
# output channel cannot re-resolve (and double-release) this one.
_RESOLVED = -2
_RESOLVE_ERR = -3


class CompiledDAGRef:
    """Handle to one compiled iteration's result. NOT an ObjectRef — no
    owner bookkeeping, no reference counting, no store entry."""

    __slots__ = ("_dag", "_idx", "_outcome")

    def __init__(self, dag: "CompiledDAG", idx: int):
        self._dag = dag
        self._idx = idx
        self._outcome = None  # ("val", v) | ("err", exc) once consumed

    @property
    def execution_index(self) -> int:
        return self._idx

    def get(self, timeout: float | None = None):
        if self._outcome is None:
            self._outcome = self._dag._get_result(self._idx, timeout)
        kind, payload = self._outcome
        if kind == "err":
            raise payload
        return payload

    def __repr__(self):
        return f"CompiledDAGRef(idx={self._idx})"


class _Stage:
    """Compile-time view of one ClassMethodNode."""

    def __init__(self, sid: int, node: ClassMethodNode, actor_id: str):
        self.sid = sid
        self.node = node
        self.actor_id = actor_id
        self.method = node._method_name
        self.label = f"{sid}:{node._method_name}"
        self.arg_specs: list = []    # ["c", desc] | ["v", bytes]
        self.kwarg_specs: dict = {}
        self.out_descs: list = []
        self.has_input = False


class CompiledDAG:
    def __init__(
        self,
        root: DAGNode,
        *,
        max_buffered_results: int = 16,
        slot_size_bytes: int = 64 * 1024,
        submit_timeout_s: float = 30.0,
    ):
        from ray_tpu._private import worker_context

        if max_buffered_results < 1:
            raise ValueError("max_buffered_results must be >= 1")
        self._cw = worker_context.get_core_worker()
        self._root = root
        self._num_slots = int(max_buffered_results)
        self._slot_size = max(4096, int(slot_size_bytes))
        self._submit_timeout = submit_timeout_s
        self._dag_id = os.urandom(8).hex()

        self._next_idx = 0
        self._next_out_seq = 0
        # Envelopes already consumed from SOME output readers of the
        # in-progress iteration: a get(timeout=) that expires halfway through
        # a multi-output drain must not lose them (the ring read is
        # destructive) or every later result would pair mismatched
        # iterations.
        self._staged: list = []
        self._results: dict[int, tuple] = {}
        self._consume_lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._error: BaseException | None = None
        self._torn_down = False

        self._input_writers: list[tuple] = []    # (projection key, writer)
        self._output_readers: list[ChannelReader] = []
        self._all_descs: list[dict] = []
        self._allocs: list[tuple] = []           # (raylet_addr|None, cid)
        self._actor_addrs: dict[str, tuple] = {}
        self._actor_outputs: dict[str, list] = {}  # actor_id -> [(label, desc)]
        self._dead_actors: set[str] = set()

        # Channel payloads this driver creates (device-resident jax.Array
        # inputs routed as descriptor slots) reclaim under this scope at
        # teardown if a consumer's release never arrived.
        self._payload_scope = f"dag:{self._dag_id}"

        try:
            self._stages = self._plan()
            self._staged = [None] * len(self._output_readers)
            # Input writers grouped by projection key: one serialized body
            # (or one device payload entry) per key per execute, fanned to
            # every writer fed by that key.
            groups: dict = {}
            key_order: list = []
            for key, writer in self._input_writers:
                if key not in groups:
                    groups[key] = []
                    key_order.append(key)
                groups[key].append(writer)
            self._writers_by_key = [(k, groups[k]) for k in key_order]
            self._install()
        except BaseException:
            # Channels may already be allocated (validation interleaves with
            # edge allocation) and loops partially installed: release both so
            # a failed compile leaks nothing.
            self._torn_down = True
            self._release_channels(list(self._actor_addrs))
            raise
        self._monitor_stop = threading.Event()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="compiled-dag-monitor", daemon=True
        )
        self._monitor.start()

    # ------------------------------------------------------------------
    # Compilation: validate -> resolve actors -> allocate channels
    # ------------------------------------------------------------------

    def _plan(self) -> list[_Stage]:
        cw = self._cw
        order = self._root.topological_order()
        input_nodes = [n for n in order if isinstance(n, InputNode)]
        if any(isinstance(n, FunctionNode) for n in order):
            raise ValueError(
                "experimental_compile() supports actor-method nodes only; "
                "FunctionNode tasks keep the classic execute() path"
            )
        if len(input_nodes) != 1:
            raise ValueError(
                "a compiled DAG needs exactly one InputNode "
                f"(found {len(input_nodes)})"
            )
        method_nodes = [n for n in order if isinstance(n, ClassMethodNode)]
        if not method_nodes:
            raise ValueError("a compiled DAG needs at least one actor-method node")
        if isinstance(self._root, MultiOutputNode):
            terminals = list(self._root._bound_args[0])
            if not all(isinstance(t, ClassMethodNode) for t in terminals):
                raise ValueError(
                    "every MultiOutputNode output of a compiled DAG must be "
                    "an actor-method node"
                )
            self._multi_output = True
        elif isinstance(self._root, ClassMethodNode):
            terminals = [self._root]
            self._multi_output = False
        else:
            raise ValueError(
                f"a compiled DAG must terminate in an actor-method node or a "
                f"MultiOutputNode of them, not {type(self._root).__name__}"
            )

        # Resolve the actor gang (shared resolution with classic execute():
        # the per-DAG actor cache on ClassNode).
        stage_by_node: dict[int, _Stage] = {}
        stages: list[_Stage] = []
        for node in method_nodes:
            class_node = node._class_node
            if class_node._children():
                raise ValueError(
                    "compiled DAGs require static actor constructor arguments "
                    "(no DAG nodes bound into the ClassNode)"
                )
            handle = class_node.resolve_actor_handle()
            stage = _Stage(len(stages), node, handle.actor_id)
            stage_by_node[id(node)] = stage
            stages.append(stage)

        # Actor placement (address + node) for channel-mode decisions.
        actor_nodes: dict[str, str] = {}
        for stage in stages:
            aid = stage.actor_id
            if aid in self._actor_addrs:
                continue
            self._actor_addrs[aid] = tuple(cw._resolve_actor(aid))
            resp = cw.gcs.call("get_actor", {"actor_id": aid})
            if not resp.get("found"):
                raise ActorDiedError(f"actor {aid[:8]} not found during compile")
            actor_nodes[aid] = resp["info"].get("node_id") or ""
        cluster_nodes = cw.gcs.call("get_nodes").get("nodes", {})

        consumers = {s.sid: 0 for s in stages}

        def classify_arg(stage: _Stage, arg):
            """Build the wire arg spec for one top-level bound arg."""
            if isinstance(arg, (InputNode, InputAttributeNode)):
                key = arg._key if isinstance(arg, InputAttributeNode) else None
                desc = self._alloc_channel(
                    writer_node=cw.node_id,
                    reader_node=actor_nodes[stage.actor_id],
                    reader_addr=self._actor_addrs[stage.actor_id],
                    cluster_nodes=cluster_nodes,
                    label=f"input->{stage.label}",
                )
                self._input_writers.append((key, ChannelWriter(desc, cw)))
                stage.has_input = True
                return ["c", desc]
            if isinstance(arg, ClassMethodNode):
                producer = stage_by_node[id(arg)]
                desc = self._alloc_channel(
                    writer_node=actor_nodes[producer.actor_id],
                    reader_node=actor_nodes[stage.actor_id],
                    reader_addr=self._actor_addrs[stage.actor_id],
                    cluster_nodes=cluster_nodes,
                    label=f"{producer.label}->{stage.label}",
                )
                producer.out_descs.append(desc)
                self._actor_outputs.setdefault(producer.actor_id, []).append(
                    (producer.label, desc)
                )
                consumers[producer.sid] += 1
                stage.has_input = stage.has_input or producer.has_input
                return ["c", desc]
            if isinstance(arg, ClassNode):
                # An actor handle as a constant argument.
                return ["v", serialization.serialize(arg.resolve_actor_handle()).to_bytes()]
            if isinstance(arg, DAGNode):
                raise ValueError(
                    f"compiled DAGs cannot bind {type(arg).__name__} as a "
                    "stage argument"
                )
            return ["v", serialization.serialize(arg).to_bytes()]

        for stage in stages:
            node = stage.node
            top_level = [a for a in node._bound_args] + list(node._bound_kwargs.values())
            nested = [
                c
                for c in node._children()
                if c is not node._class_node and not any(c is a for a in top_level)
            ]
            if nested:
                raise ValueError(
                    f"stage {stage.label}: DAG nodes nested inside "
                    "lists/dicts/tuples are not supported by "
                    "experimental_compile(); bind them as top-level arguments"
                )
            stage.arg_specs = [classify_arg(stage, a) for a in node._bound_args]
            stage.kwarg_specs = {
                k: classify_arg(stage, v) for k, v in node._bound_kwargs.items()
            }
            if not stage.has_input:
                raise ValueError(
                    f"stage {stage.label} is not (transitively) fed by the "
                    "InputNode; a free-running stage would spin unboundedly"
                )

        # Driver-facing output channels, one per terminal occurrence.
        for t in terminals:
            stage = stage_by_node[id(t)]
            desc = self._alloc_channel(
                writer_node=actor_nodes[stage.actor_id],
                reader_node=cw.node_id,
                reader_addr=cw.address,
                cluster_nodes=cluster_nodes,
                label=f"{stage.label}->output",
            )
            stage.out_descs.append(desc)
            self._actor_outputs.setdefault(stage.actor_id, []).append(
                (stage.label, desc)
            )
            self._output_readers.append(ChannelReader(desc, cw))
            consumers[stage.sid] += 1
        dangling = [s.label for s in stages if consumers[s.sid] == 0]
        if dangling:
            raise ValueError(
                f"stage(s) {dangling} produce results nobody consumes; add "
                "them to a MultiOutputNode or drop them from the graph"
            )
        return stages

    def _alloc_channel(self, *, writer_node, reader_node, reader_addr,
                       cluster_nodes, label) -> dict:
        """One ring per edge. shm mode when both endpoints share a node's
        arena (allocated through that node's raylet); otherwise a
        descriptor with no arena — both endpoints take the RPC fallback."""
        cw = self._cw
        cid = os.urandom(12).hex()
        size = ring_bytes(self._num_slots, self._slot_size)
        arena = None
        offset = 0
        if writer_node == reader_node:
            if reader_node == cw.node_id:
                raylet, arena = cw.raylet, cw.store.arena.name
            else:
                info = cluster_nodes.get(reader_node) or {}
                arena = info.get("arena_name")
                raylet = (
                    cw._owner_client(tuple(info["address"]))
                    if arena and info.get("address")
                    else None
                )
            if arena and raylet is not None:
                # Short per-attempt ack, more retries: channel_create is
                # idempotent on the raylet (an existing ring is returned),
                # so a silently lost reply costs one 5s slice instead of a
                # 30s stall; transport exhaustion surfaces as the TYPED
                # channel error naming the node, not a bare TimeoutError.
                try:
                    resp = raylet.call(
                        "channel_create", {"channel_id": cid, "size": size},
                        timeout=5, retries=6,
                    )
                except Exception as e:
                    from ray_tpu.experimental.channel.channel import ChannelError

                    raise ChannelError(
                        f"could not allocate channel {label or cid[:8]} on "
                        f"node {reader_node[:8]}: {type(e).__name__}: {e}"
                    ) from e
                offset = resp["offset"]
                self._allocs.append((raylet, cid))
            else:
                arena = None
        desc = make_descriptor(
            cid,
            arena=arena,
            offset=offset,
            num_slots=self._num_slots,
            slot_size=self._slot_size,
            reader_addr=reader_addr,
            label=label,
        )
        self._all_descs.append(desc)
        return desc

    def _install(self):
        """Ship each actor its resident-loop program (stages in topo order)."""
        cw = self._cw
        by_actor: dict[str, list] = {}
        for stage in self._stages:
            by_actor.setdefault(stage.actor_id, []).append(
                {
                    "label": stage.label,
                    "hop_key": f"s{stage.sid}",
                    "method": stage.method,
                    "args": stage.arg_specs,
                    "kwargs": stage.kwarg_specs,
                    "outputs": stage.out_descs,
                }
            )
        for actor_id, stage_wires in by_actor.items():
            client = cw._owner_client(self._actor_addrs[actor_id])
            resp = client.call(
                "channel_loop_install",
                {"loop_id": self._dag_id, "stages": stage_wires},
                timeout=30,
            )
            if resp.get("error"):
                raise ValueError(
                    f"compiling DAG on actor {actor_id[:8]} failed: "
                    f"{resp['error']}"
                )

    # ------------------------------------------------------------------
    # Execution
    # ------------------------------------------------------------------

    def execute(self, *args, **kwargs) -> CompiledDAGRef:
        """Write the input channel(s); returns a CompiledDAGRef. Blocks
        (then raises ChannelTimeoutError) when ``max_buffered_results``
        iterations are in flight and unconsumed. Not thread-safe: one
        submitting thread per CompiledDAG."""
        if self._torn_down:
            raise ValueError("this CompiledDAG has been torn down")
        err = self._error
        if err is not None:
            raise err
        # Reserve space on EVERY input channel before writing ANY: a full
        # ring discovered halfway through the fan-out would otherwise leave
        # the written channels one iteration ahead of the rest, pairing
        # mismatched iterations forever after a retried execute().
        for _, writer in self._input_writers:
            writer.wait_writable(timeout=self._submit_timeout)
        # Full stamps under hop_timing, 1-in-N sampled otherwise — compiled
        # iterations feed the same production dispatch-latency metric as the
        # classic paths.
        hop = self._cw._hop_stamp_start() or None
        idx = self._next_idx
        from ray_tpu._private.core_worker import _maybe_jax_array

        for key, writers in self._writers_by_key:
            value = self._project_input(args, kwargs, key)
            if hop is not None:
                hop["ship"] = time.monotonic()
            if _maybe_jax_array(value):
                # A device-resident jax.Array must not be msgpack-serialized
                # through the host ring (a silent D2H copy per iteration):
                # the driver is the holder — route a descriptor slot and
                # stream the payload out of band (device_envelope).
                from ray_tpu.experimental.channel import device_envelope

                device_envelope.emit(
                    self._cw, value, writers, scope=self._payload_scope,
                    hop=hop, timeout=self._submit_timeout,
                )
                continue
            data = serialization.serialize(value).to_bytes()
            for writer in writers:
                writer.write(KIND_VALUE, data, hop, timeout=self._submit_timeout)
        self._next_idx += 1
        return CompiledDAGRef(self, idx)

    @staticmethod
    def _project_input(args, kwargs, key):
        if key is None:
            if len(args) == 1 and not kwargs:
                return args[0]
            return _DAGInputData(args, kwargs)
        if len(args) == 1 and not kwargs:
            value = args[0]
            try:
                return value[key]
            except (TypeError, KeyError, IndexError):
                if isinstance(key, str):
                    return getattr(value, key)
                raise
        return _DAGInputData(args, kwargs)[key]

    def _get_result(self, idx: int, timeout: float | None) -> tuple:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._consume_lock:
            while idx not in self._results:
                if self._torn_down:
                    raise ValueError("this CompiledDAG has been torn down")
                self._drain_next(deadline)
            return self._results.pop(idx)

    def _drain_next(self, deadline):
        """Read the next iteration's envelope from every output channel (in
        execution order) and record its outcome. Partially-consumed
        iterations stage in self._staged so a timeout raised halfway never
        loses a destructively-read envelope (the retry resumes where this
        attempt stopped instead of pairing mismatched iterations)."""
        for i, reader in enumerate(self._output_readers):
            if self._staged[i] is None:
                self._staged[i] = self._read_sliced(reader, deadline)
        # Device descriptor slots resolve out of band; the outcome is
        # memoized into the staged slot (resolution releases the consumer
        # pin on the holder — it must happen exactly once even when a
        # get(timeout=) expires while resolving a LATER output channel).
        for i, reader in enumerate(self._output_readers):
            kind, data, hop = self._staged[i]
            if kind != KIND_DEVICE:
                continue
            from ray_tpu.experimental.channel import device_envelope

            try:
                value = device_envelope.resolve(
                    self._cw, data, cid=reader.cid, seq=reader.last_seq,
                    gate=reader.gate, deadline=deadline,
                    consumer_release=not reader.shm,
                )
            except GetTimeoutError:
                raise  # staged slot keeps the unresolved envelope; retryable
            except ChannelClosedError:
                raise ValueError(
                    "this CompiledDAG was torn down while results were pending"
                ) from None
            except BaseException as e:  # noqa: BLE001 — typed loss/death
                self._staged[i] = (_RESOLVE_ERR, e, hop)
            else:
                self._staged[i] = (_RESOLVED, value, hop)
        envs, self._staged = self._staged, [None] * len(self._output_readers)
        seq = self._next_out_seq
        self._next_out_seq += 1
        error = None
        values = []
        hop_rec: dict = {}
        for kind, data, hop in envs:
            if hop:
                hop_rec.update(hop)
            if kind == _RESOLVED:
                values.append(data)
            elif kind == _RESOLVE_ERR:
                if error is None:
                    error = data
                values.append(None)
            elif kind == KIND_ERROR:
                err = serialization.deserialize(data)
                if error is None:
                    error = err
                values.append(None)
            else:
                values.append(serialization.deserialize(data))
        if hop_rec:
            hop_rec["owner_recv"] = hop_rec.get("owner_recv") or time.monotonic()
            hop_rec["wake"] = time.monotonic()
            self._cw.record_compiled_hop(
                {"path": "compiled", "name": f"dag-{self._dag_id[:6]}", "seq": seq, **hop_rec}
            )
        if error is not None:
            if isinstance(error, TaskError) and isinstance(error.cause, ActorDiedError):
                error = error.cause
            self._results[seq] = ("err", error)
        else:
            self._results[seq] = ("val", values if self._multi_output else values[0])
        if len(self._results) > self._num_slots:
            # Skipped refs would otherwise grow this buffer without bound,
            # silently defeating the max_buffered_results backpressure the
            # ring enforces (reference semantics: consuming out of order is
            # fine, abandoning results is an error).
            raise ValueError(
                f"more than max_buffered_results={self._num_slots} compiled "
                "results are buffered driver-side; get() earlier "
                "CompiledDAGRefs before executing further"
            )

    def _read_sliced(self, reader: ChannelReader, deadline):
        """Short read slices so a death detected by the monitor surfaces as
        its typed error even if poison delivery itself failed."""
        while True:
            try:
                return reader.read(timeout=_GET_SLICE_S)
            except ChannelTimeoutError:
                err = self._error
                if err is not None and reader.gate.sticky is None:
                    raise err
                if deadline is not None and time.monotonic() >= deadline:
                    raise GetTimeoutError(
                        "CompiledDAGRef.get() timed out"
                    ) from None
            except ChannelClosedError:
                raise ValueError(
                    "this CompiledDAG was torn down while results were pending"
                ) from None

    # ------------------------------------------------------------------
    # Failure propagation + teardown
    # ------------------------------------------------------------------

    def _monitor_loop(self):
        cw = self._cw
        while not self._monitor_stop.wait(0.25):
            if cw._shutdown:
                return  # driver exiting without teardown: nothing to watch
            for aid in list(self._actor_addrs):
                if aid in self._dead_actors:
                    continue
                try:
                    resp = cw.gcs.call("get_actor", {"actor_id": aid}, timeout=5)
                except Exception:
                    continue  # GCS hiccup: re-check next tick
                info = resp.get("info") if resp.get("found") else None
                state = (info or {}).get("state")
                if info is None or state in ("DEAD", "RESTARTING"):
                    cause = (info or {}).get("death_cause") or state or "actor gone"
                    self._on_actor_dead(aid, cause)

    def _on_actor_dead(self, actor_id: str, cause: str):
        """Plant typed-error poison through every channel the dead actor
        produced; downstream resident loops forward it edge-by-edge until
        it reaches the driver's output reader."""
        self._dead_actors.add(actor_id)
        stage_outputs = self._actor_outputs.get(actor_id, [])
        labels = sorted({label for label, _ in stage_outputs})
        err = ActorDiedError(
            f"compiled DAG stage(s) {labels} died: actor {actor_id[:8]} "
            f"({cause})",
            actor_id=actor_id,
        )
        with self._state_lock:
            if self._error is None:
                self._error = err
        env = pack_envelope(
            KIND_ERROR, serialization.serialize(err).to_bytes(), None
        )
        cw = self._cw
        for _, desc in stage_outputs:
            reader_addr = tuple(desc["reader_addr"])
            if reader_addr == tuple(cw.address):
                cw.channels.gate(desc["cid"]).poison(env)
                continue
            try:
                cw._owner_client(reader_addr).call(
                    "channel_poison", {"cid": desc["cid"], "env": env}, timeout=5
                )
            except Exception:
                logger.warning(
                    "poisoning channel %s after actor death failed",
                    desc["cid"][:8],
                )

    def teardown(self):
        """Stop the resident loops, close every channel (blocked readers and
        writers raise instead of hanging) and release the channel slots back
        to the arena. Idempotent."""
        with self._state_lock:
            if self._torn_down:
                return
            self._torn_down = True
        self._monitor_stop.set()
        self._release_channels(list(self._actor_addrs))
        # Reclaim driver-created channel payloads whose consumer releases
        # never arrived (dead stage, torn connection): no leaked device
        # buffers across teardown.
        from ray_tpu.experimental.device_object.manager import active_manager

        mgr = active_manager()
        if mgr is not None:
            mgr.reclaim_scope(self._payload_scope)
        if self._monitor.is_alive():
            self._monitor.join(timeout=2)

    def _release_channels(self, actor_ids):
        cw = self._cw
        # 1. Stop resident loops first so no endpoint is mid-slot while the
        # arena blocks are freed. A loop that cannot be CONFIRMED stopped
        # (stop timed out, or the worker is unreachable but not known dead)
        # forbids freeing: a still-running loop writing into a reallocated
        # arena block would corrupt an unrelated object for every reader on
        # the node — leaking the rings is the safe failure.
        confirmed = True
        for actor_id in actor_ids:
            if actor_id in self._dead_actors:
                continue  # loop died with the process; endpoints are gone
            try:
                resp = cw._owner_client(self._actor_addrs[actor_id]).call(
                    "channel_loop_stop", {"loop_id": self._dag_id}, timeout=20
                )
                if not resp.get("ok"):
                    confirmed = False
            except Exception:
                if not self._actor_gone(actor_id):
                    confirmed = False
        # 2. Close: shm rings get their closed word set (any still-blocked
        # local endpoint observes it within a poll); every reader gate is
        # closed so remote-mode endpoints unblock too.
        arena = cw.store.arena
        local_cids = []
        for desc in self._all_descs:
            if desc.get("arena") and desc["arena"] == getattr(arena, "name", None):
                struct.pack_into(
                    "<Q", arena.view, desc["offset"] + _OFF_CLOSED, 1
                )
            reader_addr = tuple(desc["reader_addr"])
            if reader_addr == tuple(cw.address):
                local_cids.append(desc["cid"])
            else:
                try:
                    cw._owner_client(reader_addr).call(
                        "channel_close", {"cid": desc["cid"]}, timeout=5
                    )
                except Exception:
                    pass
        cw.channels.drop(local_cids)
        # Eager payloads pushed at the driver that were never taken must
        # not sit in the inbox until the age sweep.
        for cid in local_cids:
            cw.p2p_inbox.purge_prefix(f"chdev/{cid}/")
        # 3. Release the arena blocks (no leaked shm) — only once every
        # live endpoint is confirmed out of them (the closed words set in
        # step 2 stop an unconfirmed loop within one poll, but "within one
        # poll" is not "now").
        if not confirmed:
            logger.warning(
                "a resident channel loop could not be confirmed stopped; "
                "leaking %d channel ring(s) instead of freeing memory a "
                "live loop may still write",
                len(self._allocs),
            )
            return
        for raylet, cid in self._allocs:
            try:
                raylet.call("channel_free", {"channel_id": cid}, timeout=10)
            except Exception:
                logger.warning("channel_free(%s) failed", cid[:8])
        self._allocs.clear()

    def _actor_gone(self, actor_id: str) -> bool:
        """True only when the GCS confirms the actor's process is gone (its
        channel endpoints died with it, so freeing their rings is safe)."""
        try:
            resp = self._cw.gcs.call("get_actor", {"actor_id": actor_id}, timeout=5)
        except Exception:
            return False  # unknowable: treat as live, leak instead of free
        info = resp.get("info") if resp.get("found") else None
        return info is None or info.get("state") in ("DEAD", "RESTARTING")

    def __del__(self):
        try:
            if not self._torn_down and not self._cw._shutdown:
                self.teardown()
        except Exception:
            pass
