"""ray_tpu.dag — lazy task/actor DAGs (reference: python/ray/dag/)."""

from ray_tpu.dag.dag_node import (  # noqa: F401
    ClassMethodNode,
    ClassNode,
    DAGNode,
    FunctionNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
)

__all__ = [
    "DAGNode",
    "FunctionNode",
    "ClassNode",
    "ClassMethodNode",
    "InputNode",
    "InputAttributeNode",
    "MultiOutputNode",
]
