"""ray_tpu.dag — lazy task/actor DAGs (reference: python/ray/dag/) plus
compiled execution graphs over shm channels (dag/compiled.py)."""

from ray_tpu.dag.compiled import CompiledDAG, CompiledDAGRef  # noqa: F401
from ray_tpu.dag.dag_node import (  # noqa: F401
    ClassMethodNode,
    ClassNode,
    DAGNode,
    FunctionNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
)

__all__ = [
    "CompiledDAG",
    "CompiledDAGRef",
    "DAGNode",
    "FunctionNode",
    "ClassNode",
    "ClassMethodNode",
    "InputNode",
    "InputAttributeNode",
    "MultiOutputNode",
]
