"""Lazy task/actor DAGs.

Analog of the reference's ray.dag (python/ray/dag/dag_node.py:23 DAGNode,
function_node.py / class_node.py / input_node.py): ``f.bind(x)`` builds a
graph without executing; ``dag.execute(*inputs)`` walks it, submitting each
function node as a task and each class node as an actor, passing ObjectRefs
straight through as downstream arguments so intermediate results flow through
the object store without a driver-side get.

Used by Serve's deployment graphs and by the workflow library's durable
executor.
"""

from __future__ import annotations

import threading


class DAGNode:
    """Abstract node. Holds bound args/kwargs which may contain other nodes."""

    def __init__(self, args: tuple, kwargs: dict):
        self._bound_args = tuple(args)
        self._bound_kwargs = dict(kwargs)

    # -- traversal ---------------------------------------------------------
    def _children(self):
        out = []

        def scan(v):
            if isinstance(v, DAGNode):
                out.append(v)
            elif isinstance(v, (list, tuple)):
                for x in v:
                    scan(x)
            elif isinstance(v, dict):
                for x in v.values():
                    scan(x)

        for a in self._bound_args:
            scan(a)
        for a in self._bound_kwargs.values():
            scan(a)
        return out

    def topological_order(self):
        """Deterministic post-order over the graph reachable from self."""
        seen = {}
        order = []

        def visit(node):
            if id(node) in seen:
                return
            seen[id(node)] = node
            for c in node._children():
                visit(c)
            order.append(node)

        visit(self)
        return order

    def _resolve(self, value, results):
        if isinstance(value, DAGNode):
            return results[id(value)]
        if isinstance(value, list):
            return [self._resolve(v, results) for v in value]
        if isinstance(value, tuple):
            return tuple(self._resolve(v, results) for v in value)
        if isinstance(value, dict):
            return {k: self._resolve(v, results) for k, v in value.items()}
        return value

    def _resolved_args(self, results):
        args = tuple(self._resolve(a, results) for a in self._bound_args)
        kwargs = {k: self._resolve(v, results) for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def _execute_impl(self, resolved_args, resolved_kwargs, ctx):
        raise NotImplementedError

    def experimental_compile(self, **kwargs):
        """Compile this static DAG of actor-method nodes for repeated
        zero-RPC dispatch over pre-allocated shm channels; returns a
        :class:`~ray_tpu.dag.compiled.CompiledDAG` (see dag/compiled.py)."""
        from ray_tpu.dag.compiled import CompiledDAG

        return CompiledDAG(self, **kwargs)

    def execute(self, *input_args, **input_kwargs):
        """Execute the DAG rooted at this node. Returns this node's result
        (an ObjectRef for function/method nodes, an ActorHandle for class
        nodes, a list for MultiOutputNode)."""
        ctx = {"input_args": input_args, "input_kwargs": input_kwargs}
        results = {}
        ctx["_results"] = results
        order = self.topological_order()
        if sum(1 for n in order if isinstance(n, InputNode)) > 1:
            raise RuntimeError("a DAG can have at most one InputNode")
        for node in order:
            args, kwargs = node._resolved_args(results)
            results[id(node)] = node._execute_impl(args, kwargs, ctx)
        return results[id(self)]


class FunctionNode(DAGNode):
    """A bound @remote function call (reference: dag/function_node.py)."""

    def __init__(self, remote_fn, args, kwargs, options=None):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn
        self._options = dict(options or {})

    def options(self, **opts):
        return FunctionNode(self._remote_fn, self._bound_args, self._bound_kwargs, {**self._options, **opts})

    def _execute_impl(self, args, kwargs, ctx):
        fn = self._remote_fn.options(**self._options) if self._options else self._remote_fn
        return fn.remote(*args, **kwargs)

    def __str__(self):
        return f"FunctionNode({self._remote_fn.underlying_function.__name__})"


class ClassNode(DAGNode):
    """A bound actor construction (reference: dag/class_node.py). The actor
    is created ONCE per ClassNode and cached: repeated ``dag.execute()``
    calls reuse the gang instead of spawning fresh actors per call (and
    ``experimental_compile()`` resolves the same cache, so a DAG compiled
    after a classic run binds the same actors). Only constructors whose
    bound args contain other DAG nodes — i.e. truly per-execution actors —
    keep the old create-per-execute behavior."""

    def __init__(self, actor_cls, args, kwargs, options=None):
        super().__init__(args, kwargs)
        self._actor_cls = actor_cls
        self._options = dict(options or {})
        self._cached_handle = None

    def options(self, **opts):
        return ClassNode(self._actor_cls, self._bound_args, self._bound_kwargs, {**self._options, **opts})

    def resolve_actor_handle(self, args=None, kwargs=None):
        """The per-DAG actor cache: create the actor on first resolution,
        return the same handle afterwards. Shared by classic execute() and
        the compiled-graph planner."""
        if self._cached_handle is None:
            cls = self._actor_cls.options(**self._options) if self._options else self._actor_cls
            self._cached_handle = cls.remote(
                *(self._bound_args if args is None else args),
                **(self._bound_kwargs if kwargs is None else kwargs),
            )
        return self._cached_handle

    def _execute_impl(self, args, kwargs, ctx):
        if self._children():
            # Constructor args flow from other DAG nodes: a fresh actor per
            # execution is the only correct reading — no cache.
            cls = self._actor_cls.options(**self._options) if self._options else self._actor_cls
            return cls.remote(*args, **kwargs)
        return self.resolve_actor_handle(args, kwargs)

    def __getattr__(self, method_name):
        if method_name.startswith("_"):
            raise AttributeError(method_name)
        return _UnboundClassMethod(self, method_name)


class _UnboundClassMethod:
    def __init__(self, class_node: ClassNode, method_name: str):
        self._class_node = class_node
        self._method_name = method_name

    def bind(self, *args, **kwargs):
        return ClassMethodNode(self._class_node, self._method_name, args, kwargs)


class ClassMethodNode(DAGNode):
    """A bound actor method call on a ClassNode's actor."""

    def __init__(self, class_node, method_name, args, kwargs):
        super().__init__(args, kwargs)
        self._class_node = class_node
        self._method_name = method_name

    def _children(self):
        return [self._class_node] + super()._children()

    def _execute_impl(self, args, kwargs, ctx):
        # topological_order guarantees the class node ran first; its handle
        # is what _resolve would give us, but the class node is not a bound
        # arg, so fetch it from ctx-scoped results via the resolved parent.
        handle = ctx["_results"][id(self._class_node)]
        return getattr(handle, self._method_name).remote(*args, **kwargs)

    def __str__(self):
        return f"ClassMethodNode({self._method_name})"


class InputNode(DAGNode):
    """The runtime input placeholder (reference: dag/input_node.py). Use as a
    context manager::

        with InputNode() as inp:
            dag = f.bind(inp)
        ray_tpu.get(dag.execute(5))
    """

    _local = threading.local()

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        if getattr(InputNode._local, "current", None) is not None:
            raise RuntimeError(
                "a DAG can have at most one InputNode; close the previous "
                "`with InputNode()` block first"
            )
        InputNode._local.current = self
        return self

    def __exit__(self, *exc):
        InputNode._local.current = None

    def _execute_impl(self, args, kwargs, ctx):
        in_args = ctx["input_args"]
        if len(in_args) == 1 and not ctx["input_kwargs"]:
            return in_args[0]
        return _DAGInputData(in_args, ctx["input_kwargs"])

    def __getattr__(self, key):
        if key.startswith("_"):
            raise AttributeError(key)
        return InputAttributeNode(self, key)

    def __getitem__(self, key):
        return InputAttributeNode(self, key)


class _DAGInputData:
    def __init__(self, args, kwargs):
        self.args = args
        self.kwargs = kwargs

    def __getitem__(self, key):
        if isinstance(key, int):
            return self.args[key]
        return self.kwargs[key]


class InputAttributeNode(DAGNode):
    """``inp[0]`` / ``inp.key`` — a projection of the runtime input."""

    def __init__(self, input_node: InputNode, key):
        super().__init__((input_node,), {})
        self._key = key

    def _execute_impl(self, args, kwargs, ctx):
        value = args[0]
        if isinstance(value, _DAGInputData):
            return value[self._key]
        # single positional input: subscript it, falling back to attribute
        try:
            return value[self._key]
        except (TypeError, KeyError, IndexError):
            if isinstance(self._key, str):
                return getattr(value, self._key)
            raise


class MultiOutputNode(DAGNode):
    """Groups several terminal nodes; execute() returns a list."""

    def __init__(self, outputs):
        super().__init__((list(outputs),), {})

    def _execute_impl(self, args, kwargs, ctx):
        return args[0]
