"""Preprocessor base.

Analog of the reference's ``ray.data.preprocessor.Preprocessor``
(python/ray/data/preprocessor.py): stateful fit over a Dataset, stateless
transform of Datasets and batches; fitted state rides inside AIR checkpoints
so Predictors can re-apply the same preprocessing at inference time.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from ray_tpu.data.dataset import Dataset


class PreprocessorNotFittedError(RuntimeError):
    pass


class Preprocessor:
    _is_fittable: bool = True

    def fit(self, ds: "Dataset") -> "Preprocessor":
        if self._is_fittable:
            self._fit(ds)
        self._fitted = True
        return self

    def fit_transform(self, ds: "Dataset") -> "Dataset":
        return self.fit(ds).transform(ds)

    def transform(self, ds: "Dataset") -> "Dataset":
        self._check_fitted()
        return ds.map_batches(self._transform_pandas_or_dict, batch_format="default")

    def transform_batch(self, batch: dict) -> dict:
        self._check_fitted()
        return self._transform_pandas_or_dict(batch)

    def _check_fitted(self):
        if self._is_fittable and not getattr(self, "_fitted", False):
            raise PreprocessorNotFittedError(
                f"{type(self).__name__} must be fit before transform"
            )

    # -- subclass hooks ------------------------------------------------
    def _fit(self, ds: "Dataset"):
        raise NotImplementedError

    def _transform_pandas_or_dict(self, batch: dict) -> dict:
        raise NotImplementedError
