"""Batch iteration with prefetching (reference:
python/ray/data/iterator.py DataIterator + _internal/block_batching/).

``iter_batches_from_refs`` pulls the next block ref while slicing the current
one into batches; ``DataIterator`` is the per-consumer view used by Train
(`session.get_dataset_shard`), including the shared-shard state behind
``streaming_split``.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Iterator, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import BlockAccessor


def iter_batches_from_refs(
    ref_iter,
    *,
    batch_size: Optional[int],
    batch_format: str = "numpy",
    prefetch_batches: int = 1,
    drop_last: bool = False,
    local_shuffle_buffer_size: Optional[int] = None,
    local_shuffle_seed: Optional[int] = None,
) -> Iterator[Any]:
    """Slice a stream of block refs into batches, prefetching blocks."""
    rng = np.random.default_rng(local_shuffle_seed)

    def fetch_blocks():
        # Real prefetch: background-thread gets overlap block transfer with
        # the consumer's compute (holding refs alone starts no fetch).
        from concurrent.futures import ThreadPoolExecutor

        depth = max(1, prefetch_batches)
        with ThreadPoolExecutor(max_workers=depth, thread_name_prefix="data-prefetch") as pool:
            window: collections.deque = collections.deque()
            for ref, _meta in ref_iter:
                window.append(pool.submit(ray_tpu.get, ref))
                while len(window) > depth:
                    yield window.popleft().result()
            while window:
                yield window.popleft().result()

    carry: Optional[Any] = None  # leftover table slice
    shuffle_buf: list = []

    def emit(table):
        acc = BlockAccessor.for_block(table)
        return acc.to_batch(batch_format)

    for block in fetch_blocks():
        table = block if carry is None else BlockAccessor.concat([carry, block])
        carry = None
        if local_shuffle_buffer_size:
            shuffle_buf.append(table)
            buffered = sum(t.num_rows for t in shuffle_buf)
            if buffered < local_shuffle_buffer_size:
                continue
            merged = BlockAccessor.concat(shuffle_buf)
            table = BlockAccessor.for_block(merged).random_shuffle(int(rng.integers(2**31)))
            shuffle_buf = []
        if batch_size is None:
            yield emit(table)
            continue
        acc = BlockAccessor.for_block(table)
        n = acc.num_rows()
        start = 0
        while n - start >= batch_size:
            yield emit(acc.slice(start, start + batch_size))
            start += batch_size
        if start < n:
            carry = acc.slice(start, n)

    if shuffle_buf:
        merged = BlockAccessor.concat(shuffle_buf + ([carry] if carry is not None else []))
        carry = BlockAccessor.for_block(merged).random_shuffle(int(rng.integers(2**31)))
    if carry is not None and BlockAccessor.for_block(carry).num_rows() > 0:
        if batch_size is None:
            yield emit(carry)
            return
        acc = BlockAccessor.for_block(carry)
        n = acc.num_rows()
        start = 0
        while n - start >= batch_size:
            yield emit(acc.slice(start, start + batch_size))
            start += batch_size
        if start < n and not drop_last:
            yield emit(acc.slice(start, n))


class _ShardState:
    """Shared execution state behind streaming_split: one executor run,
    bundles dealt to n consumers (reference: OutputSplitter).

    equal=True matters for SPMD gangs: if one rank sees more rows than
    another, a pjit training gang deadlocks at the shorter rank's epoch end.
    Bundles are dealt to the least-loaded shard (imbalance bounded by one
    block) and, at exhaustion, still-queued surplus is trimmed to the
    minimum assigned row count via remote slice tasks."""

    def __init__(self, dataset, n: int, equal: bool):
        self._dataset = dataset
        self._n = n
        self._equal = equal
        self._lock = threading.Lock()
        self._queues = [collections.deque() for _ in range(n)]
        self._assigned_rows = [0] * n
        self._source: Optional[Iterator] = None
        self._exhausted = False
        self._next_shard = 0
        self._trimmed = False
        self._trim_event = threading.Event()

    def _deal_one(self) -> bool:
        """Pull one bundle from the source and assign it. Lock held."""
        if self._source is None:
            self._source = self._dataset.iter_internal_refs()
        try:
            bundle = next(self._source)
        except StopIteration:
            self._exhausted = True
            return False
        if self._equal:
            target = min(range(self._n), key=lambda i: self._assigned_rows[i])
        else:
            target = self._next_shard
            self._next_shard = (self._next_shard + 1) % self._n
        self._queues[target].append(bundle)
        self._assigned_rows[target] += bundle[1].num_rows
        return True

    def _trim_to_equal(self):
        """Equalize assigned rows across shards at exhaustion. The remote
        slice round-trips run with the lock RELEASED (the plan — which
        bundles to drop/slice — is made and applied under the lock; only
        the slicing itself happens outside), so sibling consumers aren't
        stalled behind object-store calls."""
        from ray_tpu.data._internal.executor import _slice_block_task

        slice_jobs = []  # (shard, ref, keep)
        with self._lock:
            if self._trimmed:
                return
            self._trimmed = True
            floor = min(self._assigned_rows)
            for i in range(self._n):
                excess = self._assigned_rows[i] - floor
                while excess > 0 and self._queues[i]:
                    ref, meta = self._queues[i].pop()
                    if meta.num_rows <= excess:
                        excess -= meta.num_rows
                        self._assigned_rows[i] -= meta.num_rows
                        continue
                    slice_jobs.append((i, ref, meta.num_rows - excess))
                    self._assigned_rows[i] -= excess
                    excess = 0
                # Rows a shard already consumed beyond the floor can't be
                # clawed back; least-loaded dealing bounds that to < one
                # block when consumers pull concurrently.
        if not slice_jobs:
            self._trim_event.set()
            return
        pairs = [
            (i, ray_tpu.remote(num_returns=2)(_slice_block_task).remote(ref, 0, keep))
            for i, ref, keep in slice_jobs
        ]
        resolved = [(i, refs[0], ray_tpu.get(refs[1])) for i, refs in pairs]
        with self._lock:
            for i, ref, meta in resolved:
                self._queues[i].append((ref, meta))
        self._trim_event.set()

    def next_bundle(self, shard: int):
        while True:
            with self._lock:
                if self._queues[shard]:
                    return self._queues[shard].popleft()
                if self._exhausted:
                    if not self._equal or self._trim_event.is_set():
                        if self._queues[shard]:
                            continue
                        return None
                    need_trim = not self._trimmed
                else:
                    self._deal_one()
                    continue
            # Exhausted, equal-split: first consumer here runs the trim
            # (outside the lock); the rest wait for it to finish.
            if need_trim:
                self._trim_to_equal()
            else:
                self._trim_event.wait(timeout=300)


class DataIterator:
    """Per-consumer iterator handle (reference: data/iterator.py)."""

    def __init__(self, dataset=None, shard_state: Optional[_ShardState] = None, shard_index: int = 0):
        self._dataset = dataset
        self._shard_state = shard_state
        self._shard_index = shard_index
        # Bundles this shard has claimed from the shared state: replayed on
        # re-iteration so count()/multiple epochs see the same shard.
        self._claimed: list = []
        self._drained = False

    def _ref_iter(self):
        if self._shard_state is not None:
            yield from self._claimed
            while not self._drained:
                bundle = self._shard_state.next_bundle(self._shard_index)
                if bundle is None:
                    self._drained = True
                    return
                self._claimed.append(bundle)
                yield bundle
        else:
            yield from self._dataset.iter_internal_refs()

    def iter_batches(self, **kwargs) -> Iterator[Any]:
        kwargs.setdefault("batch_size", 256)
        kwargs.setdefault("batch_format", "numpy")
        return iter_batches_from_refs(self._ref_iter(), **kwargs)

    def iter_rows(self) -> Iterator[dict]:
        for ref, _ in self._ref_iter():
            yield from BlockAccessor.for_block(ray_tpu.get(ref)).iter_rows()

    def iter_jax_batches(self, *, batch_size: int = 256, drop_last: bool = True, sharding=None, dtypes: Optional[dict] = None, **kwargs):
        """Device-fed batches with one batch of transfer lookahead: batch
        i+1's host->device DMA is issued (async under jit workloads) while
        the consumer computes on batch i (reference feeds accelerators via
        the prefetching block batcher; lookahead is the TPU-idiomatic part)."""
        import jax

        def to_device(batch):
            out = {}
            for k, v in batch.items():
                if dtypes and k in dtypes:
                    v = v.astype(dtypes[k])
                out[k] = jax.device_put(v, sharding) if sharding is not None else jax.device_put(v)
            return out

        prev = None
        for batch in self.iter_batches(batch_size=batch_size, drop_last=drop_last, **kwargs):
            cur = to_device(batch)
            if prev is not None:
                yield prev
            prev = cur
        if prev is not None:
            yield prev

    def iter_torch_batches(self, *, batch_size: int = 256, device=None, **kwargs):
        import torch

        for batch in self.iter_batches(batch_size=batch_size, **kwargs):
            yield {k: torch.as_tensor(np.ascontiguousarray(v)).to(device or "cpu") for k, v in batch.items()}

    def materialize(self):
        from ray_tpu.data._internal.logical_plan import InputData
        from ray_tpu.data.dataset import Dataset

        bundles = list(self._ref_iter())
        ds = Dataset(InputData(name="InputData", input_op=None, bundles=bundles))
        ds._cached_bundles = bundles
        return ds

    def count(self) -> int:
        return sum(m.num_rows for _, m in self._ref_iter())
