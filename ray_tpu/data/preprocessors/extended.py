"""Extended preprocessor families.

Analogs of the reference's remaining preprocessor modules
(python/ray/data/preprocessors/): discretizer.py (uniform/custom K-bins),
hasher.py (FeatureHasher), normalizer.py (row-wise Normalizer),
tokenizer.py, vectorizer.py (Count/Hashing vectorizers), transformer.py
(PowerTransformer), scaler.py extras (MaxAbsScaler, RobustScaler), and
encoder.py extras (OrdinalEncoder, MultiHotEncoder).

Hash-based features use crc32 (stable across processes — Python's builtin
``hash`` is salted per interpreter and would scatter tokens differently on
every worker). RobustScaler fits percentiles from a bounded per-column
reservoir sample folded in one distributed aggregation pass.
"""

from __future__ import annotations

import re
import zlib
from typing import Callable, List, Optional

import numpy as np

from ray_tpu.data.aggregate import AggregateFn, Max, Min
from ray_tpu.data.preprocessor import Preprocessor
from ray_tpu.data.preprocessors import _safe_scale


def _stable_hash(token: str, buckets: int) -> int:
    return zlib.crc32(str(token).encode("utf-8")) % buckets


def default_tokenizer(text: str) -> List[str]:
    return [t for t in re.split(r"[^0-9a-zA-Z]+", str(text).lower()) if t]


class _Reservoir(AggregateFn):
    """Bounded uniform sample of one column (Vitter's algorithm R), merged
    across blocks — feeds driver-side percentile fits in one pass."""

    def __init__(self, on: str, k: int = 4096, seed: int = 0):
        def accumulate(state, row):
            sample, n = state
            v = row.get(on)
            if v is None:
                return state
            n += 1
            if len(sample) < k:
                return (sample + [float(v)], n)
            # RNG only on the (increasingly rare) replacement path — per-row
            # generator construction dominated the fit otherwise.
            rng = np.random.default_rng((seed + n) & 0xFFFFFFFF)
            j = int(rng.integers(0, n))
            if j < k:
                sample = list(sample)
                sample[j] = float(v)
            return (sample, n)

        def merge(a, b):
            sa, na = a
            sb, nb = b
            n = na + nb
            pooled = sa + sb
            if len(pooled) <= k:
                return (pooled, n)
            # Weighted union: each slot draws from a side with probability
            # proportional to that side's OBSERVED count — uniform choice
            # over the pooled values would overweight small blocks by the
            # ratio of their sampling rates.
            rng = np.random.default_rng((seed + n) & 0xFFFFFFFF)
            ia, ib = list(sa), list(sb)
            rng.shuffle(ia)
            rng.shuffle(ib)
            out = []
            for _ in range(k):
                pick_a = ia and (not ib or rng.random() < na / (na + nb))
                out.append(ia.pop() if pick_a else ib.pop())
            return (out, n)

        super().__init__(
            init=lambda key: ([], 0),
            accumulate=accumulate,
            merge=merge,
            finalize=lambda a: a[0],
            name=f"reservoir({on})",
        )


class MaxAbsScaler(Preprocessor):
    """x / max|x| per column (reference: scaler.py MaxAbsScaler)."""

    def __init__(self, columns: list):
        self.columns = list(columns)

    def _fit(self, ds):
        from ray_tpu.data.aggregate import AbsMax

        res = ds.aggregate(*[AbsMax(col) for col in self.columns])
        self.stats_ = {c: _safe_scale(res[f"abs_max({c})"]) for c in self.columns}

    def _transform_pandas_or_dict(self, batch: dict) -> dict:
        out = dict(batch)
        for col in self.columns:
            out[col] = np.asarray(batch[col], np.float64) / self.stats_[col]
        return out


class RobustScaler(Preprocessor):
    """(x - median) / IQR per column, quantiles from a one-pass reservoir
    sample (reference: scaler.py RobustScaler)."""

    def __init__(self, columns: list, *, quantile_range: tuple = (0.25, 0.75)):
        self.columns = list(columns)
        self.quantile_range = quantile_range

    def _fit(self, ds):
        res = ds.aggregate(*[_Reservoir(col) for col in self.columns])
        lo_q, hi_q = self.quantile_range
        self.stats_ = {}
        for col in self.columns:
            sample = np.asarray(res[f"reservoir({col})"], np.float64)
            if sample.size == 0:
                self.stats_[col] = (0.0, 1.0)
                continue
            med = float(np.median(sample))
            iqr = float(np.quantile(sample, hi_q) - np.quantile(sample, lo_q))
            self.stats_[col] = (med, _safe_scale(iqr))

    def _transform_pandas_or_dict(self, batch: dict) -> dict:
        out = dict(batch)
        for col in self.columns:
            med, iqr = self.stats_[col]
            out[col] = (np.asarray(batch[col], np.float64) - med) / iqr
        return out


class UniformKBinsDiscretizer(Preprocessor):
    """Equal-width binning into integer codes 0..bins-1 (reference:
    discretizer.py UniformKBinsDiscretizer)."""

    def __init__(self, columns: list, bins: int):
        self.columns = list(columns)
        self.bins = int(bins)

    def _fit(self, ds):
        aggs = []
        for col in self.columns:
            aggs += [Min(col), Max(col)]
        res = ds.aggregate(*aggs)
        self.edges_ = {}
        for col in self.columns:
            lo, hi = float(res[f"min({col})"]), float(res[f"max({col})"])
            self.edges_[col] = np.linspace(lo, hi, self.bins + 1)

    def _transform_pandas_or_dict(self, batch: dict) -> dict:
        out = dict(batch)
        for col in self.columns:
            edges = self.edges_[col]
            codes = np.digitize(np.asarray(batch[col], np.float64), edges[1:-1])
            out[col] = codes.astype(np.int64)
        return out


class CustomKBinsDiscretizer(Preprocessor):
    """Binning with caller-provided edges (reference: discretizer.py
    CustomKBinsDiscretizer)."""

    _is_fittable = False

    def __init__(self, columns: list, bin_edges: list):
        self.columns = list(columns)
        self.bin_edges = np.asarray(bin_edges, np.float64)

    def _transform_pandas_or_dict(self, batch: dict) -> dict:
        out = dict(batch)
        for col in self.columns:
            out[col] = np.digitize(
                np.asarray(batch[col], np.float64), self.bin_edges
            ).astype(np.int64)
        return out


class Normalizer(Preprocessor):
    """Row-wise normalization ACROSS the given columns (reference:
    normalizer.py): each row's [col...] vector is scaled to unit l1/l2/max
    norm."""

    _is_fittable = False

    def __init__(self, columns: list, norm: str = "l2"):
        if norm not in ("l1", "l2", "max"):
            raise ValueError("norm must be l1|l2|max")
        self.columns = list(columns)
        self.norm = norm

    def _transform_pandas_or_dict(self, batch: dict) -> dict:
        out = dict(batch)
        mat = np.stack(
            [np.asarray(batch[c], np.float64) for c in self.columns], axis=1
        )
        if self.norm == "l1":
            denom = np.abs(mat).sum(axis=1)
        elif self.norm == "l2":
            denom = np.sqrt((mat**2).sum(axis=1))
        else:
            denom = np.abs(mat).max(axis=1)
        denom = np.where(denom == 0, 1.0, denom)
        for i, c in enumerate(self.columns):
            out[c] = mat[:, i] / denom
        return out


class PowerTransformer(Preprocessor):
    """Box-Cox / Yeo-Johnson with a caller-provided power (reference:
    transformer.py PowerTransformer — power is an argument, not fitted)."""

    _is_fittable = False

    def __init__(self, columns: list, power: float, method: str = "yeo-johnson"):
        if method not in ("yeo-johnson", "box-cox"):
            raise ValueError("method must be yeo-johnson|box-cox")
        self.columns = list(columns)
        self.power = float(power)
        self.method = method

    def _transform_pandas_or_dict(self, batch: dict) -> dict:
        out = dict(batch)
        lmbda = self.power
        for col in self.columns:
            x = np.asarray(batch[col], np.float64)
            if self.method == "box-cox":
                out[col] = np.log(x) if lmbda == 0 else (x**lmbda - 1) / lmbda
            else:
                pos = x >= 0
                if lmbda == 0:
                    y_pos = np.log1p(np.where(pos, x, 0))
                else:
                    y_pos = ((np.where(pos, x, 0) + 1) ** lmbda - 1) / lmbda
                if lmbda == 2:
                    y_neg = -np.log1p(np.where(pos, 0, -x))
                else:
                    y_neg = -(((np.where(pos, 0, -x) + 1) ** (2 - lmbda) - 1) / (2 - lmbda))
                out[col] = np.where(pos, y_pos, y_neg)
        return out


class OrdinalEncoder(Preprocessor):
    """Each categorical column -> integer codes by sorted category order
    (reference: encoder.py OrdinalEncoder)."""

    def __init__(self, columns: list):
        self.columns = list(columns)

    def _fit(self, ds):
        self.categories_ = {c: sorted(ds.unique(c)) for c in self.columns}
        self._index = {
            c: {v: i for i, v in enumerate(vals)} for c, vals in self.categories_.items()
        }

    def _transform_pandas_or_dict(self, batch: dict) -> dict:
        out = dict(batch)
        for col in self.columns:
            index = self._index[col]
            try:
                out[col] = np.asarray(
                    [index[v] for v in np.asarray(batch[col]).tolist()], np.int64
                )
            except KeyError as e:
                raise ValueError(
                    f"OrdinalEncoder({col!r}): unseen value {e.args[0]!r}"
                ) from None
        return out


class MultiHotEncoder(Preprocessor):
    """Column of LISTS -> [N, num_classes] indicator matrix (reference:
    encoder.py MultiHotEncoder)."""

    def __init__(self, columns: list):
        self.columns = list(columns)

    def _fit(self, ds):
        self.classes_ = {}
        for col in self.columns:
            values = set()
            for row in ds.select_columns([col]).take_all():
                cell = row[col]
                if cell is None:
                    continue
                # Cells come back as lists OR numpy arrays depending on the
                # block lane; np truthiness is ambiguous, so iterate plainly.
                values.update(np.asarray(cell).tolist())
            self.classes_[col] = sorted(values)

    def _transform_pandas_or_dict(self, batch: dict) -> dict:
        out = dict(batch)
        for col in self.columns:
            classes = self.classes_[col]
            index = {v: i for i, v in enumerate(classes)}
            rows = batch[col]
            mat = np.zeros((len(rows), len(classes)), np.int64)
            for r, values in enumerate(rows):
                if values is None:
                    continue
                for v in np.asarray(values).tolist():
                    j = index.get(v)
                    if j is not None:
                        mat[r, j] = 1
            out[col] = mat
        return out


class Tokenizer(Preprocessor):
    """String column -> list of tokens (reference: tokenizer.py)."""

    _is_fittable = False

    def __init__(self, columns: list, tokenization_fn: Optional[Callable] = None):
        self.columns = list(columns)
        self.fn = tokenization_fn or default_tokenizer

    def _transform_pandas_or_dict(self, batch: dict) -> dict:
        out = dict(batch)
        for col in self.columns:
            out[col] = np.asarray(
                [self.fn(v) for v in np.asarray(batch[col]).tolist()], dtype=object
            )
        return out


class CountVectorizer(Preprocessor):
    """Text column -> per-token count columns ``<col>_<token>`` for the
    ``max_features`` most frequent tokens (reference: vectorizer.py)."""

    def __init__(self, columns: list, tokenization_fn: Optional[Callable] = None,
                 max_features: Optional[int] = None):
        self.columns = list(columns)
        self.fn = tokenization_fn or default_tokenizer
        self.max_features = max_features

    def _fit(self, ds):
        fn = self.fn

        class _TokenCounts(AggregateFn):
            def __init__(self, on):
                def accumulate(counts, row):
                    counts = dict(counts)
                    for t in fn(row.get(on) or ""):
                        counts[t] = counts.get(t, 0) + 1
                    return counts

                def merge(a, b):
                    out = dict(a)
                    for t, n in b.items():
                        out[t] = out.get(t, 0) + n
                    return out

                super().__init__(
                    init=lambda key: {},
                    accumulate=accumulate,
                    merge=merge,
                    finalize=lambda a: a,
                    name=f"tokens({on})",
                )

        res = ds.aggregate(*[_TokenCounts(col) for col in self.columns])
        self.vocabularies_ = {}
        for col in self.columns:
            counts = res[f"tokens({col})"]
            vocab = sorted(counts, key=lambda t: (-counts[t], t))
            if self.max_features:
                vocab = vocab[: self.max_features]
            self.vocabularies_[col] = sorted(vocab)

    def _transform_pandas_or_dict(self, batch: dict) -> dict:
        out = dict(batch)
        for col in self.columns:
            vocab = self.vocabularies_[col]
            index = {t: i for i, t in enumerate(vocab)}
            texts = np.asarray(batch[col]).tolist()
            mat = np.zeros((len(texts), len(vocab)), np.int64)
            for r, text in enumerate(texts):
                for t in self.fn(text or ""):
                    j = index.get(t)
                    if j is not None:
                        mat[r, j] += 1
            for i, t in enumerate(vocab):
                out[f"{col}_{t}"] = mat[:, i]
            del out[col]
        return out


class HashingVectorizer(Preprocessor):
    """Text column -> fixed ``num_features`` hashed count columns; no fit,
    no vocabulary state (reference: vectorizer.py HashingVectorizer)."""

    _is_fittable = False

    def __init__(self, columns: list, num_features: int,
                 tokenization_fn: Optional[Callable] = None):
        self.columns = list(columns)
        self.num_features = int(num_features)
        self.fn = tokenization_fn or default_tokenizer

    def _transform_pandas_or_dict(self, batch: dict) -> dict:
        out = dict(batch)
        for col in self.columns:
            texts = np.asarray(batch[col]).tolist()
            mat = np.zeros((len(texts), self.num_features), np.int64)
            for r, text in enumerate(texts):
                for t in self.fn(text or ""):
                    mat[r, _stable_hash(t, self.num_features)] += 1
            for i in range(self.num_features):
                out[f"{col}_hash_{i}"] = mat[:, i]
            del out[col]
        return out


class FeatureHasher(Preprocessor):
    """Hash (column name, value) pairs of the given columns into
    ``num_features`` buckets (reference: hasher.py FeatureHasher)."""

    _is_fittable = False

    def __init__(self, columns: list, num_features: int,
                 output_column_name: str = "hashed_features"):
        self.columns = list(columns)
        self.num_features = int(num_features)
        self.output_column_name = output_column_name

    def _transform_pandas_or_dict(self, batch: dict) -> dict:
        out = dict(batch)
        n = len(np.asarray(batch[self.columns[0]]))
        mat = np.zeros((n, self.num_features), np.float64)
        for col in self.columns:
            values = np.asarray(batch[col]).tolist()
            for r, v in enumerate(values):
                mat[r, _stable_hash(f"{col}={v}", self.num_features)] += 1
        for col in self.columns:
            del out[col]
        out[self.output_column_name] = mat
        return out
