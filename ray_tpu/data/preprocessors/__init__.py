"""Built-in preprocessors.

Analog of the reference's ray.data.preprocessors (python/ray/data/
preprocessors/{scaler.py,encoder.py,imputer.py,concatenator.py,
batch_mapper.py,chain.py}): scalers fitted via Dataset aggregates, categorical
encoders via unique(), imputation, column concatenation (the bridge to a
single feature matrix for MXU-friendly matmuls), arbitrary batch mapping, and
chaining.

Batches are dicts of numpy column arrays (this framework's default batch
format), so every transform is vectorized.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from ray_tpu.data.aggregate import AggregateFn, Max, Mean, Min, Std
from ray_tpu.data.preprocessor import Preprocessor


def _safe_scale(x: float) -> float:
    """Scale denominator: NaN (e.g. Std of a 1-row fit) and 0 both mean
    'don't scale', never 'emit NaN columns silently'."""
    return 1.0 if (x is None or x == 0 or not np.isfinite(x)) else float(x)


class StandardScaler(Preprocessor):
    """(x - mean) / std per column (reference: preprocessors/scaler.py)."""

    def __init__(self, columns: list):
        self.columns = list(columns)

    def _fit(self, ds):
        # One distributed aggregation pass for every column's mean+std.
        aggs = []
        for col in self.columns:
            aggs += [Mean(col), Std(col)]
        res = ds.aggregate(*aggs)
        self.stats_ = {
            col: (res[f"mean({col})"], _safe_scale(res[f"std({col})"]))
            for col in self.columns
        }

    def _transform_pandas_or_dict(self, batch: dict) -> dict:
        out = dict(batch)
        for col in self.columns:
            mean, std = self.stats_[col]
            out[col] = (np.asarray(batch[col], dtype=np.float64) - mean) / std
        return out


class MinMaxScaler(Preprocessor):
    """(x - min) / (max - min) per column."""

    def __init__(self, columns: list):
        self.columns = list(columns)

    def _fit(self, ds):
        aggs = []
        for col in self.columns:
            aggs += [Min(col), Max(col)]
        res = ds.aggregate(*aggs)
        self.stats_ = {}
        for col in self.columns:
            lo, hi = res[f"min({col})"], res[f"max({col})"]
            self.stats_[col] = (lo, _safe_scale(hi - lo))

    def _transform_pandas_or_dict(self, batch: dict) -> dict:
        out = dict(batch)
        for col in self.columns:
            lo, span = self.stats_[col]
            out[col] = (np.asarray(batch[col], dtype=np.float64) - lo) / span
        return out


class LabelEncoder(Preprocessor):
    """Column of categories -> integer codes (reference: encoder.py)."""

    def __init__(self, label_column: str):
        self.label_column = label_column

    def _fit(self, ds):
        self.classes_ = sorted(ds.unique(self.label_column))
        self._index = {v: i for i, v in enumerate(self.classes_)}

    def _transform_pandas_or_dict(self, batch: dict) -> dict:
        out = dict(batch)
        col = batch[self.label_column]
        codes = []
        for v in np.asarray(col).tolist():
            try:
                codes.append(self._index[v])
            except KeyError:
                raise ValueError(
                    f"LabelEncoder({self.label_column!r}): value {v!r} was not "
                    f"seen during fit (classes: {self.classes_})"
                ) from None
        out[self.label_column] = np.asarray(codes)
        return out


class OneHotEncoder(Preprocessor):
    """Categorical columns -> <col>_<value> indicator columns."""

    def __init__(self, columns: list):
        self.columns = list(columns)

    def _fit(self, ds):
        self.categories_ = {col: sorted(ds.unique(col)) for col in self.columns}

    def _transform_pandas_or_dict(self, batch: dict) -> dict:
        out = dict(batch)
        for col in self.columns:
            values = np.asarray(batch[col])
            for cat in self.categories_[col]:
                out[f"{col}_{cat}"] = (values == cat).astype(np.int64)
            del out[col]
        return out


class SimpleImputer(Preprocessor):
    """Fill NaNs with mean ("mean" strategy) or a constant."""

    def __init__(self, columns: list, strategy: str = "mean", fill_value=None):
        if strategy not in ("mean", "constant"):
            raise ValueError("strategy must be 'mean' or 'constant'")
        if strategy == "constant" and fill_value is None:
            raise ValueError("constant strategy requires fill_value")
        self.columns = list(columns)
        self.strategy = strategy
        self.fill_value = fill_value

    def _fit(self, ds):
        if self.strategy == "mean":
            # NaN-skipping distributed mean (plain Mean would be poisoned).
            res = ds.aggregate(*[_NanMean(col) for col in self.columns])
            self.fill_ = {col: res[f"nanmean({col})"] for col in self.columns}
        else:
            self.fill_ = {col: self.fill_value for col in self.columns}

    def _transform_pandas_or_dict(self, batch: dict) -> dict:
        out = dict(batch)
        for col in self.columns:
            arr = np.asarray(batch[col], dtype=np.float64)
            out[col] = np.where(np.isnan(arr), self.fill_[col], arr)
        return out


def _is_nan(v) -> bool:
    try:
        return bool(np.isnan(v))
    except TypeError:
        return False


class _NanMean(AggregateFn):
    """Mean over non-NaN/non-None values; 0.0 if every value is missing."""

    def __init__(self, on: str):
        def accumulate(a, row):
            v = row.get(on)
            if v is None or _is_nan(v):
                return a
            return (a[0] + float(v), a[1] + 1)

        super().__init__(
            init=lambda k: (0.0, 0),
            accumulate=accumulate,
            merge=lambda a, b: (a[0] + b[0], a[1] + b[1]),
            finalize=lambda a: a[0] / a[1] if a[1] else 0.0,
            name=f"nanmean({on})",
        )


class Concatenator(Preprocessor):
    """Concatenate feature columns into one 2-D matrix column — the layout
    jitted models want (one big array onto the MXU, not a dict of slivers)."""

    _is_fittable = False

    def __init__(self, columns: Optional[list] = None, output_column_name: str = "concat_out", dtype=np.float32, exclude: Optional[list] = None):
        self.columns = list(columns) if columns else None
        self.output_column_name = output_column_name
        self.dtype = dtype
        self.exclude = set(exclude or [])

    def _transform_pandas_or_dict(self, batch: dict) -> dict:
        cols = self.columns or [c for c in batch if c not in self.exclude]
        mats = []
        for c in cols:
            arr = np.asarray(batch[c], dtype=self.dtype)
            n = arr.shape[0] if arr.ndim else 0
            # reshape(0, -1) is a numpy error; empty blocks keep width 0.
            mats.append(arr.reshape(n, -1) if arr.size else arr.reshape(n, 0))
        out = {k: v for k, v in batch.items() if k not in cols}
        out[self.output_column_name] = np.concatenate(mats, axis=1) if mats else np.zeros((0, 0), self.dtype)
        return out


class BatchMapper(Preprocessor):
    """Arbitrary stateless batch UDF as a preprocessor."""

    _is_fittable = False

    def __init__(self, fn: Callable[[dict], dict]):
        self.fn = fn

    def _transform_pandas_or_dict(self, batch: dict) -> dict:
        return self.fn(batch)


class Chain(Preprocessor):
    """Sequential composition; fit_transform is applied stage by stage so
    later stages fit on earlier stages' output (reference: chain.py)."""

    def __init__(self, *preprocessors: Preprocessor):
        self.preprocessors = list(preprocessors)

    def fit(self, ds):
        for p in self.preprocessors[:-1]:
            ds = p.fit_transform(ds).materialize()
        if self.preprocessors:
            self.preprocessors[-1].fit(ds)
        self._fitted = True
        return self

    def fit_transform(self, ds):
        for p in self.preprocessors:
            ds = p.fit_transform(ds).materialize()
        self._fitted = True
        return ds

    def _transform_pandas_or_dict(self, batch: dict) -> dict:
        for p in self.preprocessors:
            batch = p.transform_batch(batch)
        return batch

    def _check_fitted(self):
        for p in self.preprocessors:
            p._check_fitted()


# Extended families (discretizers, hashers, vectorizers, tokenizer, extra
# scalers/encoders, row normalizer, power transform) live in their own
# module; imported last so they can use this module's helpers.
from ray_tpu.data.preprocessors.extended import (  # noqa: E402,F401
    CountVectorizer,
    CustomKBinsDiscretizer,
    FeatureHasher,
    HashingVectorizer,
    MaxAbsScaler,
    MultiHotEncoder,
    Normalizer,
    OrdinalEncoder,
    PowerTransformer,
    RobustScaler,
    Tokenizer,
    UniformKBinsDiscretizer,
)
