"""DatasetPipeline — epoch/window pipelining (analog of reference
python/ray/data/dataset_pipeline.py).

A thin user-facing surface over the existing streaming executor: a pipeline
is a *factory* of per-window Datasets, re-invoked per epoch, so nothing is
materialized beyond the window in flight —

    pipe = ray_tpu.data.range(10_000).window(blocks_per_window=4).repeat(3)
    for epoch_ds in pipe.iter_epochs():          # 3 epochs
        for batch in epoch_ds.iter_batches():    # windows stream through
            ...

``Dataset.window`` groups streamed block bundles into window Datasets;
``Dataset.repeat`` re-executes the (lazy) plan per epoch. Per-window
transforms (``map_batches`` etc.) are applied lazily to each window as it is
formed.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterator, Optional

from ray_tpu.data.dataset import Dataset
from ray_tpu.data._internal.logical_plan import InputData


def _windows_of(ds: Dataset, blocks_per_window: int) -> Iterator[Dataset]:
    """Stream the dataset's block bundles, grouping every ``blocks_per_window``
    into a window Dataset. Pulls from the streaming executor — the source is
    never materialized wholesale."""
    batch: list = []
    for bundle in ds.iter_internal_refs():
        batch.append(bundle)
        if len(batch) >= blocks_per_window:
            yield _window_dataset(batch)
            batch = []
    if batch:
        yield _window_dataset(batch)


def _window_dataset(bundles: list) -> Dataset:
    w = Dataset(InputData(name="InputData", input_op=None, bundles=list(bundles)))
    w._cached_bundles = list(bundles)
    return w


class DatasetPipeline:
    """A lazy sequence of window Datasets, optionally repeated for epochs.

    ``_make_windows`` is re-invoked per epoch, so a lazy source re-executes
    (fresh reads, bounded memory) rather than replaying a materialized copy.
    """

    def __init__(
        self,
        make_windows: Callable[[], Iterator[Dataset]],
        *,
        epochs: Optional[int] = 1,
        length: Optional[int] = None,
    ):
        self._make_windows = make_windows
        self._epochs = epochs  # None = repeat forever
        self._length = length  # windows per epoch, if known

    # -- construction --------------------------------------------------------

    @staticmethod
    def from_dataset(ds: Dataset, blocks_per_window: int) -> "DatasetPipeline":
        if blocks_per_window < 1:
            raise ValueError("blocks_per_window must be >= 1")
        return DatasetPipeline(lambda: _windows_of(ds, blocks_per_window))

    def repeat(self, times: Optional[int] = None) -> "DatasetPipeline":
        """Repeat the pipeline's windows for ``times`` epochs (None = forever).
        Each epoch re-invokes the window factory — a lazy plan re-executes."""
        if self._epochs not in (1, None) or (times is not None and times < 1):
            raise ValueError("repeat() takes times >= 1 and applies once")
        return DatasetPipeline(self._make_windows, epochs=times, length=self._length)

    # -- per-window transforms ----------------------------------------------

    def foreach_window(self, fn: Callable[[Dataset], Dataset]) -> "DatasetPipeline":
        make = self._make_windows

        def mapped():
            for w in make():
                yield fn(w)

        return DatasetPipeline(mapped, epochs=self._epochs, length=self._length)

    def map(self, fn, **kw) -> "DatasetPipeline":
        return self.foreach_window(lambda w: w.map(fn, **kw))

    def map_batches(self, fn, **kw) -> "DatasetPipeline":
        return self.foreach_window(lambda w: w.map_batches(fn, **kw))

    def filter(self, fn, **kw) -> "DatasetPipeline":
        return self.foreach_window(lambda w: w.filter(fn, **kw))

    def random_shuffle_each_window(self, *, seed: Optional[int] = None) -> "DatasetPipeline":
        return self.foreach_window(lambda w: w.random_shuffle(seed=seed))

    # -- iteration -----------------------------------------------------------

    def _epoch_iter(self) -> Iterator[Iterator[Dataset]]:
        count = itertools.count() if self._epochs is None else range(self._epochs)
        for _ in count:
            yield self._make_windows()

    def iter_epochs(self) -> Iterator["_EpochDataset"]:
        """One `_EpochDataset` per epoch — a Dataset-like view chaining that
        epoch's windows."""
        for windows in self._epoch_iter():
            yield _EpochDataset(windows)

    def iter_datasets(self) -> Iterator[Dataset]:
        """Every window Dataset across all epochs, in order."""
        for windows in self._epoch_iter():
            yield from windows

    def iter_rows(self) -> Iterator[dict]:
        for w in self.iter_datasets():
            yield from w.iter_rows()

    def iter_batches(self, **kw) -> Iterator[Any]:
        for w in self.iter_datasets():
            yield from w.iter_batches(**kw)

    def iter_jax_batches(self, **kw) -> Iterator[Any]:
        for w in self.iter_datasets():
            yield from w.iter_jax_batches(**kw)

    def iter_torch_batches(self, **kw) -> Iterator[Any]:
        for w in self.iter_datasets():
            yield from w.iter_torch_batches(**kw)

    def stats(self) -> str:
        return f"DatasetPipeline(epochs={self._epochs}, windows_per_epoch={self._length or 'unknown'})"


class _EpochDataset:
    """One epoch's windows, exposed with the Dataset iteration surface."""

    def __init__(self, windows: Iterator[Dataset]):
        self._windows = windows

    def iter_windows(self) -> Iterator[Dataset]:
        return self._windows

    def iter_rows(self) -> Iterator[dict]:
        for w in self._windows:
            yield from w.iter_rows()

    def iter_batches(self, **kw) -> Iterator[Any]:
        for w in self._windows:
            yield from w.iter_batches(**kw)

    def iter_jax_batches(self, **kw) -> Iterator[Any]:
        for w in self._windows:
            yield from w.iter_jax_batches(**kw)

    def iter_torch_batches(self, **kw) -> Iterator[Any]:
        for w in self._windows:
            yield from w.iter_torch_batches(**kw)
