"""DataContext — execution configuration (reference: python/ray/data/context.py)."""

from __future__ import annotations

import threading
from typing import Optional


class DataContext:
    _instance: Optional["DataContext"] = None
    _lock = threading.Lock()

    def __init__(self):
        self.default_parallelism: Optional[int] = None
        self.target_max_block_size: int = 128 * 1024 * 1024
        self.max_tasks_in_flight: Optional[int] = None
        self.preserve_order: bool = True
        # Push-based (3-stage map/merge/reduce) shuffle. Default off, like
        # the reference's RAY_DATA_PUSH_BASED_SHUFFLE: its reduced reducer
        # fan-in wins on wide multi-node shuffles, while the extra merge
        # tasks are overhead on a single host.
        self.use_push_based_shuffle: Optional[bool] = None

    @classmethod
    def get_current(cls) -> "DataContext":
        with cls._lock:
            if cls._instance is None:
                cls._instance = DataContext()
            return cls._instance
