"""Dataset — lazy, distributed, streaming-executable datasets.

Analog of the reference's Dataset (python/ray/data/dataset.py:168 —
map_batches:381, iter_batches:2877, materialize:3967): transforms append
logical ops to a lazy plan; consumption lowers the plan to the streaming
executor (blocks flow as object-store refs between ray_tpu tasks). The TPU
twist is `iter_jax_batches`, which yields device-resident (optionally
mesh-sharded) ``jax.Array`` batches.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Iterator, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data import aggregate as agg_mod
from ray_tpu.data._internal import shuffle as shuffle_mod
from ray_tpu.data._internal.executor import (
    ActorPoolStrategy,
    ExecutionContext,
    execute_streaming,
)
from ray_tpu.data._internal.logical_plan import (
    AllToAll,
    InputData,
    Limit,
    LogicalOp,
    MapTransform,
    Union as UnionOp,
    Zip as ZipOp,
)
from ray_tpu.data.block import BlockAccessor, BlockMetadata


def _batch_udf_to_block_fn(fn, batch_format, batch_size, fn_args, fn_kwargs):
    """Wrap a user batch UDF into Block -> Block."""

    def block_fn(block):
        acc = BlockAccessor.for_block(block)
        n = acc.num_rows()
        outs = []
        size = batch_size or max(n, 1)
        for start in range(0, max(n, 1), size):
            sub = acc.slice(start, min(start + size, n)) if n else block
            batch = BlockAccessor.for_block(sub).to_batch(batch_format)
            out = fn(batch, *fn_args, **fn_kwargs)
            outs.append(BlockAccessor.batch_to_block(out))
        return BlockAccessor.concat(outs)

    return block_fn


class Dataset:
    def __init__(self, plan: LogicalOp):
        self._plan = plan
        self._cached_bundles: Optional[list] = None

    # ------------------------------------------------------------------
    # Transforms (lazy)
    # ------------------------------------------------------------------
    def map_batches(
        self,
        fn: Callable,
        *,
        batch_size: Optional[int] = None,
        batch_format: str = "numpy",
        compute: Optional[ActorPoolStrategy] = None,
        fn_args: tuple = (),
        fn_kwargs: Optional[dict] = None,
        num_cpus: Optional[float] = None,
        num_tpus: Optional[float] = None,
        **ray_remote_args,
    ) -> "Dataset":
        """Apply a UDF over batches (reference: dataset.py:381)."""
        fn_kwargs = fn_kwargs or {}
        if num_cpus is not None:
            ray_remote_args["num_cpus"] = num_cpus
        if num_tpus is not None:
            ray_remote_args["num_tpus"] = num_tpus
        if isinstance(fn, type):
            # Callable class: runs on an actor pool with constructed state.
            compute = compute or ActorPoolStrategy()

            def block_fn(block, udf, _bf=batch_format, _bs=batch_size, _fa=fn_args, _fk=fn_kwargs):
                inner = _batch_udf_to_block_fn(udf, _bf, _bs, _fa, _fk)
                return inner(block)

            op = MapTransform(
                name="MapBatches",
                input_op=self._plan,
                block_fn=block_fn,
                compute=compute,
                ray_remote_args=ray_remote_args,
                fn_constructor=fn,
            )
            return Dataset(op)
        block_fn = _batch_udf_to_block_fn(fn, batch_format, batch_size, fn_args, fn_kwargs)
        op = MapTransform(
            name="MapBatches",
            input_op=self._plan,
            block_fn=block_fn,
            compute=compute,
            ray_remote_args=ray_remote_args,
        )
        return Dataset(op)

    def map(self, fn: Callable[[dict], dict], **ray_remote_args) -> "Dataset":
        def block_fn(block):
            rows = [fn(row) for row in BlockAccessor.for_block(block).iter_rows()]
            return BlockAccessor.batch_to_block(rows)

        return Dataset(MapTransform(name="Map", input_op=self._plan, block_fn=block_fn, ray_remote_args=ray_remote_args))

    def flat_map(self, fn: Callable[[dict], list], **ray_remote_args) -> "Dataset":
        def block_fn(block):
            rows = []
            for row in BlockAccessor.for_block(block).iter_rows():
                rows.extend(fn(row))
            return BlockAccessor.batch_to_block(rows)

        return Dataset(MapTransform(name="FlatMap", input_op=self._plan, block_fn=block_fn, ray_remote_args=ray_remote_args))

    def filter(self, fn: Callable[[dict], bool], **ray_remote_args) -> "Dataset":
        def block_fn(block):
            return BlockAccessor.for_block(block).filter_rows(fn)

        return Dataset(MapTransform(name="Filter", input_op=self._plan, block_fn=block_fn, ray_remote_args=ray_remote_args))

    def select_columns(self, cols: List[str]) -> "Dataset":
        return Dataset(MapTransform(name="Select", input_op=self._plan, block_fn=lambda b: BlockAccessor.for_block(b).select(cols)))

    def drop_columns(self, cols: List[str]) -> "Dataset":
        return Dataset(MapTransform(name="Drop", input_op=self._plan, block_fn=lambda b: BlockAccessor.for_block(b).drop(cols)))

    def rename_columns(self, mapping: dict) -> "Dataset":
        return Dataset(MapTransform(name="Rename", input_op=self._plan, block_fn=lambda b: BlockAccessor.for_block(b).rename(mapping)))

    def add_column(self, name: str, fn: Callable[[Any], Any]) -> "Dataset":
        def block_fn(block):
            import pyarrow as pa

            df_batch = BlockAccessor.for_block(block).to_batch("pandas")
            col = fn(df_batch)
            if name in block.column_names:
                block = BlockAccessor.for_block(block).drop([name])
            return block.append_column(name, pa.array(np.asarray(col)))

        return Dataset(MapTransform(name="AddColumn", input_op=self._plan, block_fn=block_fn))

    def random_sample(self, fraction: float, *, seed: Optional[int] = None) -> "Dataset":
        def block_fn(block):
            acc = BlockAccessor.for_block(block)
            rng = np.random.default_rng(seed)
            keep = np.nonzero(rng.random(acc.num_rows()) < fraction)[0]
            return acc.take_indices(keep)

        return Dataset(MapTransform(name="RandomSample", input_op=self._plan, block_fn=block_fn))

    def random_shuffle(self, *, seed: Optional[int] = None, num_blocks: Optional[int] = None) -> "Dataset":
        return Dataset(AllToAll(
            name="RandomShuffle",
            input_op=self._plan,
            bulk_fn=lambda bundles: shuffle_mod.random_shuffle(bundles, num_blocks, seed),
        ))

    def repartition(self, num_blocks: int) -> "Dataset":
        return Dataset(AllToAll(
            name="Repartition",
            input_op=self._plan,
            bulk_fn=lambda bundles: shuffle_mod.repartition(bundles, num_blocks),
        ))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return Dataset(AllToAll(
            name="Sort",
            input_op=self._plan,
            bulk_fn=lambda bundles: shuffle_mod.sort(bundles, key, descending),
        ))

    def limit(self, n: int) -> "Dataset":
        return Dataset(Limit(name="Limit", input_op=self._plan, limit=n))

    def union(self, *others: "Dataset") -> "Dataset":
        return Dataset(UnionOp(name="Union", input_op=self._plan, extra_inputs=[o._plan for o in others]))

    def zip(self, other: "Dataset") -> "Dataset":
        return Dataset(ZipOp(name="Zip", input_op=self._plan, other=other._plan))

    def window(self, *, blocks_per_window: int = 10) -> "DatasetPipeline":
        """Epoch/window pipelining (reference: data/dataset_pipeline.py):
        stream this dataset's blocks in windows of ``blocks_per_window``,
        each exposed as its own Dataset — nothing is materialized beyond
        the window in flight."""
        from ray_tpu.data.dataset_pipeline import DatasetPipeline

        return DatasetPipeline.from_dataset(self, blocks_per_window)

    def repeat(self, times: Optional[int] = None) -> "DatasetPipeline":
        """Repeat this dataset for ``times`` epochs (None = forever). A lazy
        plan re-executes per epoch — fresh reads, bounded memory."""
        from ray_tpu.data.dataset_pipeline import DatasetPipeline

        if times is not None and times < 1:
            raise ValueError("repeat() takes times >= 1 (or None for forever)")
        return DatasetPipeline(lambda: iter([self]), epochs=times)

    def groupby(self, key: Optional[str]) -> "GroupedData":
        from ray_tpu.data.grouped_data import GroupedData

        return GroupedData(self, key)

    # ------------------------------------------------------------------
    # Execution
    # ------------------------------------------------------------------
    def _execute(self) -> list:
        if self._cached_bundles is None:
            from ray_tpu.data._internal.executor import ExecutionContext

            ctx = ExecutionContext()
            self._cached_bundles = list(execute_streaming(self._plan, ctx))
            self._last_stats = ctx.dataset_stats
        return self._cached_bundles

    def iter_internal_refs(self) -> Iterator[tuple]:
        if self._cached_bundles is not None:
            yield from self._cached_bundles
        else:
            yield from execute_streaming(self._plan)

    def materialize(self) -> "Dataset":
        bundles = self._execute()
        out = Dataset(InputData(name="InputData", input_op=None, bundles=bundles))
        out._cached_bundles = bundles
        # ds.materialize().stats() must show the execution that produced it.
        out._last_stats = getattr(self, "_last_stats", None)
        return out

    def stats(self) -> str:
        """Per-operator execution summary (reference: DatasetStats,
        data/_internal/stats.py:117)."""
        bundles = self._execute()
        total = sum(m.num_rows for _, m in bundles)
        sz = sum(m.size_bytes for _, m in bundles)
        totals = f"Dataset: {len(bundles)} blocks, {total} rows, {sz} bytes"
        last = getattr(self, "_last_stats", None)
        if last is None or not last.op_stats:
            return totals
        return last.summary_string(totals)

    # ------------------------------------------------------------------
    # Consumption
    # ------------------------------------------------------------------
    def count(self) -> int:
        return sum(m.num_rows for _, m in self._execute())

    def num_blocks(self) -> int:
        return len(self._execute())

    def size_bytes(self) -> int:
        return sum(m.size_bytes for _, m in self._execute())

    def schema(self):
        for _, m in self._execute():
            if m.schema is not None:
                return m.schema
        return None

    def columns(self) -> Optional[list]:
        s = self.schema()
        return list(s.names) if s is not None else None

    def input_files(self) -> list:
        files: list = []
        for _, m in self._execute():
            files.extend(m.input_files or [])
        return sorted(set(files))

    def take(self, n: int = 20) -> List[dict]:
        out: List[dict] = []
        for ref, _meta in self.iter_internal_refs():
            block = ray_tpu.get(ref)
            for row in BlockAccessor.for_block(block).iter_rows():
                out.append({k: (v.item() if hasattr(v, "item") and getattr(v, "ndim", 1) == 0 else v) for k, v in row.items()})
                if len(out) >= n:
                    return out
        return out

    def take_all(self) -> List[dict]:
        return self.take(n=2**63 - 1)

    def take_batch(self, batch_size: int = 20, *, batch_format: str = "numpy"):
        for batch in self.iter_batches(batch_size=batch_size, batch_format=batch_format):
            return batch
        raise ValueError("empty dataset")

    def show(self, n: int = 20):
        for row in self.take(n):
            print(row)

    def iter_rows(self) -> Iterator[dict]:
        for ref, _ in self.iter_internal_refs():
            yield from BlockAccessor.for_block(ray_tpu.get(ref)).iter_rows()

    def iter_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        batch_format: str = "numpy",
        prefetch_batches: int = 1,
        drop_last: bool = False,
        local_shuffle_buffer_size: Optional[int] = None,
        local_shuffle_seed: Optional[int] = None,
    ) -> Iterator[Any]:
        from ray_tpu.data.iterator import iter_batches_from_refs

        yield from iter_batches_from_refs(
            self.iter_internal_refs(),
            batch_size=batch_size,
            batch_format=batch_format,
            prefetch_batches=prefetch_batches,
            drop_last=drop_last,
            local_shuffle_buffer_size=local_shuffle_buffer_size,
            local_shuffle_seed=local_shuffle_seed,
        )

    def iter_jax_batches(
        self,
        *,
        batch_size: int = 256,
        drop_last: bool = True,
        sharding=None,
        dtypes: Optional[dict] = None,
        **kwargs,
    ) -> Iterator[dict]:
        """Yield batches as device-resident ``jax.Array``s, optionally laid
        out under a ``NamedSharding`` (data-parallel batch sharding across a
        mesh). TPU-native analog of iter_torch_batches (dataset.py:3008)."""
        import jax

        for batch in self.iter_batches(batch_size=batch_size, batch_format="numpy", drop_last=drop_last, **kwargs):
            out = {}
            for k, v in batch.items():
                if dtypes and k in dtypes:
                    v = v.astype(dtypes[k])
                out[k] = jax.device_put(v, sharding) if sharding is not None else jax.device_put(v)
            yield out

    def iter_torch_batches(self, *, batch_size: int = 256, drop_last: bool = False, device=None, **kwargs) -> Iterator[dict]:
        import torch

        for batch in self.iter_batches(batch_size=batch_size, batch_format="numpy", drop_last=drop_last, **kwargs):
            yield {k: torch.as_tensor(np.ascontiguousarray(v)).to(device or "cpu") for k, v in batch.items()}

    def to_pandas(self, limit: Optional[int] = None):
        import pandas as pd

        frames = []
        n = 0
        for ref, _ in self.iter_internal_refs():
            df = BlockAccessor.for_block(ray_tpu.get(ref)).to_pandas()
            frames.append(df)
            n += len(df)
            if limit is not None and n >= limit:
                break
        if not frames:
            return pd.DataFrame()
        out = pd.concat(frames, ignore_index=True)
        return out.head(limit) if limit is not None else out

    def to_arrow_refs(self) -> list:
        return [ref for ref, _ in self._execute()]

    def to_numpy_refs(self) -> list:
        def conv(block):
            return BlockAccessor.for_block(block).to_numpy()

        return [ray_tpu.remote(num_returns=1)(conv).remote(ref) for ref, _ in self._execute()]

    # ------------------------------------------------------------------
    # Aggregation
    # ------------------------------------------------------------------
    def aggregate(self, *aggs) -> dict:
        bundles = self._execute()
        out = shuffle_mod.hash_aggregate(bundles, None, list(aggs))
        rows = list(BlockAccessor.for_block(ray_tpu.get(out[0][0])).iter_rows())
        row = rows[0] if rows else {}
        # Unwrap numpy SCALARS only — an aggregate may legitimately return
        # an array/list (e.g. a reservoir sample), where .item() throws.
        return {
            k: (v.item() if hasattr(v, "item") and getattr(v, "size", 1) == 1 else v)
            for k, v in row.items()
        }

    def sum(self, on: str):
        return self.aggregate(agg_mod.Sum(on))[f"sum({on})"]

    def min(self, on: str):
        return self.aggregate(agg_mod.Min(on))[f"min({on})"]

    def max(self, on: str):
        return self.aggregate(agg_mod.Max(on))[f"max({on})"]

    def mean(self, on: str):
        return self.aggregate(agg_mod.Mean(on))[f"mean({on})"]

    def std(self, on: str, ddof: int = 1):
        return self.aggregate(agg_mod.Std(on, ddof))[f"std({on})"]

    def unique(self, column: str) -> list:
        seen = set()
        for ref, _ in self.iter_internal_refs():
            vals = BlockAccessor.for_block(ray_tpu.get(ref)).to_numpy([column])[column]
            seen.update(v.item() if hasattr(v, "item") else v for v in vals)
        return sorted(seen)

    # ------------------------------------------------------------------
    # Splitting (Train ingest)
    # ------------------------------------------------------------------
    def split(self, n: int, *, equal: bool = False, locality_hints=None) -> List["Dataset"]:
        bundles = self._execute()
        total = sum(m.num_rows for _, m in bundles)
        if equal:
            per = total // n
            sizes = [per] * n
        else:
            per = (total + n - 1) // n
            sizes = [min(per, max(0, total - i * per)) for i in range(n)]
        from ray_tpu.data._internal.executor import _resplit

        outs = []
        flat = _resplit(bundles, [s for s in sizes if s > 0])
        it = iter(flat)
        for s in sizes:
            if s <= 0:
                outs.append(Dataset(InputData(name="InputData", input_op=None, bundles=[])))
            else:
                b = next(it)
                outs.append(Dataset(InputData(name="InputData", input_op=None, bundles=[b])))
        return outs

    def split_at_indices(self, indices: List[int]) -> List["Dataset"]:
        bundles = self._execute()
        total = sum(m.num_rows for _, m in bundles)
        points = [0] + list(indices) + [total]
        sizes = [points[i + 1] - points[i] for i in range(len(points) - 1)]
        from ray_tpu.data._internal.executor import _resplit

        flat = _resplit(bundles, [max(s, 0) for s in sizes])
        return [Dataset(InputData(name="InputData", input_op=None, bundles=[b])) for b in flat]

    def split_proportionately(self, proportions: List[float]) -> List["Dataset"]:
        total = self.count()
        indices, acc = [], 0.0
        for p in proportions:
            acc += p
            indices.append(int(total * acc))
        return self.split_at_indices(indices)

    def train_test_split(self, test_size: float, *, shuffle: bool = False, seed: Optional[int] = None):
        ds = self.random_shuffle(seed=seed) if shuffle else self
        train, test = ds.split_proportionately([1.0 - test_size])
        return train, test

    def streaming_split(self, n: int, *, equal: bool = True, locality_hints=None) -> list:
        """Per-consumer iterators over disjoint shards (reference:
        dataset.py streaming_split via OutputSplitter). equal=True re-chunks
        to exactly-equal row counts (SPMD consumers lockstep-iterate, so
        uneven shards would desync collectives); equal=False deals bundles
        round-robin without materializing."""
        from ray_tpu.data.iterator import DataIterator, _ShardState

        if equal:
            parts = self.split(n, equal=True)
            return [DataIterator(dataset=p) for p in parts]
        state = _ShardState(self, n, equal)
        return [DataIterator(shard_state=state, shard_index=i) for i in range(n)]

    def iterator(self):
        from ray_tpu.data.iterator import DataIterator

        return DataIterator(dataset=self)

    # ------------------------------------------------------------------
    # Writes
    # ------------------------------------------------------------------
    def _write(self, path: str, write_one: Callable, extension: str):
        import os

        os.makedirs(path, exist_ok=True)

        def task(block, i):
            fname = os.path.join(path, f"part-{i:05d}.{extension}")
            write_one(block, fname)
            return fname

        refs = [
            ray_tpu.remote(num_returns=1)(task).remote(ref, i)
            for i, (ref, _) in enumerate(self.iter_internal_refs())
        ]
        return ray_tpu.get(refs)

    def write_parquet(self, path: str):
        def write_one(block, fname):
            import pyarrow.parquet as pq

            pq.write_table(block, fname)

        return self._write(path, write_one, "parquet")

    def write_webdataset(self, path: str):
        """One .tar shard per block; rows become key-prefixed files decoded
        back by read_webdataset (reference: write_webdataset)."""
        def write_one(block, fname):
            import tarfile

            from ray_tpu.data.block import BlockAccessor
            from ray_tpu.data.datasource.webdataset_datasource import write_sample

            with tarfile.open(fname, "w") as tf:
                for n, row in enumerate(BlockAccessor.for_block(block).iter_rows()):
                    key = str(row.get("__key__", f"{n:08d}"))
                    write_sample(tf, key, row)

        return self._write(path, write_one, "tar")

    def write_csv(self, path: str):
        def write_one(block, fname):
            import pyarrow.csv as pacsv

            pacsv.write_csv(block, fname)

        return self._write(path, write_one, "csv")

    def write_json(self, path: str):
        def write_one(block, fname):
            BlockAccessor.for_block(block).to_pandas().to_json(fname, orient="records", lines=True)

        return self._write(path, write_one, "json")

    def write_numpy(self, path: str, column: str):
        def write_one(block, fname):
            np.save(fname, BlockAccessor.for_block(block).to_numpy([column])[column])

        return self._write(path, write_one, "npy")

    def write_sql(self, table: str, connection_factory: Callable) -> int:
        """Insert every row into a SQL table via DB-API 2.0 connections
        (reference: Dataset.write_sql). Connections are created INSIDE the
        write tasks — pass a factory, not a live handle. SQLite note: its
        writer lock serializes concurrent INSERTs, so blocks write from
        parallel tasks but commit sequentially."""
        from ray_tpu.data.datasource.sql_datasource import write_sql_block

        refs = [
            ray_tpu.remote(num_returns=1)(write_sql_block).remote(
                ref, table, connection_factory
            )
            for ref, _ in self.iter_internal_refs()
        ]
        return sum(ray_tpu.get(refs))

    def __repr__(self):
        return f"Dataset(plan={self._plan.name})"
