"""Dataset creation APIs (reference: python/ray/data/read_api.py —
range:~, from_items, read_parquet:527, etc.)."""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data._internal.logical_plan import InputData, Read
from ray_tpu.data.block import BlockAccessor
from ray_tpu.data.context import DataContext
from ray_tpu.data.dataset import Dataset
from ray_tpu.data.datasource.datasource import (
    BinaryDatasource,
    CSVDatasource,
    Datasource,
    ImageDatasource,
    JSONDatasource,
    NumpyDatasource,
    ParquetDatasource,
    RangeDatasource,
    ReadTask,
    TextDatasource,
    TFRecordsDatasource,
)


def _default_parallelism(override: Optional[int]) -> int:
    if override is not None and override > 0:
        return override
    ctx = DataContext.get_current()
    if ctx.default_parallelism:
        return ctx.default_parallelism
    try:
        return max(2, int(ray_tpu.cluster_resources().get("CPU", 4)))
    except Exception:
        return 4


def read_datasource(datasource: Datasource, *, parallelism: int = -1, ray_remote_args: Optional[dict] = None) -> Dataset:
    tasks = datasource.get_read_tasks(_default_parallelism(parallelism if parallelism > 0 else None))
    return Dataset(Read(name="Read", input_op=None, read_tasks=tasks, ray_remote_args=ray_remote_args or {}))


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001 - reference name
    return read_datasource(RangeDatasource(n), parallelism=parallelism)


def read_sql(
    sql: str,
    connection_factory,
    *,
    parallelism: int = -1,
    shard_column: Optional[str] = None,
    shard_bounds: Optional[tuple] = None,
) -> Dataset:
    """Load a SQL query's results (reference: read_api.read_sql over DB-API
    connections; sqlite3 works out of the box). With ``shard_column`` (an
    integer column) the query is range-partitioned into parallel read
    tasks; otherwise it runs as one task."""
    from ray_tpu.data.datasource.sql_datasource import SQLDatasource

    return read_datasource(
        SQLDatasource(sql, connection_factory, shard_column, shard_bounds),
        parallelism=parallelism if shard_column is not None else 1,
    )


def range_tensor(n: int, *, shape: tuple = (1,), parallelism: int = -1) -> Dataset:
    ds = range(n, parallelism=parallelism)

    def to_tensor(batch):
        ids = batch["id"]
        data = np.broadcast_to(ids.reshape((-1,) + (1,) * len(shape)), (len(ids),) + tuple(shape)).copy()
        return {"data": data}

    return ds.map_batches(to_tensor)


def from_items(items: List[Any], *, parallelism: int = -1) -> Dataset:
    if items and not isinstance(items[0], dict):
        items = [{"item": x} for x in items]
    par = max(1, min(_default_parallelism(parallelism if parallelism > 0 else None), max(len(items), 1)))
    chunks = np.array_split(np.arange(len(items)), par)
    bundles = []
    for c in chunks:
        if len(c) == 0:
            continue
        block = BlockAccessor.batch_to_block([items[i] for i in c])
        ref = ray_tpu.put(block)
        bundles.append((ref, BlockAccessor.for_block(block).get_metadata()))
    return Dataset(InputData(name="InputData", input_op=None, bundles=bundles))


def from_pandas(dfs) -> Dataset:
    if not isinstance(dfs, list):
        dfs = [dfs]
    bundles = []
    for df in dfs:
        block = BlockAccessor.batch_to_block(df)
        bundles.append((ray_tpu.put(block), BlockAccessor.for_block(block).get_metadata()))
    return Dataset(InputData(name="InputData", input_op=None, bundles=bundles))


def from_numpy(arrays, column: str = "data") -> Dataset:
    if not isinstance(arrays, list):
        arrays = [arrays]
    bundles = []
    for arr in arrays:
        block = BlockAccessor.batch_to_block({column: arr})
        bundles.append((ray_tpu.put(block), BlockAccessor.for_block(block).get_metadata()))
    return Dataset(InputData(name="InputData", input_op=None, bundles=bundles))


def from_arrow(tables) -> Dataset:
    if not isinstance(tables, list):
        tables = [tables]
    bundles = []
    for t in tables:
        bundles.append((ray_tpu.put(t), BlockAccessor.for_block(t).get_metadata()))
    return Dataset(InputData(name="InputData", input_op=None, bundles=bundles))


def read_parquet(paths, *, columns: Optional[list] = None, parallelism: int = -1, **kwargs) -> Dataset:
    if "meta_provider" not in kwargs:
        from ray_tpu.data.datasource.partitioning import ParquetMetadataProvider

        # Footer-only row counts/sizes: exact progress + memory accounting
        # without reading data pages.
        kwargs["meta_provider"] = ParquetMetadataProvider()
    return read_datasource(ParquetDatasource(paths, columns=columns, **kwargs), parallelism=parallelism)


def read_csv(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return read_datasource(CSVDatasource(paths, **kwargs), parallelism=parallelism)


def read_json(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return read_datasource(JSONDatasource(paths, **kwargs), parallelism=parallelism)


def read_numpy(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return read_datasource(NumpyDatasource(paths, **kwargs), parallelism=parallelism)


def read_text(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return read_datasource(TextDatasource(paths, **kwargs), parallelism=parallelism)


def read_binary_files(paths, *, include_paths: bool = False, parallelism: int = -1, **kwargs) -> Dataset:
    return read_datasource(BinaryDatasource(paths, include_paths=include_paths, **kwargs), parallelism=parallelism)


def read_images(paths, *, size: Optional[tuple] = None, mode: str = "RGB", include_paths: bool = False, parallelism: int = -1, **kwargs) -> Dataset:
    return read_datasource(ImageDatasource(paths, size=size, mode=mode, include_paths=include_paths, **kwargs), parallelism=parallelism)


def read_tfrecords(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return read_datasource(TFRecordsDatasource(paths, **kwargs), parallelism=parallelism)


def read_webdataset(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    """Read WebDataset .tar shards (reference: read_webdataset): samples
    are key-prefixed file groups inside each shard, decoded by extension."""
    from ray_tpu.data.datasource.webdataset_datasource import WebDatasetDatasource

    return read_datasource(WebDatasetDatasource(paths, **kwargs), parallelism=parallelism)


def read_mongo(uri: str, database: str, collection: str, *, pipeline=None,
               parallelism: int = -1, **kwargs) -> Dataset:
    """Read a MongoDB collection, range-partitioned into parallel tasks
    (reference: read_mongo; requires pymongo unless a collection_factory
    is injected)."""
    from ray_tpu.data.datasource.mongo_datasource import MongoDatasource

    return read_datasource(
        MongoDatasource(uri, database, collection, pipeline=pipeline, **kwargs),
        parallelism=parallelism,
    )
