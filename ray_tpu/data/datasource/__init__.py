from ray_tpu.data.datasource.datasource import (  # noqa: F401
    BinaryDatasource,
    CSVDatasource,
    Datasource,
    FileBasedDatasource,
    ImageDatasource,
    JSONDatasource,
    NumpyDatasource,
    ParquetDatasource,
    RangeDatasource,
    ReadTask,
    TextDatasource,
    TFRecordsDatasource,
)
