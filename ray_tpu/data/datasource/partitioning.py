"""Path partitioning + file-metadata providers.

Analog of the reference's python/ray/data/datasource/partitioning.py:34
(``Partitioning`` — hive and dir path styles) and file_meta_provider.py:20
(``FileMetadataProvider`` — size/row-count prefetch feeding BlockMetadata
and parallelism autodetection). Partition values parse from the PATH, so
pruning with ``partition_filter`` happens before any file is opened.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional

from ray_tpu.data.block import BlockMetadata


class Partitioning:
    """Describes how partition fields are encoded in file paths.

    - ``style="hive"``: ``.../year=2024/country=fr/part-0.parquet`` — field
      names come from the path itself.
    - ``style="dir"``: ``.../2024/fr/part-0.parquet`` with
      ``field_names=["year", "country"]`` — positional directories under
      ``base_dir``.

    Values are strings by default (matching the reference); pass
    ``field_types={"year": int}`` to cast specific fields.
    """

    def __init__(self, style: str = "hive", *, base_dir: Optional[str] = None,
                 field_names: Optional[List[str]] = None,
                 field_types: Optional[Dict[str, Callable]] = None):
        if style not in ("hive", "dir"):
            raise ValueError(f"unknown partitioning style {style!r} (hive|dir)")
        if style == "dir" and not field_names:
            raise ValueError("style='dir' requires field_names")
        if style == "dir" and not base_dir:
            # Without an anchor the leading path segments would zip against
            # field_names (e.g. year='' from the root slash) — wrong values
            # with no error.
            raise ValueError("style='dir' requires base_dir")
        self.style = style
        self.base_dir = os.path.normpath(base_dir) if base_dir else None
        self.field_names = list(field_names or [])
        self.field_types = dict(field_types or {})

    def _rel_dirs(self, path: str) -> List[str]:
        d = os.path.dirname(os.path.normpath(path))
        if self.base_dir:
            rel = os.path.relpath(d, self.base_dir)
            if rel.startswith(".."):
                return []
            if rel == ".":
                return []
            return rel.split(os.sep)
        return d.split(os.sep)

    def parse(self, path: str) -> Dict[str, object]:
        """Extract partition fields from one file path."""
        parts = self._rel_dirs(path)
        out: Dict[str, object] = {}
        if self.style == "hive":
            for seg in parts:
                if "=" in seg:
                    k, v = seg.split("=", 1)
                    out[k] = v
            if self.field_names:
                out = {k: v for k, v in out.items() if k in self.field_names}
        else:
            # dir style: positional under base_dir.
            for name, seg in zip(self.field_names, parts):
                out[name] = seg
        for k, cast in self.field_types.items():
            if k in out:
                out[k] = cast(out[k])
        return out


class FileMetadataProvider:
    """Supplies BlockMetadata for a group of input files WITHOUT reading
    their contents (reference: file_meta_provider.py:20). The streaming
    executor uses size/row estimates for memory budgeting and the read
    layer for parallelism autodetection."""

    def get_metadata(self, paths: List[str]) -> BlockMetadata:
        raise NotImplementedError


class DefaultFileMetadataProvider(FileMetadataProvider):
    """os.stat sizes; row counts unknown."""

    def get_metadata(self, paths: List[str]) -> BlockMetadata:
        size = 0
        for p in paths:
            try:
                size += os.path.getsize(p)
            except OSError:
                pass
        return BlockMetadata(num_rows=-1, size_bytes=size, input_files=list(paths))


class FastFileMetadataProvider(FileMetadataProvider):
    """Skips per-file stat calls entirely — for huge listings where even
    stat round-trips dominate (reference: FastFileMetadataProvider)."""

    def get_metadata(self, paths: List[str]) -> BlockMetadata:
        return BlockMetadata(num_rows=-1, size_bytes=-1, input_files=list(paths))


class ParquetMetadataProvider(FileMetadataProvider):
    """Exact row counts + uncompressed sizes from parquet footers — no
    data pages are read (reference: ParquetMetadataProvider)."""

    def get_metadata(self, paths: List[str]) -> BlockMetadata:
        import pyarrow.parquet as pq

        rows = 0
        size = 0
        for p in paths:
            try:
                md = pq.ParquetFile(p).metadata
                rows += md.num_rows
                for rg in range(md.num_row_groups):
                    size += md.row_group(rg).total_byte_size
            except Exception:
                return DefaultFileMetadataProvider().get_metadata(paths)
        return BlockMetadata(num_rows=rows, size_bytes=size, input_files=list(paths))
