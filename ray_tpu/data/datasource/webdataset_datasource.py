"""WebDataset tar-shard reader/writer.

Analog of the reference's webdataset_datasource.py: samples are groups of
files inside .tar shards sharing a key prefix (``{key}.{ext}``); the
extension names the column AND its format. Implemented on stdlib
``tarfile`` — no webdataset pip package required.

Conventions (round-trip safe):
- A column whose name is itself a known format (``txt``, ``json``,
  ``cls``, images, ``bin``) is stored as ``{key}.{col}``.
- Any other column gets a format suffix: ``{key}.{col}.{fmt}`` (e.g.
  ``sample0.meta.json``) and decodes back into column ``col``.
Writing goes through ``Dataset.write_webdataset`` (one shard per block).
Formats: json (dict/list/float/bool), txt (str), cls (int),
jpg/jpeg/png/ppm/bmp (PIL image -> np array when PIL is available, else
raw bytes), bin (raw bytes).
"""

from __future__ import annotations

import io
import json
import os
import tarfile
from ray_tpu.data.block import BlockAccessor
from ray_tpu.data.datasource.datasource import FileBasedDatasource

_IMAGE_FORMATS = ("jpg", "jpeg", "png", "ppm", "bmp")
_KNOWN_FORMATS = {"txt", "text", "json", "cls", "id", "index", "bin", *_IMAGE_FORMATS}


def _jsonable(value):
    import numpy as np

    if isinstance(value, np.ndarray):
        return value.tolist()
    if isinstance(value, np.integer):
        return int(value)
    if isinstance(value, np.floating):
        return float(value)
    if isinstance(value, np.bool_):
        return bool(value)
    return value


def _decode(fmt: str, data: bytes):
    fmt = fmt.lower()
    if fmt == "json":
        return json.loads(data.decode("utf-8"))
    if fmt in ("txt", "text"):
        return data.decode("utf-8")
    if fmt in ("cls", "id", "index"):
        return int(data.decode("utf-8").strip())
    if fmt in _IMAGE_FORMATS:
        try:
            import numpy as np
            from PIL import Image

            return np.asarray(Image.open(io.BytesIO(data)))
        except ImportError:
            return data
    return data  # bin / unknown: raw bytes


def _encode(col: str, value):
    """-> (member suffix, payload bytes). The suffix encodes column name
    and format per the module docstring."""
    value = _jsonable(value)
    if isinstance(value, bytes):
        fmt, data = "bin", value
    elif isinstance(value, str):
        fmt, data = "txt", value.encode()
    elif isinstance(value, bool) or not isinstance(value, int):
        fmt, data = "json", json.dumps(value).encode()
    else:
        fmt, data = "cls", str(value).encode()
    if col.lower() in _KNOWN_FORMATS:
        return col, data  # column name IS the format (trusted)
    return f"{col}.{fmt}", data


def write_sample(tf: tarfile.TarFile, key: str, row: dict):
    for col, value in row.items():
        if col == "__key__":
            continue
        suffix, data = _encode(col, value)
        info = tarfile.TarInfo(name=f"{key}.{suffix}")
        info.size = len(data)
        tf.addfile(info, io.BytesIO(data))


class WebDatasetDatasource(FileBasedDatasource):
    _suffixes = [".tar"]

    def _read_file(self, path, batch_size: int = 64, **kwargs):
        rows: list = []
        current_key = None
        sample: dict = {}
        with tarfile.open(path, "r") as tf:
            for member in tf:
                if not member.isfile():
                    continue
                base = os.path.basename(member.name)
                if "." in base:
                    key, ext = base.split(".", 1)
                else:
                    key, ext = base, "bin"
                key = os.path.join(os.path.dirname(member.name), key)
                if current_key is not None and key != current_key:
                    if sample:
                        rows.append(sample)
                    sample = {}
                    if len(rows) >= batch_size:
                        yield BlockAccessor.batch_to_block(rows)
                        rows = []
                current_key = key
                sample["__key__"] = key
                ext_parts = ext.split(".")
                fmt = ext_parts[-1]
                col = ext if len(ext_parts) == 1 else ".".join(ext_parts[:-1])
                sample[col] = _decode(fmt, tf.extractfile(member).read())
        if sample:
            rows.append(sample)
        if rows:
            yield BlockAccessor.batch_to_block(rows)


