"""MongoDB datasource (reference: mongo_datasource.py).

Partitions a collection into parallel read tasks over an _id-sorted
skip/limit sharding (stable across cursors), applying the user pipeline
per shard. Requires
``pymongo``, which is not in this image — the import gate mirrors the
reference's optional-dependency behavior; the partitioning logic is real
and exercised against any DB-API-compatible stand-in in tests via
``collection_factory`` injection.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from ray_tpu.data.block import BlockAccessor, BlockMetadata
from ray_tpu.data.datasource.datasource import Datasource, ReadTask


class MongoDatasource(Datasource):
    def __init__(self, uri: str, database: str, collection: str,
                 *, pipeline: Optional[list] = None,
                 collection_factory: Optional[Callable] = None):
        """``collection_factory``: () -> collection-like object exposing
        count_documents/find/aggregate — defaults to a pymongo client
        (gated on the package being installed)."""
        if collection_factory is None:
            try:
                import pymongo  # noqa: F401
            except ImportError as e:
                raise ImportError(
                    "read_mongo requires the 'pymongo' package, which is not "
                    "installed in this environment. Install it on the node "
                    "image, or pass collection_factory= for a custom client."
                ) from e

            def collection_factory():
                import pymongo

                return pymongo.MongoClient(uri)[database][collection]

        self._factory = collection_factory
        self._pipeline = pipeline or []

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        factory = self._factory
        pipeline = self._pipeline
        coll = factory()
        total = coll.count_documents({})
        parallelism = max(1, min(parallelism, max(total, 1)))
        chunk = (total + parallelism - 1) // parallelism if total else 1
        tasks = []
        for i in range(parallelism):
            skip, limit = i * chunk, chunk

            def read(skip=skip, limit=limit):
                c = factory()
                # Shard the COLLECTION deterministically ($sort by _id makes
                # the skip/limit windows stable across separate cursors —
                # natural order isn't), then run the user pipeline on each
                # shard. Pipelines that expand cardinality ($unwind) are
                # safe: every input document lands in exactly one shard.
                stages = [
                    {"$sort": {"_id": 1}},
                    {"$skip": skip},
                    {"$limit": limit},
                ] + list(pipeline)
                rows = [
                    {k: v for k, v in doc.items() if k != "_id"}
                    for doc in c.aggregate(stages)
                ]
                if rows:
                    yield BlockAccessor.batch_to_block(rows)

            tasks.append(ReadTask(read, BlockMetadata(num_rows=-1, size_bytes=-1)))
        return tasks
