"""Datasource protocol + file-based readers.

Analog of the reference's datasource layer (python/ray/data/datasource/*.py):
a ``Datasource.get_read_tasks(parallelism)`` returns serializable ``ReadTask``
callables, each producing one or more blocks; file readers share path
expansion + per-file task logic (file_based_datasource.py). Writers mirror
``Dataset.write_*``.
"""

from __future__ import annotations

import glob as globlib
import io
import os
from typing import Callable, Iterable, List, Optional

import numpy as np

from ray_tpu.data.block import BlockAccessor, BlockMetadata


class ReadTask:
    """A serializable zero-arg callable yielding batches/blocks, carrying
    advance metadata for scheduling (reference: datasource.py ReadTask)."""

    def __init__(self, read_fn: Callable[[], Iterable], metadata: Optional[BlockMetadata] = None):
        self._read_fn = read_fn
        self.metadata = metadata

    def __call__(self):
        return self._read_fn()


class Datasource:
    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        raise NotImplementedError

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return None


class RangeDatasource(Datasource):
    def __init__(self, n: int, column: str = "id"):
        self._n = n
        self._column = column

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        n, col = self._n, self._column
        parallelism = max(1, min(parallelism, n)) if n else 1
        tasks = []
        chunk = (n + parallelism - 1) // parallelism if n else 0
        for start in range(0, n, max(chunk, 1)):
            end = min(start + chunk, n)

            def read(start=start, end=end):
                yield {col: np.arange(start, end)}

            tasks.append(ReadTask(read, BlockMetadata(num_rows=end - start, size_bytes=8 * (end - start))))
        return tasks or [ReadTask(lambda: iter([{col: np.array([], dtype=np.int64)}]), BlockMetadata(0, 0))]


def _expand_paths(paths, suffixes=None) -> list:
    if isinstance(paths, str):
        paths = [paths]
    out = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                out.extend(os.path.join(root, f) for f in sorted(files))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(globlib.glob(p)))
        else:
            out.append(p)
    if suffixes:
        out = [p for p in out if any(p.endswith(s) for s in suffixes)]
    if not out:
        raise ValueError(f"no files found for {paths}")
    return out


def _attach_partition_cols(block, fields: dict):
    """Append constant partition columns to one block (arrow / dict /
    pandas), skipping names the data already carries."""
    if not fields:
        return block
    try:
        import pyarrow as pa

        if isinstance(block, pa.Table):
            for k, v in fields.items():
                if k not in block.column_names:
                    block = block.append_column(k, pa.array([v] * block.num_rows))
            return block
    except ImportError:
        pass
    try:
        import pandas as pd

        if isinstance(block, pd.DataFrame):
            for k, v in fields.items():
                if k not in block.columns:
                    block = block.assign(**{k: v})
            return block
    except ImportError:
        pass
    if isinstance(block, dict):
        n = len(next(iter(block.values()))) if block else 0
        out = dict(block)
        for k, v in fields.items():
            if k not in out:
                out[k] = np.full(n, v)
        return out
    return block


class FileBasedDatasource(Datasource):
    _suffixes: Optional[list] = None

    def __init__(self, paths, partitioning=None, partition_filter=None,
                 meta_provider=None, **reader_args):
        """``partitioning``: a Partitioning describing how fields encode in
        paths — parsed values become extra columns on every block.
        ``partition_filter``: dict -> bool predicate; files whose partition
        fields fail it are PRUNED before any byte is read (reference:
        partitioning.py PathPartitionFilter). ``meta_provider``: a
        FileMetadataProvider supplying size/row metadata without reading
        data (reference: file_meta_provider.py:20)."""
        all_paths = _expand_paths(paths, self._suffixes)
        self._partitions: dict = {}
        if partitioning is not None:
            self._partitions = {p: partitioning.parse(p) for p in all_paths}
            if partition_filter is not None:
                all_paths = [p for p in all_paths if partition_filter(self._partitions[p])]
                if not all_paths:
                    raise ValueError("partition_filter pruned every input file")
        elif partition_filter is not None:
            raise ValueError("partition_filter requires partitioning=")
        self._paths = all_paths
        if meta_provider is None:
            from ray_tpu.data.datasource.partitioning import DefaultFileMetadataProvider

            meta_provider = DefaultFileMetadataProvider()
        self._meta_provider = meta_provider
        self._reader_args = reader_args

    def _read_file(self, path: str, **kwargs):
        raise NotImplementedError

    def estimate_inmemory_data_size(self) -> Optional[int]:
        size = self._meta_provider.get_metadata(self._paths).size_bytes
        return None if size is None or size < 0 else size

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        groups = np.array_split(np.arange(len(self._paths)), max(1, min(parallelism, len(self._paths))))
        read_file = self._read_file
        args = self._reader_args
        partitions = self._partitions
        tasks = []
        for g in groups:
            if len(g) == 0:
                continue
            files = [self._paths[i] for i in g]

            def read(files=files):
                for f in files:
                    fields = partitions.get(f)
                    for block in read_file(f, **args):
                        yield _attach_partition_cols(block, fields)

            tasks.append(ReadTask(read, self._meta_provider.get_metadata(files)))
        return tasks


class ParquetDatasource(FileBasedDatasource):
    _suffixes = [".parquet"]

    def _read_file(self, path, columns=None, **kwargs):
        import pyarrow.parquet as pq

        yield pq.read_table(path, columns=columns, **kwargs)


class CSVDatasource(FileBasedDatasource):
    _suffixes = None

    def _read_file(self, path, **kwargs):
        import pyarrow.csv as pacsv

        yield pacsv.read_csv(path, **kwargs)


class JSONDatasource(FileBasedDatasource):
    _suffixes = None

    def _read_file(self, path, **kwargs):
        import pandas as pd

        yield BlockAccessor.batch_to_block(pd.read_json(path, lines=kwargs.get("lines", True)))


class NumpyDatasource(FileBasedDatasource):
    _suffixes = [".npy", ".npz"]

    def _read_file(self, path, column: str = "data", **kwargs):
        arr = np.load(path, allow_pickle=False)
        if isinstance(arr, np.lib.npyio.NpzFile):
            yield {k: arr[k] for k in arr.files}
        else:
            yield {column: arr}


class BinaryDatasource(FileBasedDatasource):
    def _read_file(self, path, include_paths: bool = False, **kwargs):
        with open(path, "rb") as f:
            data = f.read()
        batch = {"bytes": np.array([data], dtype=object)}
        if include_paths:
            batch["path"] = np.array([path], dtype=object)
        # object dtype can't go to arrow directly; use pyarrow binary
        import pyarrow as pa

        cols = {"bytes": pa.array([data], type=pa.binary())}
        if include_paths:
            cols["path"] = pa.array([path])
        yield pa.table(cols)


class TextDatasource(FileBasedDatasource):
    def _read_file(self, path, encoding: str = "utf-8", drop_empty_lines: bool = True, **kwargs):
        with open(path, "r", encoding=encoding) as f:
            lines = [ln.rstrip("\n") for ln in f]
        if drop_empty_lines:
            lines = [ln for ln in lines if ln.strip()]
        yield {"text": np.array(lines, dtype=object).astype(str)}


class ImageDatasource(FileBasedDatasource):
    _suffixes = [".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp"]

    def _read_file(self, path, size: Optional[tuple] = None, mode: str = "RGB", include_paths: bool = False, **kwargs):
        from PIL import Image

        img = Image.open(path).convert(mode)
        if size is not None:
            img = img.resize(size)
        arr = np.asarray(img)
        batch = {"image": arr[None, ...]}
        if include_paths:
            import pyarrow as pa

            block = BlockAccessor.batch_to_block(batch)
            block = block.append_column("path", pa.array([path]))
            yield block
        else:
            yield batch


class TFRecordsDatasource(FileBasedDatasource):
    """Minimal TFRecord reader (uncompressed) parsing tf.train.Example
    protos without a TF dependency (reference: tfrecords_datasource.py)."""

    _suffixes = None

    def _read_file(self, path, **kwargs):
        rows = []
        with open(path, "rb") as f:
            while True:
                header = f.read(8)
                if len(header) < 8:
                    break
                (length,) = np.frombuffer(header, dtype="<u8", count=1)
                f.read(4)  # length crc
                payload = f.read(int(length))
                f.read(4)  # data crc
                rows.append(_parse_tf_example(payload))
        if rows:
            keys = rows[0].keys()
            yield {k: np.array([r[k] for r in rows]) for k in keys}


def _parse_tf_example(payload: bytes) -> dict:
    """Tiny protobuf wire-format parser for tf.train.Example."""
    out = {}
    feats = _pb_fields(payload).get(1)
    if not feats:
        return out
    for fmap in feats:
        for entry in _pb_fields(fmap).get(1, []):
            fields = _pb_fields(entry)
            name = fields[1][0].decode()
            feature = fields[2][0]
            ff = _pb_fields(feature)
            if 1 in ff:  # bytes_list
                vals = _pb_fields(ff[1][0]).get(1, [])
                out[name] = vals[0] if len(vals) == 1 else vals
            elif 2 in ff:  # float_list
                raw = _pb_fields(ff[2][0]).get(1, [])
                arr = np.concatenate([np.frombuffer(r, dtype="<f4") if isinstance(r, bytes) else np.array([r], dtype="<f4") for r in raw]) if raw else np.array([], "<f4")
                out[name] = arr[0] if arr.size == 1 else arr
            elif 3 in ff:  # int64_list
                raw = _pb_fields(ff[3][0]).get(1, [])
                vals = []
                for r in raw:
                    if isinstance(r, bytes):
                        vals.extend(_decode_varints(r))
                    else:
                        vals.append(r)
                out[name] = vals[0] if len(vals) == 1 else np.array(vals)
    return out


def _pb_fields(buf: bytes) -> dict:
    """Parse one protobuf message into {field_number: [values]}."""
    out: dict = {}
    i = 0
    while i < len(buf):
        tag, i = _read_varint(buf, i)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            val, i = _read_varint(buf, i)
        elif wire == 2:
            ln, i = _read_varint(buf, i)
            val = buf[i : i + ln]
            i += ln
        elif wire == 5:
            val = np.frombuffer(buf[i : i + 4], dtype="<f4")[0]
            i += 4
        elif wire == 1:
            val = np.frombuffer(buf[i : i + 8], dtype="<f8")[0]
            i += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        out.setdefault(field, []).append(val)
    return out


def _read_varint(buf: bytes, i: int):
    shift = val = 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7


def _decode_varints(buf: bytes) -> list:
    out, i = [], 0
    while i < len(buf):
        v, i = _read_varint(buf, i)
        out.append(v)
    return out
