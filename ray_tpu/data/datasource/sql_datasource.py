"""SQL datasource (DB-API 2.0 connections; sqlite3 in the standard image).

Reference: python/ray/data/datasource/sql_datasource.py (read_sql) and
dataset.write_sql: the user supplies a zero-arg ``connection_factory`` so
the CONNECTION is created inside each read/write task — DB handles don't
serialize, factories do. Reads can shard on an integer column
(``shard_column``) so partitions run as parallel tasks; without one the
query runs as a single task (the reference's default too, since an
arbitrary SQL query has no general row-addressing scheme).
"""

from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np

from ray_tpu.data.block import BlockMetadata
from ray_tpu.data.datasource.datasource import Datasource, ReadTask


def _rows_to_columns(rows, description):
    names = [d[0] for d in description]
    if not rows:
        return {n: np.array([]) for n in names}
    cols = {}
    for i, n in enumerate(names):
        values = [r[i] for r in rows]
        cols[n] = np.asarray(values)
    return cols


class SQLDatasource(Datasource):
    def __init__(
        self,
        sql: str,
        connection_factory: Callable,
        shard_column: Optional[str] = None,
        shard_bounds: Optional[tuple] = None,
    ):
        self.sql = sql
        self.connection_factory = connection_factory
        self.shard_column = shard_column
        self.shard_bounds = shard_bounds

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        factory = self.connection_factory
        sql = self.sql

        if self.shard_column is None or parallelism <= 1:
            def read():
                conn = factory()
                try:
                    cur = conn.cursor()
                    cur.execute(sql)
                    yield _rows_to_columns(cur.fetchall(), cur.description)
                finally:
                    conn.close()

            return [ReadTask(read, BlockMetadata(num_rows=-1, size_bytes=0))]

        column = self.shard_column
        if self.shard_bounds is not None:
            lo, hi = self.shard_bounds
        else:
            conn = factory()
            try:
                cur = conn.cursor()
                cur.execute(f"SELECT MIN({column}), MAX({column}) FROM ({sql})")
                lo, hi = cur.fetchone()
            finally:
                conn.close()
        if lo is None:
            return [ReadTask(lambda: iter(()), BlockMetadata(num_rows=0, size_bytes=0))]
        edges = np.linspace(int(lo), int(hi) + 1, parallelism + 1).astype(int)
        tasks = []
        for start, end in zip(edges[:-1], edges[1:]):
            if start == end:
                continue

            def read(start=int(start), end=int(end)):
                conn = factory()
                try:
                    cur = conn.cursor()
                    cur.execute(
                        f"SELECT * FROM ({sql}) WHERE {column} >= ? AND {column} < ?",
                        (start, end),
                    )
                    yield _rows_to_columns(cur.fetchall(), cur.description)
                finally:
                    conn.close()

            tasks.append(ReadTask(read, BlockMetadata(num_rows=-1, size_bytes=0)))

        def read_nulls():
            # Range predicates drop NULL shard-column rows from every shard;
            # a dedicated task keeps the sharded read row-equivalent to the
            # single-task read.
            conn = factory()
            try:
                cur = conn.cursor()
                cur.execute(f"SELECT * FROM ({sql}) WHERE {column} IS NULL")
                rows = cur.fetchall()
                if rows:
                    yield _rows_to_columns(rows, cur.description)
            finally:
                conn.close()

        tasks.append(ReadTask(read_nulls, BlockMetadata(num_rows=-1, size_bytes=0)))
        return tasks


def write_sql_block(block, table: str, connection_factory: Callable):
    """Insert one block into `table` (used by Dataset.write_sql tasks)."""
    from ray_tpu.data.block import BlockAccessor

    acc = BlockAccessor.for_block(block)
    rows = list(acc.iter_rows())
    if not rows:
        return 0
    names = list(rows[0].keys())
    placeholders = ",".join(["?"] * len(names))
    conn = connection_factory()
    try:
        cur = conn.cursor()
        cur.executemany(
            f"INSERT INTO {table} ({','.join(names)}) VALUES ({placeholders})",
            [
                tuple(
                    v.item() if hasattr(v, "item") and getattr(v, "ndim", 1) == 0 else v
                    for v in r.values()
                )
                for r in rows
            ],
        )
        conn.commit()
        return len(rows)
    finally:
        conn.close()
