"""Block model for ray_tpu.data.

Analog of the reference's block layer (python/ray/data/block.py:255/276
BlockAccessor/BlockMetadata and _internal/{arrow_block,pandas_block}.py), cut
down to one canonical representation: a block is a ``pyarrow.Table``. Rows are
plain dicts; batches are dicts of numpy arrays (the natural feed format for
JAX). Pandas/pyarrow views are conversions at the accessor edge rather than
parallel block implementations.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterator, Optional

import numpy as np
import pyarrow as pa

Block = pa.Table


@dataclasses.dataclass
class BlockMetadata:
    """Lightweight stats shipped next to each block ref (reference:
    data/block.py:276 BlockMetadata)."""

    num_rows: int
    size_bytes: int
    schema: Optional[pa.Schema] = None
    input_files: Optional[list] = None

    def merged_with(self, other: "BlockMetadata") -> "BlockMetadata":
        return BlockMetadata(
            num_rows=self.num_rows + other.num_rows,
            size_bytes=self.size_bytes + other.size_bytes,
            schema=self.schema or other.schema,
            input_files=(self.input_files or []) + (other.input_files or []),
        )


def _normalize_column(values: Any) -> pa.Array:
    if isinstance(values, pa.Array):
        return values
    if isinstance(values, np.ndarray) and values.ndim > 1:
        # Tensor column: store as fixed-size-list of flattened rows.
        flat = values.reshape(len(values), -1)
        inner = pa.array(flat.ravel())
        arr = pa.FixedSizeListArray.from_arrays(inner, flat.shape[1])
        meta_shape = values.shape[1:]
        return arr, meta_shape  # type: ignore[return-value]
    return pa.array(values)


class BlockAccessor:
    """Uniform operations over a block (reference: data/block.py:255)."""

    def __init__(self, block: Block):
        self._table = block

    @staticmethod
    def for_block(block: Any) -> "BlockAccessor":
        return BlockAccessor(BlockAccessor.batch_to_block(block))

    # -- construction ------------------------------------------------------
    @staticmethod
    def batch_to_block(batch: Any) -> Block:
        """Convert a user-produced batch (dict of arrays / pandas / arrow /
        list of rows) into the canonical arrow block."""
        import pandas as pd

        if isinstance(batch, pa.Table):
            return batch
        if isinstance(batch, pd.DataFrame):
            return pa.Table.from_pandas(batch, preserve_index=False)
        if isinstance(batch, dict):
            cols, fields, shapes = [], [], {}
            for name, values in batch.items():
                if isinstance(values, np.ndarray) and values.ndim > 1 and values.shape[1:].count(0) == 0:
                    flat = values.reshape(len(values), -1)
                    inner = pa.array(flat.ravel())
                    arr = pa.FixedSizeListArray.from_arrays(inner, flat.shape[1])
                    shapes[name] = values.shape[1:]
                elif isinstance(values, np.ndarray) and values.ndim > 1:
                    # Zero-width tensor column (e.g. a block of all-empty
                    # lists): FixedSizeListArray rejects size 0 — store as
                    # variable-length lists instead.
                    arr = pa.array([list(row) for row in values])
                else:
                    arr = pa.array(np.asarray(values) if isinstance(values, (list, tuple)) else values)
                cols.append(arr)
                fields.append(name)
            table = pa.table(dict(zip(fields, cols)))
            if shapes:
                meta = {b"ray_tpu.tensor_shapes": repr(shapes).encode()}
                table = table.replace_schema_metadata({**(table.schema.metadata or {}), **meta})
            return table
        if isinstance(batch, list):  # list of row dicts
            if not batch:
                return pa.table({})
            keys = batch[0].keys()
            return BlockAccessor.batch_to_block({k: np.array([r[k] for r in batch]) for k in keys})
        raise TypeError(f"cannot convert batch of type {type(batch)} to a block")

    @staticmethod
    def concat(blocks: list) -> Block:
        blocks = [b for b in blocks if b.num_rows > 0] or blocks[:1]
        if not blocks:
            return pa.table({})
        if len(blocks) == 1:
            return blocks[0]
        return pa.concat_tables(blocks, promote_options="default")

    # -- stats -------------------------------------------------------------
    def num_rows(self) -> int:
        return self._table.num_rows

    def size_bytes(self) -> int:
        return self._table.nbytes

    def schema(self) -> pa.Schema:
        return self._table.schema

    def get_metadata(self, input_files: Optional[list] = None) -> BlockMetadata:
        return BlockMetadata(
            num_rows=self.num_rows(),
            size_bytes=self.size_bytes(),
            schema=self.schema(),
            input_files=input_files,
        )

    # -- conversion --------------------------------------------------------
    def _tensor_shapes(self) -> dict:
        meta = self._table.schema.metadata or {}
        raw = meta.get(b"ray_tpu.tensor_shapes")
        return eval(raw.decode()) if raw else {}  # noqa: S307 - our own repr

    def to_numpy(self, columns: Optional[list] = None) -> dict:
        shapes = self._tensor_shapes()
        out = {}
        for name in columns or self._table.column_names:
            col = self._table.column(name)
            if pa.types.is_fixed_size_list(col.type):
                flat = col.combine_chunks().flatten().to_numpy(zero_copy_only=False)
                n = self._table.num_rows
                shape = shapes.get(name, (col.type.list_size,))
                out[name] = flat.reshape((n,) + tuple(shape))
            else:
                out[name] = col.to_numpy(zero_copy_only=False)
        return out

    def to_pandas(self):
        return self._table.to_pandas()

    def to_arrow(self) -> pa.Table:
        return self._table

    def to_batch(self, batch_format: str):
        if batch_format in ("numpy", "jax", "default"):
            return self.to_numpy()
        if batch_format == "pandas":
            return self.to_pandas()
        if batch_format in ("pyarrow", "arrow"):
            return self._table
        raise ValueError(f"unknown batch_format {batch_format!r}")

    # -- row/slice ops -----------------------------------------------------
    def iter_rows(self) -> Iterator[dict]:
        numpy_cols = self.to_numpy()
        for i in range(self.num_rows()):
            yield {k: v[i] for k, v in numpy_cols.items()}

    def slice(self, start: int, end: int) -> Block:
        return self._table.slice(start, end - start)

    def take_indices(self, indices) -> Block:
        return self._table.take(pa.array(indices))

    def random_shuffle(self, seed: Optional[int]) -> Block:
        rng = np.random.default_rng(seed)
        return self.take_indices(rng.permutation(self.num_rows()))

    def sort(self, key: str, descending: bool = False) -> Block:
        order = "descending" if descending else "ascending"
        idx = pa.compute.sort_indices(self._table, sort_keys=[(key, order)])
        return self._table.take(idx)

    def filter_rows(self, predicate: Callable[[dict], bool]) -> Block:
        keep = [i for i, row in enumerate(self.iter_rows()) if predicate(row)]
        return self.take_indices(keep)

    def select(self, columns: list) -> Block:
        return self._table.select(columns)

    def rename(self, mapping: dict) -> Block:
        return self._table.rename_columns([mapping.get(c, c) for c in self._table.column_names])

    def drop(self, columns: list) -> Block:
        keep = [c for c in self._table.column_names if c not in columns]
        return self._table.select(keep)

    def hash_partition(self, key: str, num_partitions: int) -> list:
        # Process-stable hash: builtin hash() is salted per process for
        # str/bytes, which would scatter the same key across partitions when
        # map tasks run in different workers.
        import zlib

        def stable_hash(v) -> int:
            if isinstance(v, bytes):
                return zlib.crc32(v)
            return zlib.crc32(repr(v).encode())

        vals = self._table.column(key).to_pylist()
        assignments = np.array([stable_hash(v) % num_partitions for v in vals])
        return [self.take_indices(np.nonzero(assignments == p)[0]) for p in range(num_partitions)]

    def random_partition(self, num_partitions: int, seed: Optional[int]) -> list:
        rng = np.random.default_rng(seed)
        assignments = rng.integers(0, num_partitions, self.num_rows())
        return [self.take_indices(np.nonzero(assignments == p)[0]) for p in range(num_partitions)]

    def range_partition(self, key: str, boundaries: list) -> list:
        """Split sorted-key values by boundary values (for sort-shuffle)."""
        vals = np.asarray(self._table.column(key).to_pylist())
        assignments = np.searchsorted(np.asarray(boundaries), vals, side="right")
        return [
            self.take_indices(np.nonzero(assignments == p)[0])
            for p in range(len(boundaries) + 1)
        ]
