"""GroupedData (reference: python/ray/data/grouped_data.py)."""

from __future__ import annotations

from typing import Callable, Optional

from ray_tpu.data import aggregate as agg_mod
from ray_tpu.data._internal import shuffle as shuffle_mod
from ray_tpu.data._internal.logical_plan import AllToAll, MapTransform
from ray_tpu.data.block import BlockAccessor


class GroupedData:
    def __init__(self, dataset, key: Optional[str]):
        self._dataset = dataset
        self._key = key

    def aggregate(self, *aggs):
        from ray_tpu.data.dataset import Dataset

        key = self._key
        return Dataset(AllToAll(
            name="Aggregate",
            input_op=self._dataset._plan,
            bulk_fn=lambda bundles: shuffle_mod.hash_aggregate(bundles, key, list(aggs)),
        ))

    def count(self):
        return self.aggregate(agg_mod.Count())

    def sum(self, on: str):
        return self.aggregate(agg_mod.Sum(on))

    def min(self, on: str):
        return self.aggregate(agg_mod.Min(on))

    def max(self, on: str):
        return self.aggregate(agg_mod.Max(on))

    def mean(self, on: str):
        return self.aggregate(agg_mod.Mean(on))

    def std(self, on: str, ddof: int = 1):
        return self.aggregate(agg_mod.Std(on, ddof))

    def map_groups(self, fn: Callable, *, batch_format: str = "numpy"):
        """Shuffle rows of each group together, then apply fn per group."""
        from ray_tpu.data.dataset import Dataset

        key = self._key

        def regroup(bundles):
            return shuffle_mod._shuffle(
                bundles,
                shuffle_mod._map_hash,
                (max(1, len(bundles)), key),
                shuffle_mod._reduce_concat,
                (None,),
                max(1, len(bundles)),
            )

        shuffled = Dataset(AllToAll(name="GroupShuffle", input_op=self._dataset._plan, bulk_fn=regroup))

        def block_fn(block):
            acc = BlockAccessor.for_block(block)
            if acc.num_rows() == 0:
                return block
            sorted_block = acc.sort(key)
            sacc = BlockAccessor.for_block(sorted_block)
            keys = sorted_block.column(key).to_pylist()
            outs, start = [], 0
            for i in range(1, len(keys) + 1):
                if i == len(keys) or keys[i] != keys[start]:
                    group = sacc.slice(start, i)
                    out = fn(BlockAccessor.for_block(group).to_batch(batch_format))
                    outs.append(BlockAccessor.batch_to_block(out))
                    start = i
            return BlockAccessor.concat(outs)

        return Dataset(MapTransform(name="MapGroups", input_op=shuffled._plan, block_fn=block_fn))
