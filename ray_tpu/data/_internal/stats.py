"""Per-operator execution statistics.

Analog of the reference's DatasetStats (python/ray/data/_internal/
stats.py:117): every executed operator records wall time, task count and
output blocks/rows/bytes; ``Dataset.stats()`` renders the per-op summary
the reference prints after execution, and the raw objects are exposed for
programmatic access (dashboards, tests).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


@dataclass
class OpStats:
    name: str
    start: float | None = None
    end: float | None = None
    num_tasks: int = 0
    blocks: int = 0
    rows: int = 0
    size_bytes: int = 0
    extra: dict = field(default_factory=dict)

    @property
    def wall_s(self) -> float:
        if self.start is None:
            return 0.0
        return (self.end or time.perf_counter()) - self.start

    def mark_start(self):
        if self.start is None:
            self.start = time.perf_counter()

    def record_output(self, meta):
        self.end = time.perf_counter()
        self.blocks += 1
        rows = max(0, getattr(meta, "num_rows", 0) or 0)
        size = max(0, getattr(meta, "size_bytes", 0) or 0)
        self.rows += rows
        self.size_bytes += size
        # Runtime metrics: per-op rows/bytes/blocks flow to /metrics under
        # the ray_tpu_data_* family (one inc per block, not per row).
        try:
            from ray_tpu._private import self_metrics

            inst = self_metrics.instruments()
            tags = {"op": self.name}
            inst["data_blocks"].inc(tags=tags)
            if rows:
                inst["data_rows"].inc(rows, tags=tags)
            if size:
                inst["data_bytes"].inc(size, tags=tags)
        except Exception:
            pass

    def line(self, index: int) -> str:
        return (
            f"Operator {index} {self.name}: {self.num_tasks} tasks, "
            f"{self.blocks} blocks, {self.rows} rows, {self.size_bytes} bytes "
            f"in {self.wall_s:.2f}s"
        )


class DatasetStats:
    def __init__(self, op_stats: list[OpStats] | None = None):
        self.op_stats = op_stats or []

    def summary_string(self, totals: str = "") -> str:
        lines = [s.line(i + 1) for i, s in enumerate(self.op_stats)]
        if totals:
            lines.append(totals)
        return "\n".join(lines) if lines else "Dataset: not executed"
