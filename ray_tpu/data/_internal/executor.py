"""Streaming executor for ray_tpu.data.

Analog of the reference's StreamingExecutor
(python/ray/data/_internal/execution/streaming_executor.py:48 and
operators/{task_pool,actor_pool}_map_operator.py): the logical chain is
lowered to physical operators; map stages run as ray_tpu tasks (or an actor
pool) over block refs with bounded in-flight concurrency, and completed output
bundles stream to the consumer in block order while upstream work continues.
Barrier ops (shuffle/sort/union/zip) materialize their input first, like the
reference's AllToAllOperator.

A "bundle" is ``(block_ref, BlockMetadata)`` — the metadata travels eagerly on
the driver while the block stays in the object store (reference: RefBundle).
"""

from __future__ import annotations

import collections
from typing import Iterator, Optional

import ray_tpu
from ray_tpu.data._internal.logical_plan import (
    AllToAll,
    InputData,
    Limit,
    MapTransform,
    Read,
    Union,
    Zip,
    fuse_map_chain,
    plan_to_chain,
)
from ray_tpu.data.block import BlockAccessor


def _run_read_task(read_task):
    """Execute a ReadTask: returns (block, metadata)."""
    blocks = list(read_task())
    block = BlockAccessor.concat([BlockAccessor.batch_to_block(b) for b in blocks])
    acc = BlockAccessor.for_block(block)
    return block, acc.get_metadata()


def _run_map_task(fn, block):
    out = fn(block)
    out = BlockAccessor.batch_to_block(out)
    return out, BlockAccessor.for_block(out).get_metadata()


def _slice_block_task(block, start, end):
    out = BlockAccessor.for_block(block).slice(start, end)
    return out, BlockAccessor.for_block(out).get_metadata()


def _zip_blocks_task(left, right):
    import pyarrow as pa

    la, ra = BlockAccessor.for_block(left), BlockAccessor.for_block(right)
    if la.num_rows() != ra.num_rows():
        raise ValueError(f"zip row mismatch: {la.num_rows()} vs {ra.num_rows()}")
    cols = {name: left.column(name) for name in left.column_names}
    for name in right.column_names:
        out_name = name if name not in cols else name + "_1"
        cols[out_name] = right.column(name)
    out = pa.table(cols)
    return out, BlockAccessor.for_block(out).get_metadata()


class _MapWorker:
    """Actor-pool map worker (reference: ActorPoolMapOperator._MapWorker)."""

    def __init__(self, fn_constructor=None):
        self._udf = fn_constructor() if fn_constructor is not None else None

    def ready(self):
        return True

    def map_block(self, fn, block):
        if self._udf is not None:
            out = fn(block, self._udf)
        else:
            out = fn(block)
        out = BlockAccessor.batch_to_block(out)
        return out, BlockAccessor.for_block(out).get_metadata()


class ActorPoolStrategy:
    """Compute strategy selecting an autoscaling actor pool
    (reference: data/_internal/compute.py ActorPoolStrategy)."""

    def __init__(self, size: Optional[int] = None, min_size: int = 1, max_size: Optional[int] = None, num_tpus: float = 0, num_cpus: float = 1):
        if size is not None:
            min_size = max_size = size
        self.min_size = min_size
        self.max_size = max_size or max(min_size, 2)
        self.num_tpus = num_tpus
        self.num_cpus = num_cpus


class ExecutionContext:
    def __init__(
        self,
        max_tasks_in_flight: Optional[int] = None,
        preserve_order: bool = True,
        per_op_budget_blocks: Optional[int] = None,
    ):
        if max_tasks_in_flight is None:
            try:
                max_tasks_in_flight = max(2, int(ray_tpu.cluster_resources().get("CPU", 4)))
            except Exception:
                max_tasks_in_flight = 4
        self.max_tasks_in_flight = max_tasks_in_flight
        self.preserve_order = preserve_order
        # Per-op output budget (reference: streaming_executor_state.py
        # under_output_budget / select_operator_to_run): an op may not run
        # further ahead than this many unconsumed downstream blocks, so a
        # fast upstream can't materialize the whole dataset in the object
        # store while a slow downstream lags.
        self.per_op_budget_blocks = per_op_budget_blocks or 2 * max_tasks_in_flight
        # Observability for tests/stats: high-water marks per run.
        self.stats = {"max_inter_op_queued": 0, "max_inflight": 0}
        # Per-op execution stats (reference: DatasetStats, stats.py:117).
        from ray_tpu.data._internal.stats import DatasetStats

        self.dataset_stats = DatasetStats()


class _PhysicalMapOp:
    """Task-pool (or actor-pool) map stage with bounded in-flight tasks."""

    def __init__(self, logical: MapTransform, ctx: ExecutionContext):
        from ray_tpu.data._internal.stats import OpStats

        self.logical = logical
        self.ctx = ctx
        self.op_stats = OpStats(name=logical.name)
        ctx.dataset_stats.op_stats.append(self.op_stats)
        self.input: collections.deque = collections.deque()
        self.in_flight: dict = {}  # watch_ref -> (index, meta_ref_pair)
        self.output: dict = {}  # index -> bundle
        self.upstream_done = False
        self._pool: list = []
        self._pool_idx = 0
        self._actor_cls = None
        if isinstance(logical.compute, ActorPoolStrategy):
            strat = logical.compute
            self._actor_cls = ray_tpu.remote(
                num_cpus=strat.num_cpus, num_tpus=strat.num_tpus or None
            )(_MapWorker)
            self._pool = [
                self._actor_cls.remote(logical.fn_constructor) for _ in range(strat.min_size)
            ]

    @property
    def capacity(self) -> int:
        if self._pool:
            return max(0, 2 * len(self._pool) - len(self.in_flight))
        return max(0, self.ctx.max_tasks_in_flight - len(self.in_flight))

    def dispatch(self, limit: Optional[int] = None):
        if self._pool and self.input:
            # Autoscale the pool toward max_size while a backlog exists
            # (reference: ActorPoolMapOperator's autoscaling actor pool).
            strat = self.logical.compute
            backlog = max(0, len(self.input) - self.capacity)
            grow = min(backlog, strat.max_size - len(self._pool))
            for _ in range(grow):
                self._pool.append(self._actor_cls.remote(self.logical.fn_constructor))
        n = 0
        while self.input and self.capacity > 0 and (limit is None or n < limit):
            index, (block_ref, _meta) = self.input.popleft()
            if self._pool:
                actor = self._pool[self._pool_idx % len(self._pool)]
                self._pool_idx += 1
                refs = actor.map_block.options(num_returns=2).remote(
                    self.logical.block_fn, block_ref
                )
            else:
                remote_args = dict(self.logical.ray_remote_args)
                refs = (
                    ray_tpu.remote(num_returns=2, **remote_args)(_run_map_task)
                    .remote(self.logical.block_fn, block_ref)
                )
            self.in_flight[refs[1]] = (index, refs)
            self.op_stats.mark_start()
            self.op_stats.num_tasks += 1
            n += 1

    def complete(self, watch_ref):
        index, refs = self.in_flight.pop(watch_ref)
        meta = ray_tpu.get(refs[1])
        self.op_stats.record_output(meta)
        self.output[index] = (refs[0], meta)

    @property
    def done(self) -> bool:
        return self.upstream_done and not self.input and not self.in_flight


class _PhysicalReadOp:
    def __init__(self, logical: Read, ctx: ExecutionContext):
        from ray_tpu.data._internal.stats import OpStats

        self.logical = logical
        self.ctx = ctx
        self.op_stats = OpStats(name=logical.name)
        ctx.dataset_stats.op_stats.append(self.op_stats)
        self.input = collections.deque(enumerate(logical.read_tasks))
        self.in_flight: dict = {}
        self.output: dict = {}
        self.upstream_done = True

    @property
    def capacity(self) -> int:
        return max(0, self.ctx.max_tasks_in_flight - len(self.in_flight))

    def dispatch(self, limit: Optional[int] = None):
        n = 0
        while self.input and self.capacity > 0 and (limit is None or n < limit):
            index, read_task = self.input.popleft()
            refs = (
                ray_tpu.remote(num_returns=2, **dict(self.logical.ray_remote_args))(_run_read_task)
                .remote(read_task)
            )
            self.in_flight[refs[1]] = (index, refs)
            self.op_stats.mark_start()
            self.op_stats.num_tasks += 1
            n += 1

    def complete(self, watch_ref):
        index, refs = self.in_flight.pop(watch_ref)
        meta = ray_tpu.get(refs[1])
        self.op_stats.record_output(meta)
        self.output[index] = (refs[0], meta)

    @property
    def done(self) -> bool:
        return not self.input and not self.in_flight


def execute_streaming(plan, ctx: Optional[ExecutionContext] = None) -> Iterator[tuple]:
    """Execute the plan, yielding output bundles in block order as they
    complete. The scheduling loop keeps all map stages saturated
    (reference: streaming_executor_state.py:363 select_operator_to_run)."""
    ctx = ctx or ExecutionContext()
    plan = fuse_map_chain(plan)
    chain = plan_to_chain(plan)

    # Materialize any barrier prefix: everything up to the last non-streaming
    # op runs first; the streaming suffix (reads + maps + limit) pipelines.
    bundles: list = []
    stream_ops: list = []
    i = 0
    while i < len(chain):
        op = chain[i]
        if isinstance(op, InputData):
            bundles = list(op.bundles)
        elif isinstance(op, Read):
            stream_ops.append(_PhysicalReadOp(op, ctx))
        elif isinstance(op, MapTransform):
            stream_ops.append(_PhysicalMapOp(op, ctx))
        elif isinstance(op, (AllToAll, Union, Zip, Limit)):
            # Barrier: drain current streaming suffix into bundles first.
            bundles = _drain(bundles, stream_ops, ctx)
            stream_ops = []
            from ray_tpu.data._internal.stats import OpStats

            op_stats = OpStats(name=op.name)
            ctx.dataset_stats.op_stats.append(op_stats)
            op_stats.mark_start()
            if isinstance(op, AllToAll):
                bundles = op.bulk_fn(bundles)
            elif isinstance(op, Union):
                for extra in op.extra_inputs:
                    bundles = bundles + list(execute_streaming(extra, ctx))
            elif isinstance(op, Zip):
                other = list(execute_streaming(op.other, ctx))
                bundles = _zip_bundles(bundles, other)
            elif isinstance(op, Limit):
                bundles = _apply_limit(bundles, op.limit)
            for _, meta in bundles:
                op_stats.record_output(meta)
        else:
            raise TypeError(f"unknown logical op {op}")
        i += 1

    if not stream_ops:
        yield from bundles
        return
    yield from _pump(bundles, stream_ops, ctx)


def _pump(seed_bundles, ops, ctx) -> Iterator[tuple]:
    """Core scheduling loop over a chain of streaming ops (reference:
    streaming_executor_state.py:363 select_operator_to_run).

    Backpressure: forwarding into a downstream op's input queue and each
    op's dispatch are both gated on ctx.per_op_budget_blocks of unconsumed
    downstream work, and dispatch allowances are granted downstream-first —
    so a fast producer ahead of a slow consumer parks at the budget instead
    of materializing every intermediate block in the object store at once.
    """
    if ops and isinstance(ops[0], _PhysicalMapOp):
        for idx, b in enumerate(seed_bundles):
            ops[0].input.append((idx, b))
        ops[0].upstream_done = True
    next_fwd = [0] * len(ops)  # next output index each op hands downstream
    final = ops[-1]
    budget = max(2, ctx.per_op_budget_blocks)

    def forward():
        for k, op in enumerate(ops[:-1]):
            nxt = ops[k + 1]
            while next_fwd[k] in op.output and len(nxt.input) < budget:
                nxt.input.append((next_fwd[k], op.output.pop(next_fwd[k])))
                next_fwd[k] += 1
            if op.done and not op.output:
                nxt.upstream_done = True
            ctx.stats["max_inter_op_queued"] = max(
                ctx.stats["max_inter_op_queued"], len(nxt.input)
            )

    def select_and_dispatch():
        # Downstream ops first: draining them frees budget for upstream.
        for k in range(len(ops) - 1, -1, -1):
            op = ops[k]
            # Unconsumed work this op is responsible for: its buffered
            # outputs, its in-flight tasks, and what it already handed the
            # next op but that op hasn't consumed. For the final op the
            # buffered output IS op.output — counting it again would halve
            # its effective budget.
            downstream_q = len(ops[k + 1].input) if k + 1 < len(ops) else 0
            pressure = len(op.output) + len(op.in_flight) + downstream_q
            allowance = budget - pressure
            if allowance > 0:
                op.dispatch(limit=allowance)
            ctx.stats["max_inflight"] = max(ctx.stats["max_inflight"], len(op.in_flight))

    while True:
        forward()
        select_and_dispatch()
        while next_fwd[-1] in final.output:
            yield final.output.pop(next_fwd[-1])
            next_fwd[-1] += 1
        if all(op.done for op in ops) and not final.output:
            return
        watch = [r for op in ops for r in op.in_flight]
        if not watch:
            # No tasks in flight but not done: forwarding must unblock us.
            continue
        ready, _ = ray_tpu.wait(watch, num_returns=1, timeout=30.0, fetch_local=False)
        for r in ready:
            for op in ops:
                if r in op.in_flight:
                    op.complete(r)
                    break


def _drain(seed_bundles, ops, ctx) -> list:
    if not ops:
        return list(seed_bundles)
    return list(_pump(seed_bundles, ops, ctx))


def _apply_limit(bundles, limit) -> list:
    out, count = [], 0
    for ref, meta in bundles:
        if count >= limit:
            break
        if count + meta.num_rows <= limit:
            out.append((ref, meta))
            count += meta.num_rows
        else:
            take = limit - count
            refs = ray_tpu.remote(num_returns=2)(_slice_block_task).remote(ref, 0, take)
            new_meta = ray_tpu.get(refs[1])
            out.append((refs[0], new_meta))
            count = limit
    return out


def _zip_bundles(left, right) -> list:
    """Align row counts then zip pairwise. Requires equal total rows."""
    lrows = sum(m.num_rows for _, m in left)
    rrows = sum(m.num_rows for _, m in right)
    if lrows != rrows:
        raise ValueError(f"zip: datasets have different row counts ({lrows} vs {rrows})")
    lsplit = _resplit(left, [m.num_rows for _, m in left])
    rsplit = _resplit(right, [m.num_rows for _, m in left])
    out = []
    for (lref, _), (rref, _) in zip(lsplit, rsplit):
        refs = ray_tpu.remote(num_returns=2)(_zip_blocks_task).remote(lref, rref)
        out.append((refs[0], ray_tpu.get(refs[1])))
    return out


def _resplit(bundles, target_sizes) -> list:
    """Re-chunk bundles into blocks of the given row counts."""
    out = []
    cur = list(bundles)
    cur_off = 0
    for size in target_sizes:
        need = size
        parts = []
        while need > 0:
            ref, meta = cur[0]
            avail = meta.num_rows - cur_off
            take = min(avail, need)
            refs = ray_tpu.remote(num_returns=2)(_slice_block_task).remote(ref, cur_off, cur_off + take)
            parts.append(refs[0])
            need -= take
            cur_off += take
            if cur_off >= meta.num_rows:
                cur.pop(0)
                cur_off = 0
        if len(parts) == 1:
            block_ref = parts[0]
        else:
            block_ref = ray_tpu.remote(num_returns=1)(
                lambda *bs: BlockAccessor.concat(list(bs))
            ).remote(*parts)
        nrows = size
        from ray_tpu.data.block import BlockMetadata

        out.append((block_ref, BlockMetadata(num_rows=nrows, size_bytes=0)))
    return out
