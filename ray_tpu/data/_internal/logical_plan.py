"""Lazy logical plan for ray_tpu.data.

Analog of the reference's logical operators + planner
(python/ray/data/_internal/logical/, _internal/planner/): a Dataset holds a
chain of LogicalOp nodes; at execution time consecutive one-to-one transforms
are fused into single tasks (the reference's OperatorFusionRule) and the chain
is lowered to physical operators for the streaming executor.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional


@dataclasses.dataclass
class LogicalOp:
    name: str
    input_op: Optional["LogicalOp"]


@dataclasses.dataclass
class InputData(LogicalOp):
    """Already-materialized (ref, metadata) bundles."""

    bundles: list = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Read(LogicalOp):
    read_tasks: list = dataclasses.field(default_factory=list)  # list[ReadTask]
    ray_remote_args: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class MapTransform(LogicalOp):
    """One-to-one block transform: fn(Block) -> Block. Covers map_batches,
    map, flat_map, filter, select/drop/rename — all fusable."""

    block_fn: Callable = None  # type: ignore[assignment]
    compute: Any = None  # None (tasks) or ActorPoolStrategy
    ray_remote_args: dict = dataclasses.field(default_factory=dict)
    fn_constructor: Optional[Callable] = None  # for callable-class UDFs on actors


@dataclasses.dataclass
class AllToAll(LogicalOp):
    """Barrier op: fn(list[(ref, meta)], ctx) -> list[(ref, meta)]."""

    bulk_fn: Callable = None  # type: ignore[assignment]
    num_outputs: Optional[int] = None


@dataclasses.dataclass
class Limit(LogicalOp):
    limit: int = 0


@dataclasses.dataclass
class Union(LogicalOp):
    extra_inputs: list = dataclasses.field(default_factory=list)  # list[LogicalOp]


@dataclasses.dataclass
class Zip(LogicalOp):
    other: LogicalOp = None  # type: ignore[assignment]


def fuse_map_chain(op: LogicalOp) -> LogicalOp:
    """Fuse consecutive MapTransform nodes (same compute strategy) into one.

    Reference: _internal/logical/rules/operator_fusion.py — avoids
    materializing intermediate blocks between e.g. read->map->filter.
    """
    if op is None:
        return None
    inp = fuse_map_chain(op.input_op) if op.input_op is not None else None

    if isinstance(op, Union):
        op = dataclasses.replace(op, extra_inputs=[fuse_map_chain(e) for e in op.extra_inputs])
    if isinstance(op, Zip):
        op = dataclasses.replace(op, other=fuse_map_chain(op.other))

    if (
        isinstance(op, MapTransform)
        and isinstance(inp, MapTransform)
        and op.compute is None
        and inp.compute is None
        and op.fn_constructor is None
        and inp.fn_constructor is None
    ):
        f, g = inp.block_fn, op.block_fn

        def fused(block, _f=f, _g=g):
            return _g(_f(block))

        return MapTransform(
            name=f"{inp.name}->{op.name}",
            input_op=inp.input_op,
            block_fn=fused,
            ray_remote_args={**inp.ray_remote_args, **op.ray_remote_args},
        )
    return dataclasses.replace(op, input_op=inp) if op.input_op is not inp else op


def plan_to_chain(op: LogicalOp) -> list:
    """Linearize the (mostly linear) plan into an executor chain."""
    chain: list = []
    cur = op
    while cur is not None:
        chain.append(cur)
        cur = cur.input_op
    chain.reverse()
    return chain
