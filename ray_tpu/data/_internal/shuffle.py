"""All-to-all operations: shuffle, repartition, sort, grouped aggregation.

Analog of the reference's pull-based sort-shuffle
(python/ray/data/_internal/{shuffle.py,push_based_shuffle.py,sort.py}): a map
stage splits every input block into ``num_outputs`` partitions (random, hash,
or range assignment) and a reduce stage concatenates partition *i* across all
maps. Map and reduce both run as ray_tpu tasks; the reduce task receives its
input partitions as refs so blocks move peer-to-peer through the object store,
never through the driver.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import BlockAccessor, BlockMetadata


def _map_random(block, num_outputs, seed):
    return tuple(BlockAccessor.for_block(block).random_partition(num_outputs, seed))


def _map_hash(block, num_outputs, key):
    return tuple(BlockAccessor.for_block(block).hash_partition(key, num_outputs))


def _map_range(block, key, boundaries, descending):
    acc = BlockAccessor.for_block(block)
    parts = acc.range_partition(key, boundaries)
    if descending:
        parts = parts[::-1]
    return tuple(parts)


def _reduce_concat(shuffle_seed, *parts):
    out = BlockAccessor.concat(list(parts))
    if shuffle_seed is not None:
        out = BlockAccessor.for_block(out).random_shuffle(shuffle_seed)
    return out, BlockAccessor.for_block(out).get_metadata()


def _reduce_sorted(key, descending, *parts):
    out = BlockAccessor.concat(list(parts))
    out = BlockAccessor.for_block(out).sort(key, descending)
    return out, BlockAccessor.for_block(out).get_metadata()


def _map_single(block, map_fn, *args):
    """num_returns=1 wrapper: unwrap the 1-tuple the partition fns return."""
    return map_fn(block, *args)[0]


def _shuffle(bundles, map_fn, map_args, reduce_fn, reduce_args, num_outputs) -> list:
    if not bundles:
        return []
    if num_outputs == 1:
        map_tasks = [
            [ray_tpu.remote(num_returns=1)(_map_single).remote(ref, map_fn, *map_args)]
            for ref, _ in bundles
        ]
    else:
        map_tasks = [
            ray_tpu.remote(num_returns=num_outputs)(map_fn).remote(ref, *map_args)
            for ref, _ in bundles
        ]
    out = []
    for p in range(num_outputs):
        parts = [m[p] for m in map_tasks]
        refs = ray_tpu.remote(num_returns=2)(reduce_fn).remote(*reduce_args, *parts)
        out.append(refs)
    return [(refs[0], ray_tpu.get(refs[1])) for refs in out]


def _merge_parts(*parts):
    """Merge-stage combine (push-based shuffle): concat one round's shards
    of one output partition."""
    return BlockAccessor.concat(list(parts))


def push_based_shuffle(
    bundles,
    num_outputs: Optional[int] = None,
    seed: Optional[int] = None,
    merge_factor: Optional[int] = None,
) -> list:
    """Three-stage map -> merge -> reduce shuffle (reference:
    data/_internal/push_based_shuffle.py:1).

    The plain 2-stage shuffle gives every reducer fan-in = num_maps: at M
    map blocks each reducer concatenates M tiny shards, and the object
    store holds M*N intermediate objects at once. Here map outputs are
    combined by INTERMEDIATE merge tasks in rounds of ``merge_factor``
    (default ~sqrt(M)), so reducer fan-in drops to ceil(M/merge_factor)
    and merging pipelines with mapping — a merge round only depends on its
    own round's maps, so it starts while later rounds still run (our
    submitter-side dependency resolution provides the reference's
    pipelined scheduling for free)."""
    if not bundles:
        return []
    n = num_outputs or max(1, len(bundles))
    num_maps = len(bundles)
    factor = merge_factor or max(2, int(np.sqrt(num_maps)))
    if n == 1:
        map_tasks = [
            [ray_tpu.remote(num_returns=1)(_map_single).remote(ref, _map_random, n, seed)]
            for ref, _ in bundles
        ]
    else:
        map_tasks = [
            ray_tpu.remote(num_returns=n)(_map_random).remote(ref, n, seed)
            for ref, _ in bundles
        ]
    rounds = [map_tasks[i : i + factor] for i in range(0, num_maps, factor)]
    out = []
    sub = seed if seed is not None else None
    for p in range(n):
        merged = [
            ray_tpu.remote(num_returns=1)(_merge_parts).remote(*[m[p] for m in rnd])
            for rnd in rounds
        ]
        refs = ray_tpu.remote(num_returns=2)(_reduce_concat).remote(sub, *merged)
        out.append(refs)
    return [(refs[0], ray_tpu.get(refs[1])) for refs in out]


def random_shuffle(bundles, num_outputs: Optional[int] = None, seed: Optional[int] = None) -> list:
    from ray_tpu.data.context import DataContext

    n = num_outputs or max(1, len(bundles))
    sub = seed if seed is not None else None
    ctx = DataContext.get_current()
    # Default OFF, like the reference (RAY_DATA_PUSH_BASED_SHUFFLE): the
    # merge stage adds R*N tasks, which only pays for itself when reducer
    # fan-in would otherwise pressure the object store / network — i.e.
    # wide multi-node shuffles, not single-host runs (microbench tracks the
    # crossover as shuffle_{pull,push}_rows_per_s).
    if ctx.use_push_based_shuffle:
        return push_based_shuffle(bundles, num_outputs, seed)
    return _shuffle(bundles, _map_random, (n, seed), _reduce_concat, (sub,), n)


def repartition(bundles, num_outputs: int) -> list:
    """Even re-chunking without changing row order (reference: sort.py
    repartition path). Uses slice tasks rather than a full shuffle."""
    total = sum(m.num_rows for _, m in bundles)
    if total == 0 or not bundles:
        return bundles[:num_outputs] if bundles else []
    sizes = [total // num_outputs] * num_outputs
    for i in range(total % num_outputs):
        sizes[i] += 1
    sizes = [s for s in sizes if s > 0]
    from ray_tpu.data._internal.executor import _resplit

    return _resplit(bundles, sizes)


def sort(bundles, key: str, descending: bool = False, num_outputs: Optional[int] = None) -> list:
    """Sample-based range-partitioned sort (reference: sort.py — sample
    boundaries, range-partition maps, sorted merges)."""
    if not bundles:
        return []
    n = num_outputs or len(bundles)

    def _sample(block, key):
        acc = BlockAccessor.for_block(block)
        rows = acc.num_rows()
        if rows == 0:
            return np.array([])
        idx = np.linspace(0, rows - 1, min(20, rows)).astype(int)
        return np.asarray(acc.take_indices(idx).column(key).to_pylist())

    samples = ray_tpu.get([
        ray_tpu.remote(num_returns=1)(_sample).remote(ref, key) for ref, _ in bundles
    ])
    allv = np.sort(np.concatenate([s for s in samples if len(s)]))
    if len(allv) == 0:
        return bundles
    bidx = np.linspace(0, len(allv) - 1, n + 1).astype(int)[1:-1]
    boundaries = list(allv[bidx])
    if descending:
        pass  # partitions are reversed inside _map_range
    return _shuffle(
        bundles, _map_range, (key, boundaries, descending), _reduce_sorted, (key, descending), len(boundaries) + 1
    )


def hash_aggregate(bundles, key: Optional[str], agg_fns: list, num_outputs: Optional[int] = None) -> list:
    """Grouped aggregation via hash shuffle then per-partition combine
    (reference: grouped_data.py + _internal/planner/aggregate.py)."""
    if key is None:
        # Global aggregate: per-block partials combined on one reducer.
        partial_refs = [
            ray_tpu.remote(num_returns=1)(_partial_agg).remote(ref, key, agg_fns)
            for ref, _ in bundles
        ]
        refs = ray_tpu.remote(num_returns=2)(_final_agg).remote(key, agg_fns, *partial_refs)
        return [(refs[0], ray_tpu.get(refs[1]))]
    n = num_outputs or max(1, len(bundles))
    shuffled = _shuffle(bundles, _map_hash, (n, key), _reduce_concat, (None,), n)
    out = []
    for ref, _meta in shuffled:
        p = ray_tpu.remote(num_returns=1)(_partial_agg).remote(ref, key, agg_fns)
        refs = ray_tpu.remote(num_returns=2)(_final_agg).remote(key, agg_fns, p)
        out.append((refs[0], ray_tpu.get(refs[1])))
    return out


def _partial_agg(block, key, agg_fns):
    """Returns list of (group_key, [accumulator_per_agg]) pairs."""
    acc = BlockAccessor.for_block(block)
    groups: dict = {}
    for row in acc.iter_rows():
        gk = row[key] if key is not None else None
        gk = gk.item() if hasattr(gk, "item") else gk
        if gk not in groups:
            groups[gk] = [fn.init(gk) for fn in agg_fns]
        groups[gk] = [fn.accumulate(a, row) for fn, a in zip(agg_fns, groups[gk])]
    return list(groups.items())


def _final_agg(key, agg_fns, *partials):
    merged: dict = {}
    for partial in partials:
        for gk, accs in partial:
            if gk not in merged:
                merged[gk] = accs
            else:
                merged[gk] = [fn.merge(a, b) for fn, a, b in zip(agg_fns, merged[gk], accs)]
    rows = []
    for gk in sorted(merged, key=lambda x: (x is None, x)):
        row = {} if key is None else {key: gk}
        for fn, a in zip(agg_fns, merged[gk]):
            row[fn.name] = fn.finalize(a)
        rows.append(row)
    out = BlockAccessor.batch_to_block(rows)
    return out, BlockAccessor.for_block(out).get_metadata()
