"""ray_tpu.data — lazy, streaming, distributed datasets.

Analog of the reference's Ray Data (python/ray/data/): blocks are arrow
tables moved by ref through the object store; transforms build a lazy plan
executed by a streaming task/actor-pool executor; iteration yields numpy /
pandas / arrow / torch / device-sharded JAX batches.
"""

from ray_tpu.data import aggregate  # noqa: F401
from ray_tpu.data._internal.executor import ActorPoolStrategy  # noqa: F401
from ray_tpu.data.aggregate import AbsMax, AggregateFn, Count, Max, Mean, Min, Std, Sum  # noqa: F401
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata  # noqa: F401
from ray_tpu.data.context import DataContext  # noqa: F401
from ray_tpu.data.dataset import Dataset  # noqa: F401
from ray_tpu.data.dataset_pipeline import DatasetPipeline  # noqa: F401
from ray_tpu.data.grouped_data import GroupedData  # noqa: F401
from ray_tpu.data.iterator import DataIterator  # noqa: F401
from ray_tpu.data.read_api import (  # noqa: F401
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range,
    range_tensor,
    read_binary_files,
    read_csv,
    read_datasource,
    read_images,
    read_json,
    read_mongo,
    read_numpy,
    read_parquet,
    read_sql,
    read_text,
    read_tfrecords,
    read_webdataset,
)
from ray_tpu.data.datasource.partitioning import (  # noqa: F401
    DefaultFileMetadataProvider,
    FastFileMetadataProvider,
    FileMetadataProvider,
    ParquetMetadataProvider,
    Partitioning,
)

__all__ = [
    "ActorPoolStrategy",
    "AggregateFn",
    "Block",
    "BlockAccessor",
    "BlockMetadata",
    "Count",
    "DataContext",
    "DataIterator",
    "Dataset",
    "DatasetPipeline",
    "GroupedData",
    "Max",
    "Mean",
    "Min",
    "Std",
    "Sum",
    "from_arrow",
    "from_items",
    "from_numpy",
    "from_pandas",
    "range",
    "range_tensor",
    "read_binary_files",
    "read_csv",
    "read_datasource",
    "read_images",
    "read_json",
    "read_numpy",
    "read_parquet",
    "read_sql",
    "read_text",
    "read_tfrecords",
]
