"""Aggregation functions (reference: python/ray/data/aggregate.py —
AggregateFn with init/accumulate/merge/finalize protocol)."""

from __future__ import annotations

import math
from typing import Any, Callable, Optional


class AggregateFn:
    def __init__(
        self,
        init: Callable[[Any], Any],
        accumulate: Callable[[Any, dict], Any],
        merge: Callable[[Any, Any], Any],
        finalize: Callable[[Any], Any] = lambda a: a,
        name: str = "agg",
    ):
        self.init = init
        self.accumulate = accumulate
        self.merge = merge
        self.finalize = finalize
        self.name = name


def _val(row, on):
    v = row[on]
    return v.item() if hasattr(v, "item") else v


class Count(AggregateFn):
    def __init__(self):
        super().__init__(
            init=lambda k: 0,
            accumulate=lambda a, row: a + 1,
            merge=lambda a, b: a + b,
            name="count()",
        )


class Sum(AggregateFn):
    def __init__(self, on: str):
        super().__init__(
            init=lambda k: 0,
            accumulate=lambda a, row: a + _val(row, on),
            merge=lambda a, b: a + b,
            name=f"sum({on})",
        )


class Min(AggregateFn):
    def __init__(self, on: str):
        super().__init__(
            init=lambda k: None,
            accumulate=lambda a, row: _val(row, on) if a is None else min(a, _val(row, on)),
            merge=lambda a, b: b if a is None else (a if b is None else min(a, b)),
            name=f"min({on})",
        )


class Max(AggregateFn):
    def __init__(self, on: str):
        super().__init__(
            init=lambda k: None,
            accumulate=lambda a, row: _val(row, on) if a is None else max(a, _val(row, on)),
            merge=lambda a, b: b if a is None else (a if b is None else max(a, b)),
            name=f"max({on})",
        )


class Mean(AggregateFn):
    def __init__(self, on: str):
        super().__init__(
            init=lambda k: (0.0, 0),
            accumulate=lambda a, row: (a[0] + _val(row, on), a[1] + 1),
            merge=lambda a, b: (a[0] + b[0], a[1] + b[1]),
            finalize=lambda a: a[0] / a[1] if a[1] else float("nan"),
            name=f"mean({on})",
        )


class Std(AggregateFn):
    """Welford/Chan parallel variance (reference: aggregate.py Std)."""

    def __init__(self, on: str, ddof: int = 1):
        def accumulate(a, row):
            count, mean, m2 = a
            x = _val(row, on)
            count += 1
            delta = x - mean
            mean += delta / count
            m2 += delta * (x - mean)
            return (count, mean, m2)

        def merge(a, b):
            (na, ma, m2a), (nb, mb, m2b) = a, b
            if na == 0:
                return b
            if nb == 0:
                return a
            n = na + nb
            delta = mb - ma
            return (n, ma + delta * nb / n, m2a + m2b + delta * delta * na * nb / n)

        super().__init__(
            init=lambda k: (0, 0.0, 0.0),
            accumulate=accumulate,
            merge=merge,
            finalize=lambda a: math.sqrt(a[2] / (a[0] - ddof)) if a[0] > ddof else float("nan"),
            name=f"std({on})",
        )


class AbsMax(AggregateFn):
    def __init__(self, on: str):
        super().__init__(
            init=lambda k: 0,
            accumulate=lambda a, row: max(a, abs(_val(row, on))),
            merge=lambda a, b: max(a, b),
            name=f"abs_max({on})",
        )
