"""Cross-language task invocation: native (C/C++) functions on the task plane.

Reference: python/ray/cross_language.py (``ray.cross_language.java_function``
/ ``cpp_function``) — remote handles whose execution happens in another
language, with args/results in a language-agnostic serialization instead of
pickle. Here the native side is a C-ABI shared library (the image's C++
toolchain; see cpp/xlang_kernels.cc for the contract and example kernels):

    int <symbol>(const uint8_t* in, size_t in_len,
                 uint8_t** out, size_t* out_len);   // msgpack in/out
    void ray_tpu_xlang_free(uint8_t*);

``cpp_function(symbol, library)`` returns a RemoteFunction; calls ship
msgpack-encoded positional args across the ABI and the result is stored in
the object store as a format-"x" (msgpack) object — decodable by ANY
runtime, including the C++ client driver, with no pickle involved. Python
callers just see plain data from ``ray_tpu.get``.

Arg values must be msgpack-encodable (None/bool/int/float/str/bytes, lists,
and STRING-KEYED dicts thereof; ints must fit int64 — the kernel-side
decoder rejects anything else loudly, mirroring the constraint the
reference places on cross-language calls).
"""

from __future__ import annotations

import ctypes
import threading

_lib_lock = threading.Lock()
_lib_cache: dict = {}


class CrossLanguageError(RuntimeError):
    """The native function reported an error (its utf-8 message follows)."""


def _load(library_path: str):
    with _lib_lock:
        lib = _lib_cache.get(library_path)
        if lib is None:
            lib = ctypes.CDLL(library_path)
            lib.ray_tpu_xlang_free.argtypes = [ctypes.c_void_p]
            lib.ray_tpu_xlang_free.restype = None
            _lib_cache[library_path] = lib
        return lib


class CppFunctionInvoker:
    """The callable a worker executes: msgpack the args across the C ABI,
    wrap the result bytes as a format-"x" object (serialization.XLangBytes)
    so the stored object is language-agnostic."""

    def __init__(self, library_path: str, symbol: str):
        self.library_path = library_path
        self.symbol = symbol
        self.__name__ = f"cpp:{symbol}"
        self.__qualname__ = self.__name__

    def __call__(self, *args):
        import msgpack

        from ray_tpu._private.serialization import XLangBytes

        lib = _load(self.library_path)
        try:
            fn = getattr(lib, self.symbol)
        except AttributeError:
            raise CrossLanguageError(
                f"symbol {self.symbol!r} not found in {self.library_path}"
            ) from None
        fn.argtypes = [
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_size_t),
        ]
        fn.restype = ctypes.c_int
        payload = msgpack.packb(list(args), use_bin_type=True)
        out = ctypes.c_void_p()
        out_len = ctypes.c_size_t()
        rc = fn(payload, len(payload), ctypes.byref(out), ctypes.byref(out_len))
        try:
            data = ctypes.string_at(out, out_len.value) if out.value else b""
        finally:
            if out.value:
                lib.ray_tpu_xlang_free(out)
        if rc != 0:
            raise CrossLanguageError(
                f"{self.symbol} failed (rc={rc}): {data.decode('utf-8', 'replace')}"
            )
        return XLangBytes(data)


def cpp_function(symbol: str, library: str, **remote_options):
    """Remote handle for a native function: ``cpp_function("xlang_sum",
    "/path/libkernels.so").remote([1, 2, 3])``. ``remote_options`` are the
    usual task options (num_cpus=..., resources=...)."""
    import ray_tpu

    invoker = CppFunctionInvoker(library, symbol)
    if remote_options:
        return ray_tpu.remote(**remote_options)(invoker)
    return ray_tpu.remote(invoker)
