"""Resident channel loop — the worker side of compiled execution.

Installed on an actor worker by ``channel_loop_install`` (worker_main.py)
and run on a DEDICATED daemon thread (the analog of the reference running
accelerated-DAG loops on a background execution thread): the actor's main
exec queue stays free, so classic method calls keep working while the actor
participates in a compiled graph. Classic calls and compiled stages may
therefore run concurrently on the actor instance — the same hazard class as
``max_concurrency > 1``, opted into by mixing the two paths.

Per iteration, for each bound stage in topological order: block on the
stage's input channels -> execute the bound method on the live actor
instance -> write the result envelope to every output channel. No task
spec is decoded, no ObjectRef is allocated and no raylet RPC is issued —
the loop touches only channel memory and the doorbell pipe.

Device payloads (the MPMD pipeline's microbatch stream): a ``KIND_DEVICE``
input slot resolves through ``device_envelope.resolve`` (live array /
eager-pushed inbox payload / pull fallback) before the method runs, and on
an actor created with ``tensor_transport=`` a top-level ``jax.Array``
result is emitted as a descriptor slot with the payload streamed out of
band — no tensor crosses the host ring between stages. Per-stage
stall/busy/resolve counters feed the ``ray_tpu_pipeline_*`` instruments
(plain ints; ``channel_loop_stats`` RPC exposes the per-stage split for
bubble-fraction measurement) and loop exit reclaims any channel payloads
this loop still holds (``reclaim_scope`` — no leaked device buffers).

Error flow: an application exception becomes an error envelope for THAT
iteration only (it forwards stage-to-stage to the driver, which re-raises
it from ``CompiledDAGRef.get()``; the loop keeps running). A sticky poison
envelope (actor death, planted by the driver's monitor) likewise forwards
downstream, and a descriptor whose holder died resolves to the typed
``DeviceObjectLostError``/``ActorDiedError``. ``ChannelClosedError`` —
teardown or the loop's stop event — exits the loop and its thread.
"""

from __future__ import annotations

import logging
import threading
import time

from ray_tpu._private import serialization
from ray_tpu.experimental.channel.channel import (
    KIND_DEVICE,
    KIND_ERROR,
    KIND_VALUE,
    PIPELINE_STATS,
    ChannelClosedError,
    ChannelReader,
    ChannelWriter,
)
from ray_tpu.exceptions import TaskError

logger = logging.getLogger(__name__)


class _BoundStage:
    """One compiled DAG node bound to this actor: resolved method, input
    readers / constant args, and output writers."""

    def __init__(self, cw, wire: dict):
        self.label = wire["label"]
        self.hop_key = wire.get("hop_key") or self.label
        self.method = getattr(cw._actor_instance, wire["method"])
        # Positional args then sorted kwargs — the deterministic read order
        # both endpoints agree on (each arg has its own channel, so only
        # blocking order matters, not data ordering).
        self.args: list = []  # ("c", ChannelReader) | ("v", constant)
        for spec in wire["args"]:
            if spec[0] == "c":
                self.args.append(("c", ChannelReader(spec[1], cw)))
            else:
                self.args.append(("v", serialization.deserialize(spec[1])))
        self.kwargs: list = []  # (name, same spec shape)
        for name in sorted(wire.get("kwargs") or {}):
            spec = wire["kwargs"][name]
            if spec[0] == "c":
                self.kwargs.append((name, ("c", ChannelReader(spec[1], cw))))
            else:
                self.kwargs.append((name, ("v", serialization.deserialize(spec[1]))))
        self.writers = [ChannelWriter(desc, cw) for desc in wire["outputs"]]
        # Plain-int per-stage accounting (ns): read by channel_loop_stats
        # for bubble-fraction measurement, folded into the process-wide
        # PIPELINE_STATS for the ray_tpu_pipeline_* instruments. stall_ns
        # includes descriptor-resolve waits (upstream payload latency IS
        # pipeline stall); resolve_ns is the of-which breakdown. reset_ns
        # marks the last stats reset so an interval straddling it (a loop
        # blocked in read() when the driver resets) only charges its
        # post-reset portion to the new measurement window.
        self.stall_ns = 0
        self.busy_ns = 0
        self.resolve_ns = 0
        self.iters = 0
        self.reset_ns = 0

    def channel_ids(self) -> list[str]:
        cids = [ep.cid for kind, ep in self.args if kind == "c"]
        cids += [spec[1].cid for _, spec in self.kwargs if spec[0] == "c"]
        cids += [w.cid for w in self.writers]
        return cids

    def stats_dict(self) -> dict:
        return {
            "label": self.label,
            "iters": self.iters,
            "stall_ns": self.stall_ns,
            "busy_ns": self.busy_ns,
            "resolve_ns": self.resolve_ns,
        }


class ChannelLoop:
    """The resident loop for one compiled DAG on one actor worker."""

    def __init__(self, cw, loop_id: str, stages_wire: list):
        self.cw = cw
        self.loop_id = loop_id
        self._stop = threading.Event()
        self.stages = [_BoundStage(cw, wire) for wire in stages_wire]
        self.channel_ids = [cid for s in self.stages for cid in s.channel_ids()]
        # Device-payload emission is the actor-level tensor_transport
        # opt-in (PR 9 semantics): a plain actor's jax results keep riding
        # the ring as serialized envelopes.
        self.device_outputs = bool(getattr(cw, "_tensor_transport", ""))
        # Completion signal for rpc_channel_loop_stop (set threadsafe from
        # the exec thread when run() returns). Created on the IO loop.
        import asyncio

        self.exited = asyncio.Event()

    def stop(self):
        """Any-thread: ask the loop to exit; readers/writers observe the
        stop event within one poll interval."""
        self._stop.set()

    def run(self):
        """Dedicated-thread entry; runs until stop/teardown/close."""
        try:
            while not self._stop.is_set():
                for stage in self.stages:
                    self._run_stage(stage)
        except ChannelClosedError:
            pass  # teardown / stop: the normal exit path
        except BaseException:  # noqa: BLE001 — must not kill the exec queue
            logger.exception("compiled channel loop %s crashed", self.loop_id[:8])
        finally:
            # Reclaim channel payloads this loop created whose releases
            # never arrived (dead consumer, torn connection, teardown
            # mid-iteration): no leaked device buffers across teardown.
            try:
                from ray_tpu.experimental.device_object.manager import active_manager

                mgr = active_manager()
                if mgr is not None:
                    mgr.reclaim_scope(self.loop_id)
            except Exception:
                logger.exception("channel-payload reclaim failed")
            loop = self.cw._io.loop
            loop.call_soon_threadsafe(self.exited.set)

    def _read_input(self, stage: _BoundStage, reader: ChannelReader):
        """Read one input channel; returns (value, error_data, hop). A
        KIND_DEVICE slot resolves out of band; a resolution failure becomes
        this iteration's error (typed loss / death error serialized)."""
        t0 = time.perf_counter_ns()
        ekind, data, ehop = reader.read(stop=self._stop)
        now = time.perf_counter_ns()
        stage.stall_ns += now - max(t0, stage.reset_ns)
        PIPELINE_STATS.stall_ns += now - t0
        if ekind == KIND_ERROR:
            return None, data, ehop
        if ekind == KIND_DEVICE:
            from ray_tpu.experimental.channel import device_envelope

            t1 = time.perf_counter_ns()
            try:
                value = device_envelope.resolve(
                    self.cw,
                    data,
                    cid=reader.cid,
                    seq=reader.last_seq,
                    gate=reader.gate,
                    stop=self._stop,
                    consumer_release=not reader.shm,
                )
            except ChannelClosedError:
                raise
            except BaseException as e:  # noqa: BLE001 — typed loss flows on
                if self._stop.is_set():
                    raise ChannelClosedError(
                        f"channel {reader.label} stopped mid-resolve"
                    ) from None
                err = serialization.serialize(e).to_bytes()
                return None, err, ehop
            finally:
                # Resolve waits are upstream latency, i.e. stall — without
                # this a pipeline bottlenecked on payload delivery would
                # report a small bubble. resolve_ns is the of-which split.
                t2 = time.perf_counter_ns()
                dt = t2 - max(t1, stage.reset_ns)
                stage.resolve_ns += dt
                stage.stall_ns += dt
                PIPELINE_STATS.stall_ns += t2 - t1
            return value, None, ehop
        return serialization.deserialize(data), None, ehop

    def _run_stage(self, stage: _BoundStage):
        hop: dict | None = None
        error_data = None
        args = []
        kwargs = {}
        for kind, payload in stage.args:
            if kind == "v":
                args.append(payload)
                continue
            value, err, ehop = self._read_input(stage, payload)
            if ehop:
                hop = {**(hop or {}), **ehop}
            if err is not None:
                error_data = error_data or err
            args.append(value)
        for name, (kind, payload) in stage.kwargs:
            if kind == "v":
                kwargs[name] = payload
                continue
            value, err, ehop = self._read_input(stage, payload)
            if ehop:
                hop = {**(hop or {}), **ehop}
            if err is not None:
                error_data = error_data or err
            kwargs[name] = value
        if error_data is not None:
            # Upstream error (application failure or death poison): forward
            # it through every output channel without executing this stage.
            for w in stage.writers:
                w.write(KIND_ERROR, error_data, hop, stop=self._stop)
            return
        if hop is not None:
            hop[f"{stage.hop_key}_recv"] = time.monotonic()
        value = None
        data = None
        t_exec = time.perf_counter_ns()
        try:
            value = stage.method(*args, **kwargs)
            import inspect

            if inspect.iscoroutine(value):
                # Async actor methods run on the per-actor async loop, same
                # as classic calls (core_worker._run_actor_coroutine).
                value = self.cw._run_actor_coroutine(value)
            out_kind = KIND_VALUE
        except ChannelClosedError:
            raise
        except BaseException as e:  # noqa: BLE001 — app errors flow downstream
            out_kind = KIND_ERROR
            data = serialization.serialize(
                TaskError.from_exception(e, task_name=stage.label)
            ).to_bytes()
        stage.busy_ns += time.perf_counter_ns() - max(t_exec, stage.reset_ns)
        stage.iters += 1
        PIPELINE_STATS.microbatches += 1
        if hop is not None:
            hop[f"{stage.hop_key}_exec"] = time.monotonic()
        if out_kind == KIND_VALUE:
            from ray_tpu._private.core_worker import _maybe_jax_array

            # Result publication failures (unserializable return value,
            # device-payload registration) are THIS iteration's error, not
            # a loop crash — the DAG keeps serving, like app exceptions.
            try:
                if self.device_outputs and _maybe_jax_array(value):
                    from ray_tpu.experimental.channel import device_envelope

                    device_envelope.emit(
                        self.cw, value, stage.writers, scope=self.loop_id,
                        hop=hop, stop=self._stop,
                    )
                    return
                data = serialization.serialize(value).to_bytes()
            except ChannelClosedError:
                raise
            except BaseException as e:  # noqa: BLE001
                out_kind = KIND_ERROR
                data = serialization.serialize(
                    TaskError.from_exception(e, task_name=stage.label)
                ).to_bytes()
        for w in stage.writers:
            w.write(out_kind, data, hop, stop=self._stop)
