"""Resident channel loop — the worker side of compiled execution.

Installed on an actor worker by ``channel_loop_install`` (worker_main.py)
and run on a DEDICATED daemon thread (the analog of the reference running
accelerated-DAG loops on a background execution thread): the actor's main
exec queue stays free, so classic method calls keep working while the actor
participates in a compiled graph. Classic calls and compiled stages may
therefore run concurrently on the actor instance — the same hazard class as
``max_concurrency > 1``, opted into by mixing the two paths.

Per iteration, for each bound stage in topological order: block on the
stage's input channels -> execute the bound method on the live actor
instance -> write the result envelope to every output channel. No task
spec is decoded, no ObjectRef is allocated and no raylet RPC is issued —
the loop touches only channel memory and the doorbell pipe.

Error flow: an application exception becomes an error envelope for THAT
iteration only (it forwards stage-to-stage to the driver, which re-raises
it from ``CompiledDAGRef.get()``; the loop keeps running). A sticky poison
envelope (actor death, planted by the driver's monitor) likewise forwards
downstream. ``ChannelClosedError`` — teardown or the loop's stop event —
exits the loop and its thread.
"""

from __future__ import annotations

import logging
import threading
import time

from ray_tpu._private import serialization
from ray_tpu.experimental.channel.channel import (
    KIND_ERROR,
    KIND_VALUE,
    ChannelClosedError,
    ChannelReader,
    ChannelWriter,
)
from ray_tpu.exceptions import TaskError

logger = logging.getLogger(__name__)


class _BoundStage:
    """One compiled DAG node bound to this actor: resolved method, input
    readers / constant args, and output writers."""

    def __init__(self, cw, wire: dict):
        self.label = wire["label"]
        self.hop_key = wire.get("hop_key") or self.label
        self.method = getattr(cw._actor_instance, wire["method"])
        # Positional args then sorted kwargs — the deterministic read order
        # both endpoints agree on (each arg has its own channel, so only
        # blocking order matters, not data ordering).
        self.args: list = []  # ("c", ChannelReader) | ("v", constant)
        for spec in wire["args"]:
            if spec[0] == "c":
                self.args.append(("c", ChannelReader(spec[1], cw)))
            else:
                self.args.append(("v", serialization.deserialize(spec[1])))
        self.kwargs: list = []  # (name, same spec shape)
        for name in sorted(wire.get("kwargs") or {}):
            spec = wire["kwargs"][name]
            if spec[0] == "c":
                self.kwargs.append((name, ("c", ChannelReader(spec[1], cw))))
            else:
                self.kwargs.append((name, ("v", serialization.deserialize(spec[1]))))
        self.writers = [ChannelWriter(desc, cw) for desc in wire["outputs"]]

    def channel_ids(self) -> list[str]:
        cids = [ep.cid for kind, ep in self.args if kind == "c"]
        cids += [spec[1].cid for _, spec in self.kwargs if spec[0] == "c"]
        cids += [w.cid for w in self.writers]
        return cids


class ChannelLoop:
    """The resident loop for one compiled DAG on one actor worker."""

    def __init__(self, cw, loop_id: str, stages_wire: list):
        self.cw = cw
        self.loop_id = loop_id
        self._stop = threading.Event()
        self.stages = [_BoundStage(cw, wire) for wire in stages_wire]
        self.channel_ids = [cid for s in self.stages for cid in s.channel_ids()]
        # Completion signal for rpc_channel_loop_stop (set threadsafe from
        # the exec thread when run() returns). Created on the IO loop.
        import asyncio

        self.exited = asyncio.Event()

    def stop(self):
        """Any-thread: ask the loop to exit; readers/writers observe the
        stop event within one poll interval."""
        self._stop.set()

    def run(self):
        """Dedicated-thread entry; runs until stop/teardown/close."""
        try:
            while not self._stop.is_set():
                for stage in self.stages:
                    self._run_stage(stage)
        except ChannelClosedError:
            pass  # teardown / stop: the normal exit path
        except BaseException:  # noqa: BLE001 — must not kill the exec queue
            logger.exception("compiled channel loop %s crashed", self.loop_id[:8])
        finally:
            loop = self.cw._io.loop
            loop.call_soon_threadsafe(self.exited.set)

    def _run_stage(self, stage: _BoundStage):
        hop: dict | None = None
        error_data = None
        args = []
        kwargs = {}
        for kind, payload in stage.args:
            if kind == "v":
                args.append(payload)
                continue
            ekind, data, ehop = payload.read(stop=self._stop)
            if ehop:
                hop = {**(hop or {}), **ehop}
            if ekind == KIND_ERROR:
                error_data = error_data or data
                args.append(None)
            else:
                args.append(serialization.deserialize(data))
        for name, (kind, payload) in stage.kwargs:
            if kind == "v":
                kwargs[name] = payload
                continue
            ekind, data, ehop = payload.read(stop=self._stop)
            if ehop:
                hop = {**(hop or {}), **ehop}
            if ekind == KIND_ERROR:
                error_data = error_data or data
                kwargs[name] = None
            else:
                kwargs[name] = serialization.deserialize(data)
        if error_data is not None:
            # Upstream error (application failure or death poison): forward
            # it through every output channel without executing this stage.
            for w in stage.writers:
                w.write(KIND_ERROR, error_data, hop, stop=self._stop)
            return
        if hop is not None:
            hop[f"{stage.hop_key}_recv"] = time.monotonic()
        try:
            value = stage.method(*args, **kwargs)
            import inspect

            if inspect.iscoroutine(value):
                # Async actor methods run on the per-actor async loop, same
                # as classic calls (core_worker._run_actor_coroutine).
                value = self.cw._run_actor_coroutine(value)
            out_kind = KIND_VALUE
            data = serialization.serialize(value).to_bytes()
        except ChannelClosedError:
            raise
        except BaseException as e:  # noqa: BLE001 — app errors flow downstream
            out_kind = KIND_ERROR
            data = serialization.serialize(
                TaskError.from_exception(e, task_name=stage.label)
            ).to_bytes()
        if hop is not None:
            hop[f"{stage.hop_key}_exec"] = time.monotonic()
        for w in stage.writers:
            w.write(out_kind, data, hop, stop=self._stop)
