"""Channel — the compiled-graph data plane.

A ``Channel`` is a single-producer single-consumer bounded ring of slots
carved out of the node's existing shm arena (the same segment the object
store uses; see ``_private/store/arena.py``). The compiled-DAG executor
(``ray_tpu/dag/compiled.py``) allocates one channel per DAG edge so repeated
dispatch over a static graph moves values through shared memory with ZERO
raylet RPCs, zero task specs and zero ObjectRef allocations per iteration —
the analog of the reference lineage's accelerated-DAG channels
(python/ray/experimental/channel/).

Wire/memory protocol (see README.md in this package for the full story):

- ring header (64 bytes at the channel's arena offset): ``write_count`` u64,
  ``read_count`` u64, ``closed`` u64. Counts are monotonic; slot index is
  ``seq % num_slots``. The count bump is the publication point: the producer
  fills the slot COMPLETELY before bumping ``write_count`` (x86-TSO store
  ordering; the consumer never reads a slot at/past ``write_count``).
- slot: u32 payload length then a msgpack envelope ``[kind, data, hop]``
  (kind 0 = value, 1 = error, 2 = device descriptor; ``data`` =
  serialization.py bytes — for kind 2 a serialized ``DeviceObjectMeta``
  whose PAYLOAD moves out-of-band, see device_envelope.py; ``hop`` =
  optional hop-timing stamp dict). Length ``0xFFFFFFFF`` marks an OVERSIZE
  payload delivered out-of-band through the reader's side-channel (chunked
  ``channel_data`` RPCs, the compiled analog of the chunked push path).
- doorbell: after bumping ``write_count`` the producer fires a one-way
  ``channel_doorbell`` push frame at the READER's RPC server (the existing
  worker-to-worker pipe); the handler sets the reader's gate event. The
  doorbell is a latency optimization, not a correctness requirement — a
  blocked reader also re-polls the ring, backing off exponentially from
  ``_POLL_BASE_S`` up to the ``channel_poll_interval_ms`` config cap.
- cross-node fallback: when producer and consumer do not share the arena the
  ring is skipped entirely and every envelope rides the chunked
  ``channel_data`` path, with ``channel_query`` polls for backpressure.

Robustness: ``closed`` (set at teardown) makes blocked readers/writers raise
``ChannelClosedError`` instead of hanging; a ``channel_poison`` RPC plants a
sticky error envelope at a reader so actor death propagates a typed error
through every downstream channel; writes past the ring capacity
(``max_buffered_results`` slots) block the producer; reads honor a timeout.
"""

from __future__ import annotations

import logging
import struct
import threading
import time

import msgpack

from ray_tpu._private import flight_recorder
from ray_tpu._private.concurrency import any_thread, blocking
from ray_tpu.exceptions import RayTpuError

logger = logging.getLogger(__name__)


class _ChannelStats:
    """Plain-int channel counters — compiled iterations are the hottest
    loop in the runtime (built to shed per-iteration overhead), so writes
    must not pay an instrument lock or tag-dict per envelope. Folded into
    ray_tpu_channel_* instruments at metrics-flush cadence
    (self_metrics collector), like rpc.WIRE and lease_manager.LEASE_STATS.
    last_occupancy is the ring depth observed at the most recent sampled
    write (process-wide: a per-channel gauge tag would accumulate one stale
    series per torn-down channel forever)."""

    __slots__ = ("writes", "backpressure", "last_occupancy")

    def __init__(self):
        self.writes = 0
        self.backpressure = 0
        self.last_occupancy = 0


CHANNEL_STATS = _ChannelStats()


class _PipelineStats:
    """Plain-int pipeline counters fed by the resident loops (one stage
    iteration = one microbatch through that stage) and the descriptor
    resolver; folded into ``ray_tpu_pipeline_*`` instruments at metrics
    flush (same pattern as CHANNEL_STATS above). ``resolve_samples`` is a
    bounded deque of resolve latencies (seconds) drained into the
    ``ray_tpu_pipeline_resolve_latency_s`` histogram by the flush-time
    collector, so the hot path appends a float instead of paying the
    instrument lock per microbatch."""

    __slots__ = ("microbatches", "stall_ns", "resolve_samples")

    def __init__(self):
        import collections

        self.microbatches = 0
        self.stall_ns = 0
        self.resolve_samples = collections.deque(maxlen=512)


PIPELINE_STATS = _PipelineStats()

HEADER_SIZE = 64
_OFF_WRITE = 0
_OFF_READ = 8
_OFF_CLOSED = 16
_SIDE_MARKER = 0xFFFFFFFF
# Idle re-poll backoff: first miss waits _POLL_BASE_S, then doubles per idle
# round up to the channel_poll_interval_ms config cap. The doorbell (gate
# event) short-circuits any wait, so the cap bounds only doorbell LOSS
# recovery, and sustained idle converges to one wakeup per cap interval
# instead of 20/s per blocked reader on a 1-CPU box.
_POLL_BASE_S = 0.005
_FULL_POLL_S = 0.002
_CHUNK_BYTES = 512 * 1024

# Envelope kinds.
KIND_VALUE = 0
KIND_ERROR = 1
# Device-payload descriptor: the slot carries a ~300B DeviceObjectMeta; the
# payload itself moved out-of-band (p2p direct mailbox / collective pull /
# host fallback — experimental/channel/device_envelope.py).
KIND_DEVICE = 2


class ChannelError(RayTpuError):
    """Base error for the compiled-graph channel plane."""


class ChannelClosedError(ChannelError):
    """The channel was closed (teardown) or the endpoint is stopping."""


class ChannelTimeoutError(ChannelError, TimeoutError):
    """A channel read/write did not complete within its timeout."""


def make_descriptor(
    cid: str,
    *,
    arena: str | None,
    offset: int,
    num_slots: int,
    slot_size: int,
    reader_addr,
    label: str = "",
) -> dict:
    """Wire-form channel descriptor shared by both endpoints."""
    return {
        "cid": cid,
        "arena": arena,  # None => remote (no shared segment) — RPC fallback
        "offset": offset,
        "num_slots": num_slots,
        "slot_size": slot_size,
        "reader_addr": list(reader_addr),
        "label": label,
    }


def ring_bytes(num_slots: int, slot_size: int) -> int:
    return HEADER_SIZE + num_slots * slot_size


class _Gate:
    """Reader-side meeting point between the IO loop (doorbell / side-channel
    / poison RPC handlers) and the blocked reader thread. All state behind
    one private lock; methods never block."""

    def __init__(self):
        from ray_tpu._private.ids import BoundedIdSet

        self.event = threading.Event()
        self.lock = threading.Lock()
        self.parts: dict[int, dict] = {}  # seq -> {chunk_idx: bytes}
        self.done: dict[int, bytes] = {}  # seq -> assembled envelope bytes
        self.sticky: bytes | None = None  # poison envelope (actor death)
        self.closed = False
        # Recently-completed seqs: ``channel_data`` chunks are
        # at-least-once under connection blips (and chaos dup injection);
        # a duplicate arriving after its envelope completed — or after
        # pop() consumed it — used to re-open a forever-partial
        # reassembly, leaking memory AND inflating queued(), which is the
        # remote-mode writer's backpressure credit: enough duplicates and
        # the producer throttles on phantom queue depth. Tombstoned seqs
        # drop silently instead.
        self._completed = BoundedIdSet(cap=512)

    @any_thread
    def add_chunk(self, seq: int, idx: int, total: int, data: bytes):
        with self.lock:
            if seq in self._completed or seq in self.done:
                return  # duplicate of an already-assembled envelope
            parts = self.parts.setdefault(seq, {})
            parts[idx] = data
            if len(parts) == total:
                self.parts.pop(seq)
                self.done[seq] = b"".join(parts[i] for i in range(total))
                self._completed.add(seq)
        self.event.set()

    @any_thread
    def pop(self, seq: int) -> bytes | None:
        with self.lock:
            return self.done.pop(seq, None)

    @any_thread
    def queued(self) -> int:
        with self.lock:
            return len(self.done) + len(self.parts)

    @any_thread
    def poison(self, env: bytes):
        with self.lock:
            self.sticky = env
        self.event.set()

    @any_thread
    def close(self):
        self.closed = True
        self.event.set()


class ChannelRegistry:
    """Per-process registry of channel reader gates (one per CoreWorker).
    The ``rpc_channel_*`` handlers on CoreWorker dispatch into it."""

    def __init__(self):
        import collections

        self._gates: dict[str, _Gate] = {}
        self._lock = threading.Lock()
        # Torn-down channel ids: a doorbell / chunk frame still in flight at
        # teardown must not resurrect a gate nobody will ever drop again
        # (long-lived workers join many compiled DAGs). Bounded FIFO — cids
        # are random per-DAG, collisions across the horizon don't matter.
        self._dropped = collections.deque(maxlen=4096)
        self._dropped_set: set[str] = set()

    @any_thread
    def gate(self, cid: str) -> _Gate:
        with self._lock:
            gate = self._gates.get(cid)
            if gate is None:
                gate = self._gates[cid] = _Gate()
                if cid in self._dropped_set:
                    gate.closed = True  # late frame for a torn-down channel
            return gate

    @any_thread
    def gate_if_live(self, cid: str) -> _Gate | None:
        """RPC-handler entry: None for torn-down channels so late frames
        are dropped instead of recreating state."""
        with self._lock:
            if cid in self._dropped_set:
                return None
            gate = self._gates.get(cid)
            if gate is None:
                gate = self._gates[cid] = _Gate()
            return gate

    @any_thread
    def ring_doorbell(self, cid: str):
        gate = self.gate_if_live(cid)
        if gate is not None:
            gate.event.set()

    @any_thread
    def drop(self, cids) -> None:
        with self._lock:
            for cid in cids:
                gate = self._gates.pop(cid, None)
                if gate is not None:
                    gate.close()
                if cid not in self._dropped_set:
                    if len(self._dropped) == self._dropped.maxlen:
                        self._dropped_set.discard(self._dropped[0])
                    self._dropped.append(cid)
                    self._dropped_set.add(cid)


def pack_envelope(kind: int, data: bytes, hop: dict | None = None) -> bytes:
    return msgpack.packb([kind, data, hop], use_bin_type=True)


def unpack_envelope(env: bytes) -> tuple[int, bytes, dict | None]:
    kind, data, hop = msgpack.unpackb(env, raw=False)
    return kind, data, hop


class _Endpoint:
    """State shared by both channel endpoints: descriptor fields, the arena
    view when this process shares the ring's segment, and the gate."""

    def __init__(self, desc: dict, cw):
        self.desc = desc
        self.cw = cw
        self.cid = desc["cid"]
        self.label = desc.get("label") or self.cid[:8]
        self.num_slots = int(desc["num_slots"])
        self.slot_size = int(desc["slot_size"])
        self.slot_cap = self.slot_size - 4
        self.base = int(desc["offset"])
        arena = cw.store.arena
        self.shm = bool(desc.get("arena")) and getattr(arena, "name", None) == desc["arena"]
        self._view = arena.view if self.shm else None
        self.gate = cw.channels.gate(self.cid)
        # Fallback re-poll cap (doorbell loss recovery); see _POLL_BASE_S.
        self._poll_cap_s = max(
            _POLL_BASE_S,
            getattr(cw.cfg, "channel_poll_interval_ms", 50) / 1000.0,
        )

    # ---- ring header accessors (shm mode only) ----

    def _u64(self, off: int) -> int:
        return struct.unpack_from("<Q", self._view, self.base + off)[0]

    def _set_u64(self, off: int, value: int):
        struct.pack_into("<Q", self._view, self.base + off, value)

    def _closed(self) -> bool:
        if self.gate.closed:
            return True
        return self.shm and self._u64(_OFF_CLOSED) != 0

    def _slot_off(self, seq: int) -> int:
        return self.base + HEADER_SIZE + (seq % self.num_slots) * self.slot_size

    def _reader_client(self):
        return self.cw._owner_client(tuple(self.desc["reader_addr"]))

    def _check_closed(self, stop) -> None:
        if self._closed() or (stop is not None and stop.is_set()):
            raise ChannelClosedError(f"channel {self.label} is closed")


class ChannelWriter(_Endpoint):
    """The producing endpoint. Single producer per channel by contract (the
    one exception — the driver poisoning a dead producer's consumers — goes
    through the reader's gate, never the ring, so the contract holds)."""

    def __init__(self, desc: dict, cw):
        super().__init__(desc, cw)
        self._next_seq = self._u64(_OFF_WRITE) if self.shm else 0
        # Remote-mode credit: envelopes sent since the reader's queue depth
        # was last observed. A query RPC is only paid when the local credit
        # is exhausted (bounded-credit, like the push path's admission),
        # not per write.
        self._inflight = 0
        # Device payloads published through this writer whose holder pin is
        # released by RING ADVANCE instead of a consumer frame: (seq, oid)
        # FIFO, reaped by device_envelope.emit once the consumer's
        # read_count proves the slot was popped AND its resolution is over
        # (the consumer pops seq+1 only after fully processing seq, so
        # everything <= read_count - 2 is done). shm mode only.
        self.payload_fifo = None  # lazily a deque on first device emit

    @any_thread
    def next_seq(self) -> int:
        """The sequence number the NEXT write() will publish under. Stable
        between a call here and the following write (single producer, one
        writing thread): device_envelope.emit uses it to key the eager
        out-of-band payload push to the slot it belongs to."""
        return self._u64(_OFF_WRITE) if self.shm else self._next_seq

    @blocking
    def write(self, kind: int, data: bytes, hop: dict | None = None,
              timeout: float | None = None, stop=None,
              doorbell: bool = True) -> None:
        """Publish one envelope; blocks while the ring is full (backpressure)
        up to ``timeout`` (None = forever). Raises ChannelClosedError if the
        channel closes (teardown / stop event) while blocked.
        ``doorbell=False`` skips the wakeup frame — device emits send the
        payload frame right after the slot publish and ITS deposit rings
        the reader's gate (one frame on the wire instead of two)."""
        env = pack_envelope(kind, data, hop)
        deadline = None if timeout is None else time.monotonic() + timeout
        if self.shm:
            self._write_shm(env, deadline, stop, doorbell)
        else:
            self._write_remote(env, deadline, stop)
        # Plain-int accounting per write; the flight event and occupancy
        # probe are 1-in-64 sampled (channel_block fires unsampled — it is
        # rare and is the signal that matters).
        writes = CHANNEL_STATS.writes = CHANNEL_STATS.writes + 1
        if writes & 63 == 0:
            flight_recorder.record("channel_write", f"{self.label}:n={writes}")
            if self.shm:
                CHANNEL_STATS.last_occupancy = (
                    self._u64(_OFF_WRITE) - self._u64(_OFF_READ)
                )

    @blocking
    def wait_writable(self, timeout: float | None = None, stop=None) -> None:
        """Block until the next write() cannot block on backpressure.
        Multi-channel producers (the driver's execute() fan-out) reserve
        space on EVERY channel first so a full ring discovered halfway
        through a batch of writes cannot leave the channels desynchronized
        (space only grows between this check and the write: the channel is
        single-producer and the one consumer only drains)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        if self.shm:
            while self._u64(_OFF_WRITE) - self._u64(_OFF_READ) >= self.num_slots:
                self._wait_tick(deadline, stop, _FULL_POLL_S)
            self._check_closed(stop)
        else:
            self._remote_credit_wait(deadline, stop)

    def _wait_tick(self, deadline, stop, interval: float):
        self._check_closed(stop)
        if deadline is not None and time.monotonic() >= deadline:
            raise ChannelTimeoutError(
                f"write to channel {self.label} timed out (ring full: "
                f"{self.num_slots} results buffered and unconsumed)"
            )
        time.sleep(interval)

    def _write_shm(self, env: bytes, deadline, stop, doorbell: bool = True):
        if self._u64(_OFF_WRITE) - self._u64(_OFF_READ) >= self.num_slots:
            # Backpressure entry (once per blocked write, not per poll tick).
            flight_recorder.record("channel_block", self.label)
            CHANNEL_STATS.backpressure += 1
        while self._u64(_OFF_WRITE) - self._u64(_OFF_READ) >= self.num_slots:
            self._wait_tick(deadline, stop, _FULL_POLL_S)
        self._check_closed(stop)
        seq = self._u64(_OFF_WRITE)
        off = self._slot_off(seq)
        if len(env) <= self.slot_cap:
            struct.pack_into("<I", self._view, off, len(env))
            self._view[off + 4 : off + 4 + len(env)] = env
        else:
            # Oversize: ship the envelope through the reader's side-channel
            # (chunked, acked), then publish a marker slot.
            self._send_chunks(seq, env)
            struct.pack_into("<I", self._view, off, _SIDE_MARKER)
        # Publication point: slot contents are fully written before the
        # count bump makes them visible to the consumer.
        self._set_u64(_OFF_WRITE, seq + 1)
        self._next_seq = seq + 1
        if doorbell:
            self._doorbell()

    def _write_remote(self, env: bytes, deadline, stop):
        self._remote_credit_wait(deadline, stop)
        seq = self._next_seq
        self._send_chunks(seq, env)
        self._next_seq = seq + 1
        self._inflight += 1

    def _remote_credit_wait(self, deadline, stop):
        """Honor the num_slots bound without a query RPC per write: only
        when the local credit runs out is the reader's actual queue depth
        fetched (consumption shrinks it); bounded-credit, like the push
        path's admission control."""
        self._check_closed(stop)
        if self._inflight < self.num_slots:
            return
        client = self._reader_client()
        while True:
            try:
                resp = client.call("channel_query", {"cid": self.cid}, timeout=10)
            except Exception as e:
                raise ChannelClosedError(
                    f"reader of channel {self.label} unreachable: {e!r}"
                ) from None
            if resp.get("closed"):
                raise ChannelClosedError(f"channel {self.label} is closed")
            self._inflight = resp.get("queued", 0)
            if self._inflight < self.num_slots:
                return
            self._wait_tick(deadline, stop, 0.01)

    def _send_chunks(self, seq: int, env: bytes):
        """Chunked, acked delivery of one envelope into the reader's gate —
        the compiled-graph ride on the chunked push-path shape (bounded
        frames, receiver reassembles, last chunk completes the record)."""
        client = self._reader_client()
        total = max(1, (len(env) + _CHUNK_BYTES - 1) // _CHUNK_BYTES)
        try:
            for i in range(total):
                resp = client.call(
                    "channel_data",
                    {
                        "cid": self.cid,
                        "seq": seq,
                        "idx": i,
                        "total": total,
                        "data": env[i * _CHUNK_BYTES : (i + 1) * _CHUNK_BYTES],
                    },
                    timeout=30,
                )
        except Exception as e:
            raise ChannelClosedError(
                f"side-channel delivery on {self.label} failed: {e!r}"
            ) from None
        if resp.get("closed"):
            raise ChannelClosedError(f"channel {self.label} is closed")

    def _doorbell(self):
        """One-way wakeup frame at the reader; loss is benign (readers
        re-poll the ring, backing off to the channel_poll_interval_ms cap)."""
        try:
            client = self._reader_client()
            fut = self.cw._io.spawn(
                client.apush("channel_doorbell", {"cid": self.cid})
            )
            fut.add_done_callback(lambda f: f.cancelled() or f.exception())
        except Exception:
            pass


class ChannelReader(_Endpoint):
    """The consuming endpoint (single consumer per channel)."""

    def __init__(self, desc: dict, cw):
        super().__init__(desc, cw)
        self._next_seq = self._u64(_OFF_READ) if self.shm else 0
        # Sequence number of the most recently consumed envelope — the key
        # device_envelope.resolve uses to find the eager-pushed payload for
        # a KIND_DEVICE slot.
        self.last_seq = -1

    @blocking
    def read(self, timeout: float | None = None, stop=None) -> tuple[int, bytes, dict | None]:
        """Block until the next envelope is available; returns
        ``(kind, data, hop)``. Honors ``timeout`` (ChannelTimeoutError),
        channel close and the caller's stop event (ChannelClosedError), and
        sticky poison (returns the planted error envelope). A doorbell (the
        gate event) wakes the wait immediately; the fallback re-poll backs
        off exponentially from _POLL_BASE_S to the channel_poll_interval_ms
        cap while idle."""
        deadline = None if timeout is None else time.monotonic() + timeout
        idle = 0
        while True:
            env = self._try_consume()
            if env is not None:
                return unpack_envelope(env)
            if self.gate.sticky is not None:
                return unpack_envelope(self.gate.sticky)
            self._check_closed(stop)
            if deadline is not None and time.monotonic() >= deadline:
                raise ChannelTimeoutError(f"read on channel {self.label} timed out")
            self.gate.event.clear()
            # Re-check between clear and wait: a doorbell landing in that
            # window must not be lost for a full poll interval.
            env = self._try_consume()
            if env is not None:
                return unpack_envelope(env)
            if self.gate.sticky is not None:
                return unpack_envelope(self.gate.sticky)
            poll = min(_POLL_BASE_S * (1 << min(idle, 16)), self._poll_cap_s)
            idle += 1
            remaining = None if deadline is None else deadline - time.monotonic()
            self.gate.event.wait(
                poll if remaining is None else max(0.0, min(poll, remaining))
            )

    def _try_consume(self) -> bytes | None:
        if self.shm:
            seq = self._u64(_OFF_READ)
            if self._u64(_OFF_WRITE) <= seq:
                return None
            off = self._slot_off(seq)
            length = struct.unpack_from("<I", self._view, off)[0]
            if length == _SIDE_MARKER:
                env = self.gate.pop(seq)
                if env is None:
                    return None  # side-channel chunks still in flight
            else:
                env = bytes(self._view[off + 4 : off + 4 + length])
            self._set_u64(_OFF_READ, seq + 1)
            self._next_seq = seq + 1
            self.last_seq = seq
            return env
        env = self.gate.pop(self._next_seq)
        if env is not None:
            self.last_seq = self._next_seq
            self._next_seq += 1
        return env
