"""Device-payload envelopes (``KIND_DEVICE``) — the descriptor channel plane.

A channel slot is 64 KiB by default and the compiled-graph hot loop moves
msgpack bytes through it; a device-resident ``jax.Array`` does not belong
there (serializing it through the ring is a host copy per hop — on TPU a
D2H transfer per microbatch). Instead the slot carries a compact
``DeviceObjectMeta`` descriptor (~300 B, fits any ring slot) and the
payload moves OUT OF BAND:

- **emit** (producer): register the array as a transient channel payload
  with the process's DeviceObjectManager (this process is the holder; pins
  = number of consumers). On an shm edge the ``KIND_DEVICE`` envelope slot
  is published FIRST with the doorbell suppressed, then the serialized
  payload is eager-pushed at the remote reader's p2p direct mailbox keyed
  ``chdev/<cid>/<seq>`` (one-way frames on the existing worker pipe) and
  the deposit's completion rings the reader's gate — one frame both
  delivers the bytes and wakes the reader, and because the slot was
  already visible when the gate rang, the wakeup can never beat the
  publication. (Publishing in the other order would let the deposit's
  wakeup fire before the slot exists, putting the reader back to sleep
  for up to a full poll cap.) Remote-mode edges go payload-first: the
  envelope's own chunked delivery is the wakeup there.
- **resolve** (consumer): same-process holder → the LIVE array, zero
  copies; remote holder → take the eager payload from the inbox (steady
  state: already there); missed grace window → fall back to the PR 9 pull
  path (``resolve.resolve_meta``: shared collective group p2p, else host
  fallback), which also surfaces the typed ``DeviceObjectLostError``
  naming the holder when the producing stage is dead. A sticky poison
  envelope (``ActorDiedError`` planted by the compiled DAG's monitor) or
  the loop's stop event aborts the wait immediately.
- **release**: after resolving, the consumer drops its pin on the holder
  (locally, or a one-way ``devobj_release`` frame) — the last pin frees
  the device buffers. Lost release frames are reclaimed when the creating
  loop / DAG tears down (``reclaim_scope``), so no device buffer leaks
  across teardown.

On this CPU testbed the out-of-band wire is the host p2p mailbox — a
correctness stand-in, exactly like the device-object plane's collective
path (see p2p.py): the claim the counters certify is zero payload traffic
through the shm OBJECT STORE and zero host-fallback transfers, and the
seam to swap in an ICI/DMA hop is ``p2p.direct_send``/``direct_recv``.
"""

from __future__ import annotations

import logging
import time

from ray_tpu._private import flight_recorder, serialization
from ray_tpu._private.concurrency import any_thread, blocking
from ray_tpu.exceptions import DeviceObjectLostError
from ray_tpu.experimental.channel.channel import (
    _OFF_READ,
    KIND_DEVICE,
    PIPELINE_STATS,
    unpack_envelope,
)

logger = logging.getLogger(__name__)

# How long a consumer waits for the eager-pushed payload before falling
# back to the pull path. Steady state never waits (the payload frame is
# pushed right behind the slot publish, and its deposit is what wakes the
# reader); the window only matters when the push frame was lost or the
# producer died mid-hop — and poison / stop aborts it early.
_EAGER_GRACE_S = 5.0


def payload_key(cid: str, seq: int) -> str:
    """Inbox key for the eager payload of channel ``cid``'s slot ``seq``.
    Derivable by both endpoints without widening the descriptor."""
    return f"chdev/{cid}/{seq}"


@blocking
def emit(cw, value, writers, scope: str, hop=None, stop=None, timeout=None):
    """Publish ``value`` (a jax.Array) as a device descriptor through every
    ``ChannelWriter`` in ``writers`` (they all carry the same iteration's
    result — a stage's output fan-out or one driver-input projection).

    Steady-state wire cost per shm edge is ONE one-way frame: the payload
    push lands right after the slot publish and its deposit rings the
    reader's gate (no separate doorbell), and the holder pin is released by
    RING ADVANCE — once the consumer's read_count shows slot ``seq`` popped
    and a LATER slot popped too, its resolution is over (the SPSC loop pops
    seq+1 only after fully processing seq), so the producer reaps the pin
    locally instead of the consumer paying a release frame."""
    import collections

    from ray_tpu.experimental.device_object.manager import DEVOBJ_STATS

    mgr = cw._device_manager()
    own_addr = tuple(cw.address)
    # Everything fallible happens BEFORE the first slot write: once slots
    # start publishing, a mid-loop failure would leave this iteration
    # half-fanned-out and the caller's error-envelope conversion would
    # desynchronize iteration pairing.
    meta = mgr.create_channel_payload(value, pins=len(writers), scope=scope)
    try:
        env_bytes = serialization.serialize(meta).to_bytes()
        wire = None
        if any(not w.shm or tuple(w.desc["reader_addr"]) != own_addr
               for w in writers):
            wire = serialization.dumps(value)
    except BaseException:
        mgr.free(meta.object_id)
        raise
    for w in writers:
        local = tuple(w.desc["reader_addr"]) == own_addr
        if w.shm:
            seq = w.next_seq()
            w.write(KIND_DEVICE, env_bytes, hop, timeout=timeout, stop=stop,
                    doorbell=local)
            if not local:
                p2p_direct_send(
                    cw, tuple(w.desc["reader_addr"]), payload_key(w.cid, seq), wire
                )
                DEVOBJ_STATS.chan_sends += 1
                flight_recorder.record(
                    "chan_devobj_send", f"{w.cid[:8]}:{seq}:{meta.nbytes}"
                )
            if w.payload_fifo is None:
                w.payload_fifo = collections.deque()
            w.payload_fifo.append((seq, meta.object_id))
            _reap(mgr, w)
        else:
            # Remote-mode (no shared arena): payload first — the envelope's
            # own chunked delivery is the wakeup — and the consumer releases
            # the pin with a frame (no ring header to prove consumption).
            seq = w.next_seq()
            p2p_direct_send(
                cw, tuple(w.desc["reader_addr"]), payload_key(w.cid, seq), wire
            )
            DEVOBJ_STATS.chan_sends += 1
            flight_recorder.record(
                "chan_devobj_send", f"{w.cid[:8]}:{seq}:{meta.nbytes}"
            )
            w.write(KIND_DEVICE, env_bytes, hop, timeout=timeout, stop=stop)
    return meta


def _reap(mgr, writer) -> None:
    """Release pins for every payload whose slot the consumer has provably
    finished with: read_count - 2 is the newest seq whose RESOLUTION is
    guaranteed complete (read_count - 1 may still be mid-resolve)."""
    fifo = writer.payload_fifo
    if not fifo:
        return
    done_until = writer._u64(_OFF_READ) - 2
    while fifo and fifo[0][0] <= done_until:
        _seq, oid = fifo.popleft()
        mgr.release_pin(oid)


def p2p_direct_send(cw, addr, key, data):
    from ray_tpu.util.collective.p2p import direct_send

    direct_send(cw, addr, key, data)


@blocking
def resolve(cw, env_data: bytes, *, cid: str, seq: int, gate=None, stop=None,
            deadline=None, consumer_release: bool = False):
    """Turn a ``KIND_DEVICE`` envelope back into the live value. Raises the
    typed loss/death error on failure (the caller turns it into an error
    envelope or surfaces it to ``get()``). ``consumer_release`` is True
    only for remote-mode (no shared arena) channels — shm consumers never
    pay a release frame; the producer reaps the pin off ring advance."""
    from ray_tpu.experimental.device_object.manager import DEVOBJ_STATS
    from ray_tpu.experimental.device_object.resolve import resolve_meta
    from ray_tpu.util.collective.p2p import direct_recv

    t0 = time.monotonic()
    meta = serialization.deserialize(env_data)
    if tuple(meta.holder_addr) == tuple(cw.address):
        # Same process (stage chained onto itself, or a driver round trip):
        # the live array, zero payload copies. The producer-side ring reap
        # releases the pin.
        value = resolve_meta(cw, meta, deadline)
        if consumer_release:
            release(cw, meta)
        _account(cid, seq, "local", t0)
        return value

    def aborted() -> bool:
        if stop is not None and stop.is_set():
            return True
        return gate is not None and (gate.sticky is not None or gate.closed)

    grace = _EAGER_GRACE_S
    if deadline is not None:
        grace = max(0.0, min(grace, deadline - time.monotonic()))
    data = direct_recv(cw, payload_key(cid, seq), grace, abort_check=aborted)
    if data is not None:
        value = serialization.loads(data)
        if consumer_release:
            release(cw, meta)
        DEVOBJ_STATS.chan_recvs += 1
        _account(cid, seq, "inbox", t0)
        return value
    if aborted():
        # Teardown or poison while waiting: surface the planted typed error
        # (ActorDiedError naming the dead stage) over a generic loss.
        if gate is not None and gate.sticky is not None:
            _kind, err_data, _hop = unpack_envelope(gate.sticky)
            err = serialization.deserialize(err_data)
            if isinstance(err, BaseException):
                raise err
        raise DeviceObjectLostError(meta.object_id, holder=meta.holder_label())
    # Grace expired with the producer possibly alive (lost frame, slow IO
    # loop): the pull path still finds the pinned payload on the holder —
    # and surfaces the typed loss naming the holder when it is dead.
    value = resolve_meta(cw, meta, deadline)
    if consumer_release:
        release(cw, meta)
    _account(cid, seq, "pull", t0)
    return value


def _account(cid: str, seq: int, path: str, t0: float) -> None:
    dt = time.monotonic() - t0
    PIPELINE_STATS.resolve_samples.append(dt)
    flight_recorder.record("chan_devobj_recv", f"{cid[:8]}:{seq}:{path}")


@any_thread
def release(cw, meta) -> None:
    """Drop this consumer's pin on the holder. Local holders release
    synchronously; remote ones get a one-way frame (off the hot path —
    a lost frame is reclaimed at loop/DAG teardown via reclaim_scope)."""
    from ray_tpu.experimental.device_object.manager import active_manager

    if tuple(meta.holder_addr) == tuple(cw.address):
        mgr = active_manager()
        if mgr is not None:
            mgr.release_pin(meta.object_id)
        return
    client = cw._owner_client(tuple(meta.holder_addr))

    async def _push():
        try:
            await client.apush("devobj_release", {"object_id": meta.object_id})
        except Exception:
            pass

    cw._io.spawn(_push())
