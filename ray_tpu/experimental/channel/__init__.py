"""ray_tpu.experimental.channel — shm channel plane for compiled graphs.

See README.md in this directory for the slot/doorbell protocol and its
failure semantics; ``ray_tpu/dag/compiled.py`` is the main consumer.
"""

from ray_tpu.experimental.channel.channel import (  # noqa: F401
    KIND_DEVICE,
    KIND_ERROR,
    KIND_VALUE,
    ChannelClosedError,
    ChannelError,
    ChannelReader,
    ChannelRegistry,
    ChannelTimeoutError,
    ChannelWriter,
    make_descriptor,
    pack_envelope,
    ring_bytes,
    unpack_envelope,
)

__all__ = [
    "ChannelError",
    "ChannelClosedError",
    "ChannelTimeoutError",
    "ChannelReader",
    "ChannelRegistry",
    "ChannelWriter",
    "KIND_DEVICE",
    "KIND_ERROR",
    "KIND_VALUE",
    "make_descriptor",
    "pack_envelope",
    "ring_bytes",
    "unpack_envelope",
]
