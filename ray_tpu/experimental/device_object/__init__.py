"""Device object plane — device-resident ``jax.Array`` objects, passed by
reference with out-of-band collective transfer.

The base object plane (``_private/serialization.py``) DMAs every device
array host-side on ``put`` and back on ``get``: an actor-to-actor tensor
handoff (learner→sampler weight sync, KV-cache migration, pipeline
activations) pays two host copies plus shm traffic even when both endpoints
sit on the same mesh. This plane keeps the array ON its devices and seals
only a small :class:`DeviceObjectMeta` descriptor into the normal store —
the ``ObjectRef`` stays first-class (refcounted, waitable, passable,
reconstruct-free), while the payload moves out of band:

- **same process** — the consumer gets the live ``jax.Array`` back, zero
  copies of the payload anywhere;
- **same mesh** — a ``util/collective`` group p2p ``send``/``recv`` moves
  it holder→consumer (CPU ring backend in tests, tpu backend on hardware),
  sharding layout preserved;
- **no shared group / cross-mesh** — the holder materializes a host copy
  (inline for small arrays, its node's shm arena otherwise) and the
  consumer resolves through the existing host-shm path, transparently.

Opt in per value with ``ray_tpu.put(arr, tensor_transport="collective")``
or per actor with ``@ray_tpu.remote(tensor_transport="collective")`` —
every top-level ``jax.Array`` such an actor returns stays device-resident
on the actor, which is the HOLDER; the caller stays the owner and the
normal ownership protocol frees the device buffers when the last ref
drops. Under memory pressure (``devobj_resident_limit_bytes``) the holder
spills device→host into the arena and restores on the next resolve; holder
death surfaces :class:`~ray_tpu.exceptions.DeviceObjectLostError` naming
the holder, falling back to the spilled/host copy when one exists.

Reference direction: Ray GPU objects / `tensor_transport=` direct tensor
transport over ``ray.util.collective``; Podracer (arXiv:2104.06272) is the
TPU-native case for keeping data device-resident end to end; the original
Ray paper (arXiv:1712.05889) is why this stays inside the ObjectRef
ownership model instead of becoming a side API.
"""

import itertools

from ray_tpu.experimental.device_object.descriptor import (  # noqa: F401
    TENSOR_TRANSPORTS,
    DeviceObjectMeta,
    validate_transport,
)
from ray_tpu.experimental.device_object.manager import (  # noqa: F401
    DEVOBJ_STATS,
    DeviceObjectManager,
    device_object_stats,
)
from ray_tpu.experimental.device_object.resolve import resolve_meta  # noqa: F401


def _unreachable_errors() -> tuple:
    """Exception classes that mean 'the holder process cannot be reached'
    (vs. 'the holder answered with an error')."""
    from ray_tpu._private.rpc import ConnectionLost

    return (ConnectionLost, ConnectionError, TimeoutError)


def broadcast(ref, group_name: str | None = None, *, timeout: float = 60.0,
              strict: bool = True, node_ids: list | None = None) -> dict:
    """Fan a device object's payload out with ONE group operation, so a
    learner syncing weights to K samplers stops paying K serial unicasts
    (Podracer, arXiv:2104.06272 — the fan-out this plane exists for).

    With ``group_name``: the holder runs a group broadcast over that
    collective group (``p2p.group_bcast_send`` on the cpu backend — one
    serialize, concurrent acked chunk pushes at every member's direct
    mailbox; the tpu seam maps to an ICI broadcast on hardware). Each
    member's NEXT resolve of ``ref`` (get / task-arg) takes the payload
    straight from its inbox — zero pull round trips, zero host-store
    copies. One broadcast per ref: the inbox tombstones repeated keys.

    Without ``group_name``: the cross-node host fallback — the holder
    seals a host copy into its arena and the copy rides the cut-through
    relay tree (``util.object_transfer.broadcast_object``) to every alive
    node (or ``node_ids``); consumers resolve from their LOCAL arena.

    Returns the delivery map (``ok_ranks``/``fallback_ranks``/``failed``
    for the group path, ``pushed_nodes`` for the host path). ``strict=True``
    raises :class:`~ray_tpu.exceptions.CollectiveBroadcastError` NAMING any
    rank the group path could not deliver to — surviving ranks keep their
    payload either way, and a respawned member transparently falls back to
    the pull path."""
    from ray_tpu._private import worker_context
    from ray_tpu.exceptions import CollectiveBroadcastError

    cw = worker_context.get_core_worker()
    meta = cw.get_device_meta(ref, timeout=timeout)
    if group_name is None:
        from ray_tpu.util.object_transfer import broadcast_object

        if tuple(meta.holder_addr) == tuple(cw.address):
            ok = cw._device_manager().materialize_to_store(meta.object_id)
        else:
            resp = cw._devobj_client(tuple(meta.holder_addr)).call(
                "devobj_broadcast", {"object_id": meta.object_id}, timeout=timeout
            )
            ok = resp.get("kind") == "plasma"
        if not ok:
            raise CollectiveBroadcastError(
                f"holder of device object {meta.object_id[:12]} could not "
                f"materialize a host copy (holder {meta.holder_label()})",
            )
        pushed = broadcast_object(ref, node_ids=node_ids, timeout=timeout)
        return {"kind": "plasma", "pushed_nodes": pushed}
    if tuple(meta.holder_addr) == tuple(cw.address):
        # Same typed surface as the RPC path: a freed entry is a lost
        # object, an uninitialized group a broadcast error.
        try:
            result = cw._device_manager().broadcast_via_group(
                meta.object_id, group_name, timeout
            )
        except KeyError:
            from ray_tpu.exceptions import DeviceObjectLostError

            raise DeviceObjectLostError(meta.object_id, holder=meta.holder_label())
        except ValueError as e:
            raise CollectiveBroadcastError(str(e), group=group_name) from e
        result["kind"] = "collective"
    else:
        try:
            result = cw._devobj_client(tuple(meta.holder_addr)).call(
                "devobj_broadcast",
                {"object_id": meta.object_id, "group": group_name, "timeout": timeout},
                timeout=timeout + 20.0,
            )
        except _unreachable_errors() as e:
            # Holder genuinely unreachable: the object may be lost with it.
            from ray_tpu.exceptions import DeviceObjectLostError

            raise DeviceObjectLostError(
                meta.object_id,
                holder=meta.holder_label(),
                msg=(
                    f"group broadcast of {meta.object_id[:12]} failed: holder "
                    f"{meta.holder_label()} unreachable ({e!r})"
                ),
            ) from e
        except Exception as e:
            # Holder answered with an error (or a handler bug surfaced):
            # the object is intact — a broadcast failure, not a loss.
            raise CollectiveBroadcastError(
                f"group broadcast of {meta.object_id[:12]} failed on holder "
                f"{meta.holder_label()}: {e!r}",
                group=group_name,
            ) from e
    kind = result.get("kind")
    if kind == "missing":
        from ray_tpu.exceptions import DeviceObjectLostError

        raise DeviceObjectLostError(meta.object_id, holder=meta.holder_label())
    if kind == "error":
        raise CollectiveBroadcastError(result.get("error", "group broadcast failed"), group=group_name)
    if strict and result.get("failed"):
        raise CollectiveBroadcastError(group=group_name, failed=result["failed"], info=result)
    return result


_REDUCE_SEQ = itertools.count(1)


def reduce(refs: list, group_name: str, *, op=None, dst_rank: int = 0,
           timeout: float = 120.0, strict: bool = True) -> dict:
    """Group reduce over device objects: ``refs`` holds ONE ref per group
    member (rank order), each device-resident on its holder; the holders
    combine them elementwise up the relay tree (chunk-wise at every hop on
    the cpu backend, psum on tpu) and the ``dst_rank`` holder's array is
    REPLACED in place with the result — its descriptor is unchanged, so
    the next resolve of ``refs[dst_rank]`` sees the combined value. Other
    holders keep their contribution. ``strict=True`` raises
    :class:`~ray_tpu.exceptions.CollectiveReduceError` naming any holder
    that did not finish (a partial reduce is poison — see the exception)."""
    return _reduce_verb(refs, group_name, "reduce", op, dst_rank, timeout, strict)


def allreduce(refs: list, group_name: str, *, op=None,
              timeout: float = 120.0, strict: bool = True) -> dict:
    """Group allreduce over device objects: like :func:`reduce`, but the
    combined result broadcasts back down the tree and EVERY holder's array
    is replaced in place — after this, all of ``refs`` resolve to the same
    reduced value (the multi-host gradient-sync primitive the Podracer
    learner seam rides as ``grad_sync="device_allreduce"``)."""
    return _reduce_verb(refs, group_name, "allreduce", op, 0, timeout, strict)


def _reduce_verb(refs, group_name, mode, op, dst_rank, timeout, strict) -> dict:
    from concurrent.futures import ThreadPoolExecutor

    from ray_tpu._private import worker_context
    from ray_tpu.exceptions import CollectiveReduceError
    from ray_tpu.util.collective.types import ReduceOp

    if not refs:
        raise ValueError("reduce/allreduce needs one ref per group member")
    op = op or ReduceOp.SUM
    cw = worker_context.get_core_worker()
    metas = [cw.get_device_meta(ref, timeout=timeout) for ref in refs]
    # One tag per gang op: every holder must combine under the SAME stream
    # keys, and a second reduce over the same refs must not collide with
    # the first (unlike broadcast, reduces repeat per training step).
    tag = f"{metas[0].object_id[:16]}.{next(_REDUCE_SEQ)}"

    def _one(meta):
        if tuple(meta.holder_addr) == tuple(cw.address):
            try:
                out = cw._device_manager().reduce_via_group(
                    meta.object_id, group_name, mode, op.name, dst_rank, tag, timeout
                )
                return {"kind": "collective", **out}
            except KeyError:
                return {"kind": "missing"}
            except Exception as e:
                return {"kind": "error", "error": repr(e)}
        try:
            return cw._devobj_client(tuple(meta.holder_addr)).call(
                "devobj_reduce",
                {"object_id": meta.object_id, "group": group_name, "mode": mode,
                 "op": op.name, "dst_rank": dst_rank, "tag": tag, "timeout": timeout},
                timeout=timeout + 20.0,
            )
        except _unreachable_errors() as e:
            return {"kind": "error", "error": f"holder unreachable: {e!r}"}
        except Exception as e:
            return {"kind": "error", "error": repr(e)}

    # The gang is concurrent BY REQUIREMENT: every holder blocks inside the
    # collective until its children/parent move, so the pool must be wide
    # enough for all of them at once — a capped pool would deadlock the op.
    with ThreadPoolExecutor(max_workers=len(metas)) as pool:
        per_holder = list(pool.map(_one, metas))

    failed: dict = {}
    ranks = []
    for meta, res in zip(metas, per_holder):
        if res.get("kind") == "collective":
            ranks.append(res.get("rank"))
        elif res.get("kind") == "missing":
            failed[meta.holder_label()] = "device object missing on holder"
        else:
            failed[meta.holder_label()] = res.get("error", "reduce failed")
    result = {
        "kind": "collective", "group": group_name, "mode": mode, "op": op.name,
        "tag": tag, "ok_ranks": sorted(r for r in ranks if r is not None),
        "failed": failed,
    }
    if strict and failed:
        raise CollectiveReduceError(group=group_name, failed=failed, info=result)
    return result


def allgather(refs: list, group_name: str | None = None, *, timeout: float = 60.0,
              strict: bool = True) -> list:
    """Group allgather for device objects: every member ends up able to
    resolve EVERY ref in ``refs`` locally — one descriptor and one group
    operation per ref, with the per-holder fan-outs running concurrently
    (the holders push in parallel; the driver's RPCs overlap on threads).
    Returns one delivery map per ref, in order."""
    from concurrent.futures import ThreadPoolExecutor

    if not refs:
        return []
    if len(refs) == 1:
        return [broadcast(refs[0], group_name, timeout=timeout, strict=strict)]
    with ThreadPoolExecutor(max_workers=min(8, len(refs))) as pool:
        futs = [
            pool.submit(broadcast, ref, group_name, timeout=timeout, strict=strict)
            for ref in refs
        ]
        return [f.result() for f in futs]


__all__ = [
    "DEVOBJ_STATS",
    "DeviceObjectManager",
    "DeviceObjectMeta",
    "TENSOR_TRANSPORTS",
    "allgather",
    "allreduce",
    "broadcast",
    "device_object_stats",
    "reduce",
    "resolve_meta",
    "validate_transport",
]
