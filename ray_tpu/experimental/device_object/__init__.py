"""Device object plane — device-resident ``jax.Array`` objects, passed by
reference with out-of-band collective transfer.

The base object plane (``_private/serialization.py``) DMAs every device
array host-side on ``put`` and back on ``get``: an actor-to-actor tensor
handoff (learner→sampler weight sync, KV-cache migration, pipeline
activations) pays two host copies plus shm traffic even when both endpoints
sit on the same mesh. This plane keeps the array ON its devices and seals
only a small :class:`DeviceObjectMeta` descriptor into the normal store —
the ``ObjectRef`` stays first-class (refcounted, waitable, passable,
reconstruct-free), while the payload moves out of band:

- **same process** — the consumer gets the live ``jax.Array`` back, zero
  copies of the payload anywhere;
- **same mesh** — a ``util/collective`` group p2p ``send``/``recv`` moves
  it holder→consumer (CPU ring backend in tests, tpu backend on hardware),
  sharding layout preserved;
- **no shared group / cross-mesh** — the holder materializes a host copy
  (inline for small arrays, its node's shm arena otherwise) and the
  consumer resolves through the existing host-shm path, transparently.

Opt in per value with ``ray_tpu.put(arr, tensor_transport="collective")``
or per actor with ``@ray_tpu.remote(tensor_transport="collective")`` —
every top-level ``jax.Array`` such an actor returns stays device-resident
on the actor, which is the HOLDER; the caller stays the owner and the
normal ownership protocol frees the device buffers when the last ref
drops. Under memory pressure (``devobj_resident_limit_bytes``) the holder
spills device→host into the arena and restores on the next resolve; holder
death surfaces :class:`~ray_tpu.exceptions.DeviceObjectLostError` naming
the holder, falling back to the spilled/host copy when one exists.

Reference direction: Ray GPU objects / `tensor_transport=` direct tensor
transport over ``ray.util.collective``; Podracer (arXiv:2104.06272) is the
TPU-native case for keeping data device-resident end to end; the original
Ray paper (arXiv:1712.05889) is why this stays inside the ObjectRef
ownership model instead of becoming a side API.
"""

from ray_tpu.experimental.device_object.descriptor import (  # noqa: F401
    TENSOR_TRANSPORTS,
    DeviceObjectMeta,
    validate_transport,
)
from ray_tpu.experimental.device_object.manager import (  # noqa: F401
    DEVOBJ_STATS,
    DeviceObjectManager,
    device_object_stats,
)
from ray_tpu.experimental.device_object.resolve import resolve_meta  # noqa: F401

__all__ = [
    "DEVOBJ_STATS",
    "DeviceObjectManager",
    "DeviceObjectMeta",
    "TENSOR_TRANSPORTS",
    "device_object_stats",
    "resolve_meta",
    "validate_transport",
]
