"""Consumer-side resolution: DeviceObjectMeta → live value.

``CoreWorker.get`` (and therefore task-arg resolution) hands every
materialized ``DeviceObjectMeta`` here. Resolution order:

1. **same process** — this process IS the holder: hand back the live array
   (restoring from the arena first if it was spilled). Zero payload copies.
2. **shared collective group** — ask the holder to p2p-send over a group
   both sides initialized (``devobj_pull`` RPC kicks the send on the
   holder; we ``recv`` on the consumer thread). Sharding survives the hop.
3. **host fallback** — no shared group (or transport rejected): the holder
   ships small arrays inline in the RPC reply and seals large ones into its
   node's shm arena under the same object id, which the existing store pull
   path resolves from anywhere in the cluster.
4. **holder dead** — fall back to a spilled/arena copy when one exists,
   else raise :class:`DeviceObjectLostError` naming the holder.
"""

from __future__ import annotations

import logging
import os
import time

from ray_tpu._private import flight_recorder, serialization
from ray_tpu._private.concurrency import blocking
from ray_tpu.exceptions import DeviceObjectLostError, GetTimeoutError

logger = logging.getLogger(__name__)

# Per-ATTEMPT ceiling on one devobj_pull RPC; with retries the unbounded-
# deadline worst case stays at the old 60s total, but a lost frame now
# costs one attempt (~15s), not the whole budget. Large enough for the
# holder to materialize a multi-10s-of-MiB host copy before answering.
_PULL_ATTEMPT_S = 15.0


def _remaining(deadline, cap: float) -> float:
    if deadline is None:
        return cap
    rem = deadline - time.monotonic()
    if rem <= 0:
        raise GetTimeoutError("ray_tpu.get() timed out resolving a device object")
    return min(rem, cap)


def _pick_group(meta):
    """(group_name, consumer_rank, holder_rank) for a collective group both
    endpoints initialized, or None."""
    from ray_tpu.util.collective import local_group_hints

    try:
        local = {name: rank for name, rank, _ in local_group_hints()}
    except Exception:
        return None
    for name, holder_rank, _ in meta.group_hints or []:
        my_rank = local.get(name)
        if my_rank is not None and my_rank != holder_rank:
            return (name, my_rank, holder_rank)
    return None


@blocking
def resolve_meta(cw, meta, deadline=None):
    """Turn a descriptor into the payload. ``cw`` is this process's
    CoreWorker; blocking (runs on get()'s calling thread)."""
    from ray_tpu.experimental.device_object.manager import DEVOBJ_STATS, active_manager

    oid = meta.object_id
    # 1. Same process: live (or spilled-here) array, zero payload copies.
    mgr = active_manager()
    if mgr is not None and mgr.entry(oid) is not None:
        arr = mgr.get_local(oid)
        if arr is not None:
            DEVOBJ_STATS.transfers_local += 1
            flight_recorder.record("devobj_transfer", f"{oid[:12]}:local")
            return arr
    # 1b. Group-sourced descriptor: a holder-side group broadcast
    # (device_object.broadcast) pre-delivered the payload into this
    # process's direct mailbox under a key derived from (group, oid, rank)
    # — take it with zero round trips. Non-blocking: a miss (no broadcast
    # happened, or this ref was already taken once) just falls through to
    # the pull path.
    if meta.transport == "collective":
        value = _take_broadcast(cw, meta)
        if value is not None:
            return value
    # 1c. A host copy already on THIS node's arena (the cut-through relay
    # fallback of device_object.broadcast lands one per node, and a holder
    # on this node may have materialized/spilled): resolve from local shm
    # without waking the holder.
    try:
        if cw.store.contains(oid):
            return _from_store(cw, meta, deadline)
    except GetTimeoutError:
        raise
    except Exception:
        logger.debug("local-store probe for device object %s failed", oid[:12], exc_info=True)
    # 2./3. Ask the holder. One RPC decides the path: it kicks off a
    # collective send when we named a shared group, else it hands back an
    # inline/arena host copy.
    pick = _pick_group(meta) if meta.transport == "collective" else None
    if pick is not None:
        # This process IS a member of a group it shares with the holder,
        # yet the broadcast inbox had nothing — the member fell off the
        # group-sync fast path (stale roster, missed epoch, respawn that
        # never re-registered) and is quietly riding pull-resolve. Count
        # it: this is the elastic-membership degradation signal
        # (ray_tpu_collective_host_sync_fallbacks_total).
        from ray_tpu.util.collective.p2p import COLL

        COLL.host_sync_fallbacks += 1
        flight_recorder.record("devobj_transfer", f"{oid[:12]}:host_sync_fallback:{pick[0]}")
    req: dict = {"object_id": oid}
    tag = ""
    if pick is not None:
        group_name, my_rank, _ = pick
        tag = f"{oid[:16]}-{os.urandom(4).hex()}"
        req.update({"group": group_name, "dst_rank": my_rank, "tag": tag})
    try:
        # Short-connect client: a dead holder surfaces in ~2s
        # (ConnectionLost) and falls through to the host-copy fallback /
        # typed loss instead of grinding the full connect-retry budget.
        # Per-ATTEMPT timeout is _PULL_ATTEMPT_S, not the whole pull
        # budget: a silently lost request/reply frame (chaos drop; receiver
        # hiccup) used to stall the resolve 60s before its one retry —
        # bounded attempts heal it in ~15s while the deadline still caps
        # the total (the holder's answer is idempotent, so a retry racing
        # a slow first answer is harmless).
        client = cw._devobj_client(tuple(meta.holder_addr))
        resp = client.call(
            "devobj_pull", req,
            timeout=_remaining(deadline, _PULL_ATTEMPT_S), retries=3,
        )
    except GetTimeoutError:
        raise
    except Exception:
        return _host_copy_or_lost(cw, meta, deadline)
    kind = resp.get("kind")
    if kind == "collective":
        from ray_tpu.util.collective import get_group

        try:
            value = get_group(resp["group"]).recv(
                resp["src_rank"], tag, timeout=_remaining(deadline, 120.0)
            )
        except GetTimeoutError:
            raise
        except Exception:
            # Holder-side send failed (object freed mid-pull, group torn
            # down, mailbox hiccup) — the holder answered, so it was alive:
            # re-pull over the host path before declaring the object lost.
            logger.warning(
                "collective recv of device object %s failed; falling back to "
                "the host path", oid[:12],
            )
            return _host_pull(cw, meta, deadline)
        DEVOBJ_STATS.transfers_collective += 1
        flight_recorder.record("devobj_transfer", f"{oid[:12]}:collective:{resp['group']}")
        return value
    if kind == "inline":
        value = serialization.loads(resp["data"])
        _bump_host(oid, "host_inline")
        return value
    if kind == "plasma":
        return _from_store(cw, meta, deadline)
    # "missing": the holder no longer tracks it (freed under us, or a stale
    # descriptor after holder restart) — a host copy may still exist.
    return _host_copy_or_lost(cw, meta, deadline)


def _take_broadcast(cw, meta):
    """Non-blocking inbox probe for a group-broadcast payload of this
    descriptor: for every collective group this process shares with the
    holder, try the deterministic broadcast key. At-most-once per ref per
    process (the inbox take consumes the entry); a second resolve of the
    same ref falls back to the pull path."""
    from ray_tpu._private import serialization
    from ray_tpu.util.collective import local_group_hints
    from ray_tpu.util.collective.p2p import COLL, bcast_key

    oid = meta.object_id
    try:
        local = {name: rank for name, rank, _ in local_group_hints()}
    except Exception:
        return None
    for name, holder_rank, _ in meta.group_hints or []:
        my_rank = local.get(name)
        if my_rank is None or my_rank == holder_rank:
            continue
        data = cw.p2p_inbox.take(bcast_key(name, oid))
        if data is None:
            continue
        value = serialization.loads(data)
        from ray_tpu.experimental.device_object.manager import DEVOBJ_STATS

        COLL.bcast_recvs += 1
        DEVOBJ_STATS.transfers_collective += 1
        flight_recorder.record("devobj_transfer", f"{oid[:12]}:bcast:{name}")
        return value
    return None


def _host_pull(cw, meta, deadline):
    """Pull WITHOUT naming a group: the holder ships inline or seals an
    arena copy. Used directly for non-collective descriptors and as the
    recovery path when a collective transfer dies mid-flight."""
    oid = meta.object_id
    try:
        client = cw._devobj_client(tuple(meta.holder_addr))
        resp = client.call(
            "devobj_pull",
            {"object_id": oid},
            timeout=_remaining(deadline, _PULL_ATTEMPT_S),
            retries=3,
        )
    except GetTimeoutError:
        raise
    except Exception:
        return _host_copy_or_lost(cw, meta, deadline)
    kind = resp.get("kind")
    if kind == "inline":
        value = serialization.loads(resp["data"])
        _bump_host(oid, "host_inline")
        return value
    if kind == "plasma":
        return _from_store(cw, meta, deadline)
    return _host_copy_or_lost(cw, meta, deadline)


def _bump_host(oid: str, label: str):
    from ray_tpu.experimental.device_object.manager import DEVOBJ_STATS

    DEVOBJ_STATS.transfers_host += 1
    flight_recorder.record("devobj_transfer", f"{oid[:12]}:{label}")


def _from_store(cw, meta, deadline):
    """Pull the host copy sealed under the same object id (local arena hit,
    or a cross-node pull through the raylet)."""
    oid = meta.object_id
    view = cw.store.get_view(oid, timeout=_remaining(deadline, 30.0))
    try:
        value = serialization.deserialize(view)
    finally:
        cw.store.release(oid)
    _bump_host(oid, "host_store")
    return value


def _host_copy_or_lost(cw, meta, deadline):
    """Holder unreachable/ignorant: the spilled/arena copy is the last
    resort before a typed loss naming the holder."""
    oid = meta.object_id
    try:
        if cw.store.contains(oid) or cw._has_any_location(oid):
            return _from_store(cw, meta, deadline)
    except GetTimeoutError:
        raise
    except Exception:
        logger.debug("device-object host-copy fallback for %s failed", oid[:12], exc_info=True)
    raise DeviceObjectLostError(oid, holder=meta.holder_label())
