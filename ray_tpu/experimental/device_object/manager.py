"""DeviceObjectManager — per-process registry of device-resident payloads.

One per core worker (lazily, first device put/return creates it). The
manager holds the live ``jax.Array`` for every device object this process
is the HOLDER of, plus the spill state: under memory pressure
(``devobj_resident_limit_bytes``) the least-recently-used arrays are
serialized device→host into the node's shm arena (under the SAME object id,
so every existing host-path consumer — local deserialize, cross-node pull,
holder-death fallback — finds the copy with zero new plumbing) and restored
onto their devices on the next local resolve.

Observability: every transition records a typed flight-recorder event
(``devobj_create/transfer/spill/restore/free``) and bumps the plain-int
``DEVOBJ_STATS`` counters folded into ``ray_tpu_devobj_*`` metrics by
``self_metrics`` at flush time (no instrument lock on the create path).
A best-effort GCS KV row (``devobj/<oid>``) backs the cluster state view
(``ray_tpu list device_objects``).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from dataclasses import dataclass

from ray_tpu._private import flight_recorder
from ray_tpu._private.concurrency import any_thread, blocking

logger = logging.getLogger(__name__)


class _DevObjStats:
    """Plain-int hot-path counters (self_metrics folds them at flush).
    ``chan_sends``/``chan_recvs`` count descriptor-channel payloads (PR 12)
    eager-pushed to / taken from the p2p direct mailbox — the steady-state
    microbatch path, distinct from the pull-driven transfer kinds above."""

    __slots__ = (
        "creates",
        "frees",
        "spills",
        "restores",
        "transfers_local",
        "transfers_collective",
        "transfers_host",
        "chan_sends",
        "chan_recvs",
    )

    def __init__(self):
        for name in self.__slots__:
            setattr(self, name, 0)


DEVOBJ_STATS = _DevObjStats()

# The process's manager, for the metrics collector and device_object_stats();
# written once under _active_lock when the first device object is created.
_active_manager = None
_active_lock = threading.Lock()


def active_manager():
    return _active_manager


def device_object_stats() -> dict:
    """Snapshot of this process's device-object plane (tests, actor-side
    introspection, the CLI state view's per-holder detail)."""
    mgr = _active_manager
    counters = {name: getattr(DEVOBJ_STATS, name) for name in _DevObjStats.__slots__}
    if mgr is None:
        return {"resident_count": 0, "resident_bytes": 0, "spilled_count": 0, **counters}
    return {**mgr.usage(), **counters}


@dataclass
class DeviceObjectEntry:
    meta: object  # DeviceObjectMeta
    array: object | None  # live jax.Array; None once spilled
    in_store: bool = False  # host copy sealed into the shm arena (same oid)
    last_access: float = 0.0
    # Channel-payload bookkeeping (PR 12): pins = consumers that have not
    # yet released this payload; scope = the resident loop / compiled DAG
    # that created it, so teardown can reclaim whatever releases never
    # arrived. scope == "" marks an ordinary ObjectRef-owned device object.
    pins: int = 0
    scope: str = ""


class DeviceObjectManager:
    def __init__(self, core_worker):
        global _active_manager
        self.cw = core_worker
        self._lock = threading.Lock()
        self._entries: dict[str, DeviceObjectEntry] = {}
        with _active_lock:
            _active_manager = self

    # ---- creation (holder side: put / actor-task return packaging) ----

    @blocking
    def create_resident(self, oid_hex: str, arr, transport: str, holder_id: str, holder_kind: str):
        """Register ``arr`` as device-resident under ``oid_hex``; returns the
        DeviceObjectMeta to seal into the normal store."""
        from ray_tpu.experimental.device_object.descriptor import DeviceObjectMeta
        from ray_tpu.util.collective import local_group_hints

        if not getattr(arr, "is_fully_addressable", True):
            raise TypeError(
                "cannot keep a multi-host jax.Array device-resident: this "
                "process only holds some of its shards; put per-host shards "
                "as separate device objects instead"
            )
        try:
            hints = local_group_hints()
        except Exception:
            hints = []
        meta = DeviceObjectMeta(
            object_id=oid_hex,
            shape=tuple(arr.shape),
            dtype=str(arr.dtype),
            nbytes=int(arr.nbytes),
            transport=transport,
            holder_addr=tuple(self.cw.address),
            holder_id=holder_id,
            holder_kind=holder_kind,
            sharding=repr(getattr(arr, "sharding", "")),
            group_hints=hints,
        )
        with self._lock:
            self._entries[oid_hex] = DeviceObjectEntry(
                meta=meta, array=arr, last_access=time.monotonic()
            )
        DEVOBJ_STATS.creates += 1
        flight_recorder.record("devobj_create", f"{oid_hex[:12]}:{meta.nbytes}")
        self._registry_put(meta)
        limit = getattr(self.cw.cfg, "devobj_resident_limit_bytes", 0)
        if limit > 0:
            self._spill_for_pressure(limit, protect=oid_hex)
        return meta

    @blocking
    def create_channel_payload(self, arr, pins: int, scope: str):
        """Register a TRANSIENT channel payload (descriptor channel plane,
        experimental/channel/device_envelope.py): this process holds the
        live array while its DeviceObjectMeta rides a channel slot to
        ``pins`` consumers. Unlike create_resident there is no ObjectRef
        and no owner — consumers release their pin after resolving (the
        last release frees), and ``reclaim_scope`` frees whatever is left
        when the creating loop/DAG tears down. Skips the GCS state-registry
        row (one KV write per microbatch per edge would be pure churn for
        an object that lives milliseconds) and is exempt from spill
        pressure (spilling would seal a host copy — exactly the copy the
        descriptor plane exists to avoid)."""
        import os

        from ray_tpu.experimental.device_object.descriptor import DeviceObjectMeta
        from ray_tpu.util.collective import local_group_hints

        try:
            hints = local_group_hints()
        except Exception:
            hints = []
        oid_hex = os.urandom(14).hex()
        holder_id, holder_kind = self.cw._holder_identity()
        meta = DeviceObjectMeta(
            object_id=oid_hex,
            shape=tuple(arr.shape),
            dtype=str(arr.dtype),
            nbytes=int(arr.nbytes),
            transport="collective",
            holder_addr=tuple(self.cw.address),
            holder_id=holder_id,
            holder_kind=holder_kind,
            # No sharding repr: jax renders it lazily and paying a string
            # build per microbatch per edge is measurable on the hot loop;
            # the layout itself travels exactly with the payload bytes.
            sharding="",
            group_hints=hints,
        )
        with self._lock:
            self._entries[oid_hex] = DeviceObjectEntry(
                meta=meta,
                array=arr,
                last_access=time.monotonic(),
                pins=max(1, int(pins)),
                scope=scope,
            )
        DEVOBJ_STATS.creates += 1
        flight_recorder.record("devobj_create", f"{oid_hex[:12]}:{meta.nbytes}:chan")
        return meta

    @any_thread
    def release_pin(self, oid_hex: str) -> None:
        """One consumer of a channel payload is done with it; the last pin
        release frees the entry (and with it the holder's reference to the
        device buffers)."""
        with self._lock:
            entry = self._entries.get(oid_hex)
            if entry is None:
                return
            entry.pins -= 1
            if entry.pins > 0:
                return
        self.free(oid_hex)

    @any_thread
    def reclaim_scope(self, scope: str) -> int:
        """Free every channel payload created under ``scope`` (a resident
        loop or compiled DAG tearing down): releases that were lost to a
        dead consumer or a torn connection must not leak device buffers."""
        if not scope:
            return 0
        with self._lock:
            victims = [o for o, e in self._entries.items() if e.scope == scope]
        for oid in victims:
            self.free(oid)
        return len(victims)

    # ---- resolution (consumer side, via resolve.py) ----

    def entry(self, oid_hex: str) -> DeviceObjectEntry | None:
        with self._lock:
            return self._entries.get(oid_hex)

    @blocking
    def get_local(self, oid_hex: str):
        """The live array if this process holds it (restoring a spilled one
        from the arena first); None when not the holder."""
        with self._lock:
            entry = self._entries.get(oid_hex)
            if entry is None:
                return None
            entry.last_access = time.monotonic()
            arr = entry.array
        if arr is not None:
            return arr
        return self._restore(oid_hex)

    # ---- host materialization / spill / restore ----

    @blocking
    def host_bytes(self, oid_hex: str) -> bytes | None:
        """Serialized host copy (small-object inline fallback)."""
        from ray_tpu._private import serialization

        arr = self.get_local(oid_hex)
        if arr is None:
            return None
        return serialization.dumps(arr)

    @blocking
    def materialize_to_store(self, oid_hex: str) -> bool:
        """Seal a host copy into the node's shm arena under the same object
        id — the no-group/cross-mesh fallback target — KEEPING the device
        copy resident. Idempotent."""
        from ray_tpu._private import serialization

        with self._lock:
            entry = self._entries.get(oid_hex)
            if entry is None:
                return False
            if entry.in_store:
                return True
            arr = entry.array
        if arr is None:  # spilled: the arena copy already exists
            return True
        ser = serialization.serialize(arr)
        self.cw.store.put_serialized(oid_hex, ser)
        with self._lock:
            entry = self._entries.get(oid_hex)
            if entry is not None:
                entry.in_store = True
        if entry is None:
            # free() raced the seal and saw in_store=False, so it skipped
            # the store cleanup — the copy we just sealed would be orphaned.
            async def _free_store():
                try:
                    await self.cw.raylet.acall("free_object", {"object_id": oid_hex})
                except Exception:
                    pass

            self.cw._io.spawn(_free_store())
            return False
        return True

    @blocking
    def spill(self, oid_hex: str) -> bool:
        """Device→host under memory pressure: seal the host copy into the
        arena, then release the device buffers (drop the live array)."""
        if not self.materialize_to_store(oid_hex):
            return False
        with self._lock:
            entry = self._entries.get(oid_hex)
            if entry is None or entry.array is None:
                return entry is not None
            entry.array = None
            nbytes = entry.meta.nbytes
        DEVOBJ_STATS.spills += 1
        flight_recorder.record("devobj_spill", f"{oid_hex[:12]}:{nbytes}")
        return True

    @blocking
    def _restore(self, oid_hex: str):
        """Arena → device: deserialize the spilled copy (original sharding
        reassembles via the jax.Array reducer) and pin it live again."""
        from ray_tpu._private import serialization

        view = self.cw.store.get_view(oid_hex, timeout=30.0)
        try:
            arr = serialization.deserialize(view)
        finally:
            self.cw.store.release(oid_hex)
        with self._lock:
            entry = self._entries.get(oid_hex)
            if entry is None:
                return arr  # freed while restoring: hand the value out anyway
            if entry.array is None:
                entry.array = arr
            entry.last_access = time.monotonic()
            arr = entry.array
        DEVOBJ_STATS.restores += 1
        flight_recorder.record("devobj_restore", oid_hex[:12])
        return arr

    @blocking
    def _spill_for_pressure(self, limit_bytes: int, protect: str = ""):
        """Spill LRU live entries until resident bytes fit the limit."""
        while True:
            with self._lock:
                live = [
                    (e.last_access, oid)
                    for oid, e in self._entries.items()
                    # Channel payloads (scope set) are exempt: they live
                    # milliseconds, and spilling one would seal the very
                    # host copy the descriptor plane avoids.
                    if e.array is not None and oid != protect and not e.scope
                ]
                resident = sum(
                    e.meta.nbytes for e in self._entries.values() if e.array is not None
                )
            if resident <= limit_bytes or not live:
                return
            live.sort()
            if not self.spill(live[0][1]):
                return

    # ---- transfer (holder side, driven by rpc_devobj_pull) ----

    @blocking
    def send_via_group(self, oid_hex: str, group_name: str, dst_rank: int, tag: str):
        """p2p-send the live array to the consumer's rank. Runs on an
        executor thread (the pull RPC handler must not block the IO loop).
        A janitor deletes the mailbox key after a grace period: a consumer
        that timed out (or died) mid-recv never picks it up, and the
        serialized payload must not live in the GCS KV forever."""
        from ray_tpu.util.collective import get_group
        from ray_tpu.util.collective.p2p import mailbox_key

        try:
            arr = self.get_local(oid_hex)
            if arr is None:
                raise KeyError(oid_hex)
            group = get_group(group_name)
            group.send(arr, dst_rank, tag)
            DEVOBJ_STATS.transfers_collective += 1
            flight_recorder.record("devobj_transfer", f"{oid_hex[:12]}:collective:{group_name}")
            self._schedule_mailbox_janitor(
                mailbox_key(group_name, group.rank, dst_rank, tag)
            )
        except Exception:
            logger.exception(
                "collective send of device object %s on group %s failed",
                oid_hex[:12], group_name,
            )

    @blocking
    def broadcast_via_group(self, oid_hex: str, group_name: str, timeout: float = 30.0) -> dict:
        """Group analog of :meth:`send_via_group`: fan the live array to
        EVERY member of ``group_name`` with ONE group operation — one
        serialize, concurrent acked chunk pushes at each member's direct
        mailbox (``p2p.group_bcast_send``; ICI broadcast on the tpu
        backend). Members then resolve the same descriptor straight from
        their inbox, zero pull round trips. Runs on an executor thread
        (driven by ``rpc_devobj_broadcast``; serialization plus K ack RTTs
        must not stall the IO loop). Returns the per-rank delivery map —
        a dead member lands in ``failed`` (the driver-side API turns that
        into a typed CollectiveBroadcastError naming it) while surviving
        ranks complete."""
        from ray_tpu.util.collective import get_group

        arr = self.get_local(oid_hex)
        if arr is None:
            raise KeyError(oid_hex)
        group = get_group(group_name)
        # No mailbox fallback: descriptor consumers resolve from the direct
        # inbox only — a KV drop would be a false "delivered" plus dead
        # payload bytes in the GCS until the janitor.
        result = group.bcast_send_payload(
            arr, tag=oid_hex, timeout=timeout, mailbox_fallback=False
        )
        result["group"] = group_name
        result["src_rank"] = group.rank
        DEVOBJ_STATS.transfers_collective += 1
        # Denominator is the ROSTER SNAPSHOT the send targeted (elastic
        # membership), not the world size frozen at group init.
        targets = (
            len(result["ok_ranks"]) + len(result["fallback_ranks"])
            + len(result["failed"])
        )
        flight_recorder.record(
            "coll_broadcast",
            f"{oid_hex[:12]}:{group_name}:{len(result['ok_ranks'])}/"
            f"{targets}:{result['bytes']}",
        )
        return result

    @blocking
    def reduce_via_group(self, oid_hex: str, group_name: str, mode: str,
                         op_name: str, dst_rank: int, tag: str,
                         timeout: float = 60.0) -> dict:
        """This HOLDER's share of a device-object group reduce/allreduce:
        feed the live array into the tree combine
        (``group.allreduce_payload`` / ``reduce_send_payload`` — chunk-wise
        combine at relay hops on the cpu backend, psum on tpu) and REPLACE
        the resident array with the result — NCCL-style in-place semantics:
        the descriptor keeps its identity/shape/dtype and every consumer's
        NEXT resolve sees the combined value. ``allreduce`` replaces on
        every holder; ``reduce`` only on the ``dst_rank`` holder (other
        holders keep their contribution). Runs on an executor thread
        (driven by ``rpc_devobj_reduce``). Raises KeyError when the entry
        was freed; collective errors (typed timeout naming a silent child,
        shape disagreement) propagate for the RPC layer to answer with."""
        from ray_tpu.util.collective import get_group
        from ray_tpu.util.collective.types import ReduceOp

        arr = self.get_local(oid_hex)
        if arr is None:
            raise KeyError(oid_hex)
        group = get_group(group_name)
        op = ReduceOp[op_name] if isinstance(op_name, str) else op_name
        if mode == "allreduce":
            out = group.allreduce_payload(arr, tag=tag, op=op, timeout=timeout)
        else:
            out = group.reduce_send_payload(
                arr, tag=tag, op=op, dst_rank=dst_rank, timeout=timeout
            )
        replaced = out is not None
        if replaced:
            self._replace_resident(oid_hex, out)
        DEVOBJ_STATS.transfers_collective += 1
        flight_recorder.record(
            "coll_reduce",
            f"{oid_hex[:12]}:{group_name}:{mode}:{group.rank}:{int(replaced)}",
        )
        return {"rank": group.rank, "world_size": group.world_size, "reduced": replaced}

    @any_thread
    def _replace_resident(self, oid_hex: str, value) -> None:
        """Swap the live array under an existing entry, preserving the
        descriptor's dtype/shape (the meta already sealed into the store
        must stay truthful). A freed-while-reducing entry is a no-op."""
        import jax.numpy as jnp

        with self._lock:
            entry = self._entries.get(oid_hex)
            if entry is None or entry.array is None:
                return
            entry.array = jnp.asarray(value, dtype=entry.array.dtype).reshape(
                entry.array.shape
            )
            entry.last_access = time.monotonic()
            had_store_copy = entry.in_store
            entry.in_store = False
        if had_store_copy:
            # The arena held PRE-reduce bytes: a later spill/restore or
            # host-path pull must not resurrect them. Delete the copy; the
            # next materialize reseals from the combined array.
            async def _free_store():
                try:
                    await self.cw.raylet.acall("free_object", {"object_id": oid_hex})
                except Exception:
                    pass

            self.cw._io.spawn(_free_store())

    def _schedule_mailbox_janitor(self, key: str, delay_s: float = 180.0):
        # mailbox_key layout: collective/<group>/p2p/<src>-><dst>/<tag> —
        # the sweep also runs the per-group stale-row janitor (dead-epoch
        # roster/coord rows, orphaned addr rows of departed members).
        parts = key.split("/")
        group_name = parts[1] if len(parts) > 2 and parts[0] == "collective" else None

        async def _sweep():
            import asyncio

            await asyncio.sleep(delay_s)
            try:
                await self.cw.gcs.acall("kv_del", {"key": key})
            except Exception:
                pass
            if group_name:
                from ray_tpu.util.collective.p2p import sweep_stale_group_rows

                await sweep_stale_group_rows(self.cw.gcs, group_name)

        self.cw._io.spawn(_sweep())

    # ---- release (ownership protocol: owner's last ref dropped) ----

    @any_thread
    def free(self, oid_hex: str):
        with self._lock:
            entry = self._entries.pop(oid_hex, None)
        if entry is None:
            return
        DEVOBJ_STATS.frees += 1
        flight_recorder.record("devobj_free", oid_hex[:12])
        if not entry.scope:  # channel payloads never wrote a registry row
            self._registry_del(oid_hex)
        if entry.in_store:
            # The arena/spilled copy is holder-managed (the owner's plasma
            # bookkeeping never saw it) — delete it cluster-wide here.
            async def _free_store():
                try:
                    await self.cw.raylet.acall("free_object", {"object_id": oid_hex})
                except Exception:
                    pass

            self.cw._io.spawn(_free_store())

    # ---- introspection ----

    def usage(self) -> dict:
        with self._lock:
            live = [e for e in self._entries.values() if e.array is not None]
            spilled = sum(1 for e in self._entries.values() if e.array is None)
            return {
                "resident_count": len(live),
                "resident_bytes": sum(e.meta.nbytes for e in live),
                "spilled_count": spilled,
            }

    def object_ids(self) -> list[str]:
        with self._lock:
            return list(self._entries)

    # ---- cluster state registry (best-effort, async) ----

    def _registry_put(self, meta):
        row = json.dumps(
            {
                "object_id": meta.object_id,
                "shape": list(meta.shape),
                "dtype": meta.dtype,
                "nbytes": meta.nbytes,
                "transport": meta.transport,
                "holder_id": meta.holder_id,
                "holder_kind": meta.holder_kind,
                "node_id": self.cw.node_id,
                "created_ts": meta.created_ts,
            }
        ).encode()  # the GCS KV schema takes bytes values

        async def _put():
            try:
                await self.cw.gcs.acall(
                    "kv_put", {"key": f"devobj/{meta.object_id}", "value": row}
                )
            except Exception:
                pass

        self.cw._io.spawn(_put())

    def _registry_del(self, oid_hex: str):
        async def _del():
            try:
                await self.cw.gcs.acall("kv_del", {"key": f"devobj/{oid_hex}"})
            except Exception:
                pass

        self.cw._io.spawn(_del())
