"""DeviceObjectMeta — the small descriptor that rides the normal store.

A device object's ObjectRef resolves (via get / task-arg resolution) to one
of these instead of the payload; ``resolve.py`` then turns it back into the
live array out of band. The descriptor must stay cheap to pickle and must
import neither jax nor the core worker — it crosses process boundaries
inside ordinary object payloads.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

# Valid values for the ``tensor_transport=`` option. "collective" is the
# only transport today (group p2p with host-shm fallback); the name is the
# reference's, so code written against Ray's GPU-objects direction ports
# unchanged.
TENSOR_TRANSPORTS = ("collective",)


def validate_transport(transport) -> str:
    if transport not in TENSOR_TRANSPORTS:
        raise ValueError(
            f"tensor_transport must be one of {TENSOR_TRANSPORTS}, got {transport!r}"
        )
    return transport


@dataclass
class DeviceObjectMeta:
    """Everything a consumer needs to locate and reassemble the payload."""

    object_id: str  # hex — same id as the ObjectRef wrapping this descriptor
    shape: tuple
    dtype: str
    nbytes: int
    transport: str
    # Holder process: core-worker RPC address + a human-meaningful identity
    # (actor id for actors, worker/driver id otherwise) for error messages.
    holder_addr: tuple
    holder_id: str
    holder_kind: str = "driver"  # driver | worker | actor
    # Human-readable sharding summary (the full layout travels with the
    # payload itself through serialization's jax.Array reducer).
    sharding: str = ""
    # [(group_name, rank, world_size)] of collective groups the holder had
    # initialized at create time; a consumer sharing one transfers over it.
    group_hints: list = field(default_factory=list)
    created_ts: float = field(default_factory=time.time)

    def holder_label(self) -> str:
        return f"{self.holder_kind} {self.holder_id[:16]} @ {self.holder_addr}"
