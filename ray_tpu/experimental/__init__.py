"""ray_tpu.experimental — pre-stable subsystems (compiled-graph channels)."""
