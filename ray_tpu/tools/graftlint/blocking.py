"""Pass 2 — blocking calls lexically inside ``async def`` bodies.

One stalled handler stalls EVERY socket in the process (the runtime runs all
RPC on one loop thread), so anything that can block for more than a syscall
must leave the loop via ``run_in_executor``. Checked lexically per async
body; nested ``def``/``lambda`` bodies are excluded (they are deferred —
usually run by an executor), and calls that are direct arguments of an
awaited call are excluded (``await asyncio.wait_for(ev.wait(), t)`` is the
asyncio idiom, not a block).
"""

from __future__ import annotations

from ray_tpu.tools.graftlint.core import FunctionInfo, PackageIndex
from ray_tpu.tools.graftlint.findings import Finding

PASS = "blocking"

_SUBPROCESS_FNS = {"run", "call", "check_call", "check_output", "Popen"}
_SOCKET_FNS = {"recv", "recv_into", "accept", "sendall", "makefile", "connect"}
_SYNC_WAITERS = {"wait", "acquire"}
_THREADY_RE = ("thread", "proc", "worker")


def _check_call(mod_from_imports, fi: FunctionInfo, cs) -> tuple[str, str] | None:
    """(code, what) if this call blocks, else None."""
    name, recv = cs.name, cs.receiver
    low = recv.lower()
    if name == "sleep":
        if recv == "time" or (
            recv == "" and mod_from_imports.get("sleep", ("", ""))[0] == "time"
        ):
            return "sleep-in-async", f"{recv or 'time'}.sleep"
        return None
    if recv == "subprocess" and name in _SUBPROCESS_FNS:
        return "subprocess-in-async", f"subprocess.{name}"
    if recv == "" and mod_from_imports.get(name, ("", ""))[0] == "subprocess":
        return "subprocess-in-async", f"subprocess.{name}"
    if name == "open" and recv == "":
        return "file-io-in-async", "open()"
    if recv == "os" and name in ("system", "popen"):
        return "file-io-in-async", f"os.{name}"
    if name == "result" and not cs.awaited and not cs.arg_of_awaited:
        return "future-result-in-async", f"{recv}.result()"
    if (
        name in _SYNC_WAITERS
        and not cs.awaited
        and not cs.arg_of_awaited
        and "asyncio" not in low
    ):
        return "sync-wait-in-async", f"{recv}.{name}()"
    if (
        name == "join"
        and not cs.awaited
        and not cs.arg_of_awaited
        and any(h in low for h in _THREADY_RE)
    ):
        return "thread-join-in-async", f"{recv}.join()"
    if name in _SOCKET_FNS and "sock" in low:
        return "socket-io-in-async", f"{recv}.{name}()"
    return None


def run(index: PackageIndex) -> list[Finding]:
    findings: list[Finding] = []
    for fi in index.all_functions():
        if not fi.is_async:
            continue
        mod = index.module_of(fi)
        for cs in fi.calls:
            hit = _check_call(mod.from_imports, fi, cs)
            if hit is None:
                continue
            code, what = hit
            findings.append(
                Finding(
                    pass_name=PASS,
                    code=code,
                    file=fi.relpath,
                    line=cs.lineno,
                    symbol=fi.qualname,
                    detail=what,
                    message=(
                        f"{what} blocks the event loop inside async "
                        f"{fi.qualname}; move it off-loop (run_in_executor) "
                        "or use the asyncio equivalent"
                    ),
                )
            )
    return findings
