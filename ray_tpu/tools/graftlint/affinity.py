"""Pass 1 — loop affinity.

Builds an intra-package call graph (same-thread edges only: threadsafe hops
and executor/thread spawns are *context switches*, not calls) and checks:

- ``affinity-leak``: a path from a thread entry point (``threading.Thread``
  target, ``run_in_executor``/``submit`` callable, ``@any_thread`` API,
  public module-level sync API) into a ``@loop_only`` function with no
  ``call_soon_threadsafe``/``run_coroutine_threadsafe`` hop in between.
- ``blocking-on-loop``: a path from loop context (``async def`` bodies,
  ``@loop_only`` functions, threadsafe-hop targets) into a ``@blocking``
  function with no ``run_in_executor`` hop in between (deadlock risk: the
  loop waits on something only the loop can produce).
- ``redundant-hop``: provably-on-loop code (``@loop_only`` or ``async def``)
  paying for a ``call_soon_threadsafe``/``run_coroutine_threadsafe`` round
  trip it does not need.
"""

from __future__ import annotations

from ray_tpu.tools.graftlint.core import FunctionInfo, PackageIndex, resolve_call
from ray_tpu.tools.graftlint.findings import Finding

PASS = "affinity"


def _edges(index: PackageIndex, fi: FunctionInfo):
    """Resolved same-thread call edges out of ``fi`` (callee, lineno)."""
    out = []
    for cs in fi.calls:
        target = resolve_call(index, fi, cs.name, cs.receiver)
        if target is not None and target.key != fi.key:
            out.append((target, cs.lineno))
    return out


def _resolved_targets(index: PackageIndex, fi: FunctionInfo, pairs):
    out = []
    for name, receiver, lineno in pairs:
        target = resolve_call(index, fi, name, receiver)
        if target is not None:
            out.append((target, lineno))
    return out


def run(index: PackageIndex) -> list[Finding]:
    findings: list[Finding] = []
    edge_cache: dict[str, list] = {}

    def edges_of(fi):
        if fi.key not in edge_cache:
            edge_cache[fi.key] = _edges(index, fi)
        return edge_cache[fi.key]

    # ---- root sets -------------------------------------------------------
    any_roots: list[FunctionInfo] = []
    loop_roots: list[FunctionInfo] = []
    for fi in index.all_functions():
        if fi.is_async or "loop_only" in fi.markers:
            loop_roots.append(fi)
        elif "any_thread" in fi.markers:
            any_roots.append(fi)
        # Public module-level sync API (ray_tpu/__init__.py) runs on user
        # threads by definition.
        if (
            not fi.is_async
            and fi.cls is None
            and "." not in fi.qualname
            and not fi.name.startswith("_")
            and fi.relpath.endswith("__init__.py")
            and fi.relpath.count("/") + fi.relpath.count("\\") <= 1
        ):
            any_roots.append(fi)
        for target, lineno in _resolved_targets(index, fi, fi.thread_targets):
            if not target.is_async:
                any_roots.append(target)
        for target, lineno in _resolved_targets(index, fi, fi.hop_targets):
            loop_roots.append(target)

    # ---- ANY-context BFS: reaching @loop_only is a leak ------------------
    seen: dict[str, tuple] = {}  # key -> (parent_key, via_lineno)
    queue: list[FunctionInfo] = []
    for root in any_roots:
        if root.is_async or "loop_only" in root.markers or root.key in seen:
            continue
        seen[root.key] = (None, root.lineno)
        queue.append(root)
    while queue:
        fi = queue.pop(0)
        for callee, lineno in edges_of(fi):
            if callee.is_async:
                continue  # bare call of an async fn only builds a coroutine
            if "loop_only" in callee.markers:
                chain = _chain(index, seen, fi.key) + [callee.qualname]
                findings.append(
                    Finding(
                        pass_name=PASS,
                        code="affinity-leak",
                        file=fi.relpath,
                        line=lineno,
                        symbol=fi.qualname,
                        detail=callee.qualname,
                        message=(
                            f"{callee.qualname} is @loop_only but is reachable "
                            f"from a thread context without a threadsafe hop: "
                            + " -> ".join(chain)
                        ),
                    )
                )
                continue
            if "any_thread" in callee.markers:
                pass  # documented cross-thread entry: keep walking its body
            if callee.key not in seen:
                seen[callee.key] = (fi.key, lineno)
                queue.append(callee)

    # ---- LOOP-context BFS: reaching @blocking is a deadlock risk ---------
    lseen: dict[str, tuple] = {}
    lqueue: list[FunctionInfo] = []
    for root in loop_roots:
        if "blocking" in root.markers or root.key in lseen:
            continue
        lseen[root.key] = (None, root.lineno)
        lqueue.append(root)
    while lqueue:
        fi = lqueue.pop(0)
        for callee, lineno in edges_of(fi):
            if "blocking" in callee.markers:
                chain = _chain(index, lseen, fi.key) + [callee.qualname]
                findings.append(
                    Finding(
                        pass_name=PASS,
                        code="blocking-on-loop",
                        file=fi.relpath,
                        line=lineno,
                        symbol=fi.qualname,
                        detail=callee.qualname,
                        message=(
                            f"{callee.qualname} is @blocking but is reachable "
                            f"from loop context without a run_in_executor hop: "
                            + " -> ".join(chain)
                        ),
                    )
                )
                continue
            if callee.key not in lseen:
                lseen[callee.key] = (fi.key, lineno)
                lqueue.append(callee)

    # ---- redundant threadsafe hops from provably-on-loop code ------------
    for fi in index.all_functions():
        definitely_loop = ("loop_only" in fi.markers or fi.is_async) and (
            "any_thread" not in fi.markers
        )
        if not definitely_loop:
            continue
        for kind, lineno in fi.hop_sites:
            findings.append(
                Finding(
                    pass_name=PASS,
                    code="redundant-hop",
                    file=fi.relpath,
                    line=lineno,
                    symbol=fi.qualname,
                    detail=kind,
                    message=(
                        f"{fi.qualname} always runs on the event loop but uses "
                        f"{kind}; call directly (or ensure_future) — the "
                        "threadsafe hop costs a wakeup and hides the affinity"
                    ),
                )
            )
    return findings


def _chain(index: PackageIndex, seen: dict, key: str) -> list[str]:
    names = []
    hops = 0
    while key is not None and hops < 20:
        fi = index.by_key.get(key)
        if fi is None:
            break
        names.append(fi.qualname)
        key = seen.get(key, (None, 0))[0]
        hops += 1
    return list(reversed(names))


def suggest_annotations(index: PackageIndex) -> list[str]:
    """--fix-annotations report: unannotated functions whose role is implied
    by how they are scheduled."""
    suggestions = []
    hop_targets: dict[str, int] = {}
    thread_targets: dict[str, int] = {}
    for fi in index.all_functions():
        for target, lineno in _resolved_targets(index, fi, fi.hop_targets):
            hop_targets.setdefault(target.key, lineno)
        for target, lineno in _resolved_targets(index, fi, fi.thread_targets):
            thread_targets.setdefault(target.key, lineno)
    for key in sorted(hop_targets):
        fi = index.by_key[key]
        if not fi.markers and not fi.is_async:
            suggestions.append(
                f"{fi.relpath}:{fi.lineno}: {fi.qualname} is scheduled onto the "
                "loop (call_soon_threadsafe/run_coroutine_threadsafe target) — "
                "consider @loop_only"
            )
    for key in sorted(thread_targets):
        fi = index.by_key[key]
        if not fi.markers and not fi.is_async:
            suggestions.append(
                f"{fi.relpath}:{fi.lineno}: {fi.qualname} runs on an executor/"
                "thread (Thread target / run_in_executor / submit) — consider "
                "@any_thread (and audit what it calls)"
            )
    return suggestions
