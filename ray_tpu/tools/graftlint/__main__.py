import sys

from ray_tpu.tools.graftlint.cli import main

sys.exit(main())
