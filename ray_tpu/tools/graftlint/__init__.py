"""graftlint — concurrency static analysis for the ray_tpu runtime.

Machine-checks the three families of invariants the runtime's hot paths
rely on (see CONCURRENCY.md and ray_tpu/_private/concurrency.py):

1. **Loop affinity** (``passes: affinity``): call-graph analysis proving no
   path from a thread entry point reaches a ``@loop_only`` function without a
   ``call_soon_threadsafe``/``run_coroutine_threadsafe`` hop, no loop-context
   path reaches a ``@blocking`` function without a ``run_in_executor`` hop,
   and no provably-on-loop code pays for a redundant threadsafe hop.
2. **Blocking-in-async** (``blocking``): lexical scan of ``async def`` bodies
   for calls that stall the event loop (``time.sleep``, ``subprocess``,
   sync ``Event.wait``/``Lock.acquire``, ``cf.Future.result``, file/socket
   IO).
3. **Lock order** (``lockorder``): extracts the sync-lock nesting relation
   (including one level of interprocedural summaries), reports cycles
   (AB/BA deadlocks), self-nesting of non-reentrant locks, and ``await``
   reachable while a sync lock is held.

Run: ``python -m ray_tpu.tools.graftlint ray_tpu/`` (never imports the
analyzed code — pure AST). A committed ``graftlint_baseline.json`` makes CI
fail only on NEW violations. Suppress a single finding in place with a
``# graftlint: ignore[<code>]`` comment on the offending line.
"""

from ray_tpu.tools.graftlint.core import PackageIndex  # noqa: F401
from ray_tpu.tools.graftlint.cli import main  # noqa: F401
