"""AST index shared by the graftlint passes.

Parses every ``.py`` file under the analyzed roots (never imports them) and
records, per function: markers (``@loop_only``/``@any_thread``/``@blocking``),
call sites with receiver text, threadsafe-hop and thread-spawn targets, lock
``with``-blocks, and awaits. The passes (affinity/blocking/lockorder) consume
this index; resolution of call sites to functions lives in resolve().
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

MARKERS = {"loop_only", "any_thread", "blocking"}

# Constructs that schedule a callable ONTO an event loop (a legal hop from a
# foreign thread; the scheduled callee runs in loop context).
HOP_SCHEDULERS = {"call_soon_threadsafe", "run_coroutine_threadsafe"}
# Constructs that schedule a coroutine on the CURRENT loop (callee is loop
# context, caller must already be on the loop — not a cross-thread hop).
LOOP_SCHEDULERS = {"ensure_future", "create_task", "call_soon", "call_later"}
# EventLoopThread.run/.spawn wrap run_coroutine_threadsafe; recognized via
# receiver hints (see HINTS) so e.g. subprocess.run is not misread.
IO_SCHEDULERS = {"run", "spawn"}
IO_RECEIVER_RE = re.compile(r"(^|\.)_?io$|_io\b|io_loop|loop_thread", re.IGNORECASE)

LOCKISH_RE = re.compile(r"lock|cond|mutex", re.IGNORECASE)

_IGNORE_RE = re.compile(r"#\s*graftlint:\s*ignore\[([a-z0-9_,\- ]+)\]")


@dataclass
class CallSite:
    name: str          # simple callee name
    receiver: str      # unparsed receiver expression text ("" = bare name)
    lineno: int
    awaited: bool = False
    arg_of_awaited: bool = False
    held_locks: tuple = ()  # lock ids held at this call site (lexically)


@dataclass
class WithLock:
    lock_id: str
    lineno: int
    is_async_ctx: bool  # `async with` (asyncio lock) — informational only


@dataclass
class FunctionInfo:
    key: str            # f"{relpath}::{qualname}"
    relpath: str
    qualname: str
    name: str
    cls: str | None
    lineno: int
    is_async: bool
    markers: set = field(default_factory=set)
    calls: list = field(default_factory=list)       # [CallSite]
    hop_targets: list = field(default_factory=list)     # [(name, receiver, lineno)]
    thread_targets: list = field(default_factory=list)  # [(name, receiver, lineno)]
    hop_sites: list = field(default_factory=list)       # [(kind, lineno)] threadsafe hops USED
    direct_locks: set = field(default_factory=set)      # lock ids acquired in this body
    lock_edges: list = field(default_factory=list)      # [(outer_id, inner_id, lineno)]
    awaits_under: list = field(default_factory=list)    # [(lock_ids, lineno)] await w/ sync lock held
    nested: dict = field(default_factory=dict)          # simple name -> FunctionInfo


@dataclass
class ModuleInfo:
    relpath: str
    stem: str
    functions: dict = field(default_factory=dict)   # qualname -> FunctionInfo
    toplevel: dict = field(default_factory=dict)    # name -> FunctionInfo
    classes: dict = field(default_factory=dict)     # cls -> {meth: FunctionInfo}
    bases: dict = field(default_factory=dict)       # cls -> [base-name]
    imports: dict = field(default_factory=dict)     # local name -> dotted module
    from_imports: dict = field(default_factory=dict)  # local name -> (module, orig)
    sync_locks: dict = field(default_factory=dict)  # f"{cls}.{attr}"/f"{stem}.{name}" -> "Lock"/"RLock"/...
    async_locks: set = field(default_factory=set)   # ids assigned from asyncio.*
    ignores: dict = field(default_factory=dict)     # lineno -> set(codes)


def _expr_text(node) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse is total on valid trees
        return "?"


def _callee_parts(call: ast.Call) -> tuple[str, str]:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr, _expr_text(f.value)
    if isinstance(f, ast.Name):
        return f.id, ""
    return "", _expr_text(f)


def _callable_ref(node) -> tuple[str, str] | None:
    """(name, receiver) for a callable reference passed as an argument."""
    # functools.partial(f, ...) / lambda wrappers around a single call
    if isinstance(node, ast.Call):
        name, _ = _callee_parts(node)
        if name == "partial" and node.args:
            return _callable_ref(node.args[0])
        return _callee_parts(node)  # e.g. run_coroutine_threadsafe(self._foo(...))
    if isinstance(node, ast.Attribute):
        return node.attr, _expr_text(node.value)
    if isinstance(node, ast.Name):
        return node.id, ""
    return None


_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}


class _ModuleVisitor(ast.NodeVisitor):
    def __init__(self, relpath: str, src: str):
        self.mod = ModuleInfo(relpath=relpath, stem=os.path.basename(relpath)[:-3])
        for i, line in enumerate(src.splitlines(), 1):
            m = _IGNORE_RE.search(line)
            if m:
                self.mod.ignores[i] = {c.strip() for c in m.group(1).split(",")}
        self._cls_stack: list[str] = []
        self._fn_stack: list[FunctionInfo] = []

    # ---- imports ----

    def visit_Import(self, node):
        for a in node.names:
            self.mod.imports[a.asname or a.name.split(".")[0]] = a.name

    def visit_ImportFrom(self, node):
        if node.module:
            for a in node.names:
                self.mod.from_imports[a.asname or a.name] = (node.module, a.name)

    # ---- classes / functions ----

    def visit_ClassDef(self, node):
        self._cls_stack.append(node.name)
        self.mod.classes.setdefault(node.name, {})
        self.mod.bases[node.name] = [
            b.id if isinstance(b, ast.Name) else getattr(b, "attr", "")
            for b in node.bases
        ]
        self.generic_visit(node)
        self._cls_stack.pop()

    def _enter_function(self, node, is_async: bool):
        if self._fn_stack:
            cls = self._fn_stack[-1].cls  # nested def keeps the method's class
        elif self._cls_stack:
            cls = self._cls_stack[-1]
        else:
            cls = None
        if self._fn_stack:
            qual = f"{self._fn_stack[-1].qualname}.<locals>.{node.name}"
        elif cls:
            qual = f"{cls}.{node.name}"
        else:
            qual = node.name
        fi = FunctionInfo(
            key=f"{self.mod.relpath}::{qual}",
            relpath=self.mod.relpath,
            qualname=qual,
            name=node.name,
            cls=cls,
            lineno=node.lineno,
            is_async=is_async,
        )
        for dec in node.decorator_list:
            ref = _callable_ref(dec)
            if ref and ref[0] in MARKERS:
                fi.markers.add(ref[0])
        self.mod.functions[qual] = fi
        if self._fn_stack:
            self._fn_stack[-1].nested[node.name] = fi
        elif cls:
            self.mod.classes[cls][node.name] = fi
        else:
            self.mod.toplevel[node.name] = fi
        self._fn_stack.append(fi)
        _BodyVisitor(self, fi).run(node)
        # Descend into NESTED function definitions (the body visitor skipped
        # them); their call sites belong to their own FunctionInfo. The parent
        # stays on the stack so nested qualnames get the <locals> prefix.
        for child in node.body:
            self._recurse_defs(child)
        self._fn_stack.pop()

    def _recurse_defs(self, node):
        if isinstance(node, ast.FunctionDef):
            self._enter_function(node, is_async=False)
            return
        if isinstance(node, ast.AsyncFunctionDef):
            self._enter_function(node, is_async=True)
            return
        for child in ast.iter_child_nodes(node):
            self._recurse_defs(child)

    def visit_FunctionDef(self, node):
        self._enter_function(node, is_async=False)

    def visit_AsyncFunctionDef(self, node):
        self._enter_function(node, is_async=True)

    # ---- lock classification (self.X = threading.Lock() / asyncio.Lock()) ----

    def note_lock_assign(self, target, value, cls: str | None):
        if not isinstance(value, ast.Call):
            return
        name, recv = _callee_parts(value)
        if name not in _LOCK_CTORS:
            return
        if isinstance(target, ast.Attribute) and isinstance(target.value, ast.Name) \
                and target.value.id in ("self", "cls") and cls:
            lock_id = f"{cls}.{target.attr}"
        elif isinstance(target, ast.Name) and not self._cls_stack:
            lock_id = f"{self.mod.stem}.{target.id}"
        elif isinstance(target, ast.Name) and self._cls_stack:
            lock_id = f"{self._cls_stack[-1]}.{target.id}"
        else:
            return
        if recv == "asyncio" or self.mod.imports.get(recv) == "asyncio":
            self.mod.async_locks.add(lock_id)
        else:
            self.mod.sync_locks[lock_id] = name

    def visit_Assign(self, node):
        cls = self._cls_stack[-1] if self._cls_stack else None
        for t in node.targets:
            self.note_lock_assign(t, node.value, cls)
        self.generic_visit(node)


class _BodyVisitor(ast.NodeVisitor):
    """Visits ONE function body; does not descend into nested defs/lambdas."""

    def __init__(self, mv: _ModuleVisitor, fi: FunctionInfo):
        self.mv = mv
        self.fi = fi
        self._scheduled: set = set()   # Call node ids consumed by hop wrappers
        self._await_args: set = set()  # Call node ids that are args of awaited calls
        self._awaited: set = set()     # Call node ids directly awaited
        self._held: list[str] = []

    def run(self, node):
        for child in node.body:
            self.visit(child)

    # never descend into nested defs / lambdas — separate bodies
    def visit_FunctionDef(self, node):
        cls = self.mv._cls_stack[-1] if self.mv._cls_stack else None
        for t in [n for n in ast.walk(node) if isinstance(n, ast.Assign)]:
            for tgt in t.targets:
                self.mv.note_lock_assign(tgt, t.value, cls)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass

    # function-local imports feed the same module-level resolution maps
    def visit_Import(self, node):
        self.mv.visit_Import(node)

    def visit_ImportFrom(self, node):
        self.mv.visit_ImportFrom(node)

    def visit_Assign(self, node):
        cls = self.mv._cls_stack[-1] if self.mv._cls_stack else None
        for t in node.targets:
            self.mv.note_lock_assign(t, node.value, cls)
        self.generic_visit(node)

    def visit_Await(self, node):
        if isinstance(node.value, ast.Call):
            self._awaited.add(id(node.value))
            for arg in list(node.value.args) + [k.value for k in node.value.keywords]:
                if isinstance(arg, ast.Call):
                    self._await_args.add(id(arg))
        if self._held:
            self.fi.awaits_under.append((tuple(self._held), node.lineno))
        self.generic_visit(node)

    # ---- locks ----

    def _lock_id_for(self, expr) -> str | None:
        text = _expr_text(expr)
        cls = self.fi.cls
        if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) \
                and expr.value.id in ("self", "cls"):
            lock_id = f"{cls}.{expr.attr}" if cls else f"{self.mv.mod.stem}.{expr.attr}"
            if lock_id in self.mv.mod.async_locks:
                return None
            if lock_id in self.mv.mod.sync_locks or LOCKISH_RE.search(expr.attr):
                return lock_id
            return None
        if isinstance(expr, ast.Name):
            mod_id = f"{self.mv.mod.stem}.{expr.id}"
            if mod_id in self.mv.mod.async_locks:
                return None
            if mod_id in self.mv.mod.sync_locks:
                return mod_id
            if LOCKISH_RE.search(expr.id):
                return mod_id
            return None
        if isinstance(expr, ast.Attribute):
            # Class-level / foreign-object locks: Cls._instance_lock etc.
            base = _expr_text(expr.value)
            cand = f"{base}.{expr.attr}"
            if cand in self.mv.mod.sync_locks:
                return cand
            for c in self.mv.mod.classes:
                if base == c and f"{c}.{expr.attr}" in self.mv.mod.sync_locks:
                    return f"{c}.{expr.attr}"
            if LOCKISH_RE.search(expr.attr):
                return f"{self.fi.cls or self.mv.mod.stem}.{expr.attr}"
            return None
        if LOCKISH_RE.search(text):
            norm = re.sub(r"""['"\s]""", "", text).replace("self.", "")
            return f"{self.fi.cls or self.mv.mod.stem}.{norm}"
        return None

    def _visit_with(self, node, is_async: bool):
        ids = []
        for item in node.items:
            lock_id = None if is_async else self._lock_id_for(item.context_expr)
            if lock_id is not None:
                for outer in self._held:
                    self.fi.lock_edges.append((outer, lock_id, node.lineno))
                ids.append(lock_id)
                self.fi.direct_locks.add(lock_id)
            if isinstance(item.context_expr, ast.Call):
                self.visit(item.context_expr)
        self._held.extend(ids)
        for child in node.body:
            self.visit(child)
        for _ in ids:
            self._held.pop()

    def visit_With(self, node):
        self._visit_with(node, is_async=False)

    def visit_AsyncWith(self, node):
        self._visit_with(node, is_async=True)

    # ---- calls ----

    def visit_Call(self, node):
        name, receiver = _callee_parts(node)
        is_io_recv = bool(IO_RECEIVER_RE.search(receiver)) if receiver else False
        if name in HOP_SCHEDULERS and node.args:
            ref = _callable_ref(node.args[0])
            if ref:
                self.fi.hop_targets.append((ref[0], ref[1], node.lineno))
            if isinstance(node.args[0], ast.Call):
                self._scheduled.add(id(node.args[0]))
            self.fi.hop_sites.append((name, node.lineno))
        elif name in LOOP_SCHEDULERS and node.args:
            arg = node.args[-1] if name == "call_later" else node.args[0]
            ref = _callable_ref(arg)
            if ref:
                self.fi.hop_targets.append((ref[0], ref[1], node.lineno))
            if isinstance(arg, ast.Call):
                self._scheduled.add(id(arg))
        elif name in IO_SCHEDULERS and is_io_recv and node.args:
            ref = _callable_ref(node.args[0])
            if ref:
                self.fi.hop_targets.append((ref[0], ref[1], node.lineno))
            if isinstance(node.args[0], ast.Call):
                self._scheduled.add(id(node.args[0]))
        elif name == "run_in_executor" and len(node.args) >= 2:
            ref = _callable_ref(node.args[1])
            if ref:
                self.fi.thread_targets.append((ref[0], ref[1], node.lineno))
            if isinstance(node.args[1], ast.Call):
                self._scheduled.add(id(node.args[1]))
        elif name == "Thread" and receiver in ("", "threading"):
            for kw in node.keywords:
                if kw.arg == "target":
                    ref = _callable_ref(kw.value)
                    if ref:
                        self.fi.thread_targets.append((ref[0], ref[1], node.lineno))
        elif name in ("submit", "submit_callback") and node.args:
            refs = [node.args[0]]
            if name == "submit_callback" and len(node.args) >= 3:
                refs.append(node.args[2])  # the delivery callback runs on the
                # exec thread too
            for r in refs:
                ref = _callable_ref(r)
                if ref:
                    self.fi.thread_targets.append((ref[0], ref[1], node.lineno))
        if id(node) not in self._scheduled and name:
            self.fi.calls.append(
                CallSite(
                    name=name,
                    receiver=receiver,
                    lineno=node.lineno,
                    awaited=id(node) in self._awaited,
                    arg_of_awaited=id(node) in self._await_args,
                    held_locks=tuple(self._held),
                )
            )
        self.generic_visit(node)


class PackageIndex:
    """All modules under the analyzed roots, plus cross-module resolution."""

    def __init__(self, roots: list[str], exclude: tuple[str, ...] = ("__pycache__",)):
        self.roots = [os.path.abspath(r) for r in roots]
        self.base = (
            os.path.dirname(self.roots[0])
            if os.path.isdir(self.roots[0])
            else os.getcwd()
        )
        self.modules: dict[str, ModuleInfo] = {}
        self.errors: list[str] = []
        for path in self._iter_files(exclude):
            rel = os.path.relpath(path, self.base)
            try:
                with open(path, "r", encoding="utf-8") as f:
                    src = f.read()
                tree = ast.parse(src)
            except (SyntaxError, UnicodeDecodeError, OSError) as e:
                self.errors.append(f"{rel}: {e}")
                continue
            mv = _ModuleVisitor(rel, src)
            mv.visit(tree)
            self.modules[rel] = mv.mod
        # name -> [FunctionInfo] (marked functions only: the cross-object
        # resolution set — precise where it matters, silent elsewhere)
        self.marked_by_name: dict[str, list[FunctionInfo]] = {}
        self.by_key: dict[str, FunctionInfo] = {}
        self.class_methods: dict[str, dict] = {}  # cls -> {meth: FI} package-wide
        for mod in self.modules.values():
            for fi in mod.functions.values():
                self.by_key[fi.key] = fi
                if fi.markers:
                    self.marked_by_name.setdefault(fi.name, []).append(fi)
            for cls, meths in mod.classes.items():
                self.class_methods.setdefault(cls, {}).update(meths)

    def _iter_files(self, exclude):
        for root in self.roots:
            if os.path.isfile(root):
                yield root
                continue
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames[:] = [d for d in dirnames if d not in exclude]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)

    def module_of(self, fi: FunctionInfo) -> ModuleInfo:
        return self.modules[fi.relpath]

    def all_functions(self):
        for mod in self.modules.values():
            yield from mod.functions.values()

    def ignored(self, relpath: str, lineno: int, code: str) -> bool:
        mod = self.modules.get(relpath)
        if mod is None:
            return False
        codes = mod.ignores.get(lineno)
        return codes is not None and (code in codes or "all" in codes)


# ---------------------------------------------------------------------------
# Call-site resolution
# ---------------------------------------------------------------------------

# Method names too generic to resolve package-wide by name alone: an edge is
# only drawn when the receiver text passes the hint for the marked target.
# Hints match as standalone identifiers within the receiver expression, so
# ``self._workers.get(id)`` (a dict lookup) never resolves to CoreWorker.get
# while ``self.cw.get(...)`` does.
RECEIVER_HINTS = {
    "call": ("gcs", "raylet", "client", "owner"),
    "push": ("gcs", "raylet", "client", "owner"),
    "run": ("_io", "io"),
    "spawn": ("_io", "io"),
    "get": ("cw", "core_worker", "get_core_worker"),
    "put": ("cw", "core_worker", "get_core_worker"),
    "wait": ("cw", "core_worker", "get_core_worker"),
    "submit": ("lease_mgr", "lease_manager", "get_lease_manager"),
}
# Generic names that must NEVER resolve package-wide without a hint entry.
NEVER_GLOBAL = {"close", "start", "stop", "cancel", "send", "write", "read", "main"}


def _receiver_tail(receiver: str) -> str:
    """Final attribute component of a receiver expression: dots inside
    parens/brackets don't split (``self._owner_client(tuple(a.b))`` ->
    ``_owner_client(tuple(a.b))``; ``self.cw.pending_tasks`` ->
    ``pending_tasks``)."""
    depth = 0
    last = 0
    for i, ch in enumerate(receiver):
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        elif ch == "." and depth == 0:
            last = i + 1
    return receiver[last:]


def _hint_ok(name: str, receiver: str) -> bool:
    hints = RECEIVER_HINTS.get(name)
    if hints is None:
        return name not in NEVER_GLOBAL
    tail = _receiver_tail(receiver).lower()
    return any(
        re.search(rf"(^|[._(\s_]){re.escape(h)}($|[._(\s)_])", tail) for h in hints
    )


def resolve_call(
    index: "PackageIndex",
    caller: FunctionInfo,
    name: str,
    receiver: str,
    local_only: bool = False,
) -> FunctionInfo | None:
    """Best-effort: the FunctionInfo a call site refers to, or None.

    Resolution order: nested defs of the caller, bare module-level names,
    ``self.``/``cls.`` methods (following in-package base classes), imported
    module attributes — then, unless ``local_only``, package-wide resolution
    into the MARKED function set by unique method name + receiver hint."""
    mod = index.module_of(caller)
    if receiver == "":
        cur = caller
        while cur is not None:
            if name in cur.nested:
                return cur.nested[name]
            parent_qual = cur.qualname.rsplit(".<locals>.", 1)[0]
            cur = mod.functions.get(parent_qual) if ".<locals>." in cur.qualname else None
        if name in mod.toplevel:
            return mod.toplevel[name]
        imp = mod.from_imports.get(name)
        if imp is not None:
            target_mod = _find_module(index, imp[0])
            if target_mod is not None:
                return target_mod.toplevel.get(imp[1])
        return None
    if receiver in ("self", "cls") and caller.cls:
        seen = set()
        queue = [caller.cls]
        while queue:
            cls = queue.pop(0)
            if cls in seen:
                continue
            seen.add(cls)
            meths = index.class_methods.get(cls, {})
            if name in meths:
                return meths[name]
            for mod2 in index.modules.values():
                for base in mod2.bases.get(cls, []):
                    if base:
                        queue.append(base)
        return None
    # module-attribute call (import ray_tpu; ray_tpu.get(...))
    dotted = mod.imports.get(receiver)
    if dotted is not None:
        target_mod = _find_module(index, dotted)
        if target_mod is not None:
            return target_mod.toplevel.get(name)
    if local_only:
        return None
    candidates = index.marked_by_name.get(name, [])
    if len({c.key for c in candidates}) == 1 and _hint_ok(name, receiver):
        return candidates[0]
    if len(candidates) > 1:
        hinted = [c for c in candidates if _hint_ok(name, receiver)]
        if len({c.key for c in hinted}) == 1:
            return hinted[0]
    return None


def _find_module(index: "PackageIndex", dotted: str):
    """ModuleInfo for a dotted import path, if it lives under the roots."""
    rel_pkg = dotted.replace(".", os.sep)
    for cand in (rel_pkg + ".py", os.path.join(rel_pkg, "__init__.py")):
        if cand in index.modules:
            return index.modules[cand]
    # Roots may be nested differently (e.g. analyzing a fixture dir): match
    # by suffix.
    for rel, mod in index.modules.items():
        if rel.endswith(rel_pkg + ".py") or rel.endswith(
            os.path.join(rel_pkg, "__init__.py")
        ):
            return mod
    return None
