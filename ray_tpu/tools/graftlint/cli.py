"""graftlint CLI.

``python -m ray_tpu.tools.graftlint ray_tpu/`` — exit 0 when every finding
is baselined or suppressed, 1 on new violations, 2 on usage errors.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

from ray_tpu.tools.graftlint import affinity, blocking, lockorder
from ray_tpu.tools.graftlint.core import PackageIndex
from ray_tpu.tools.graftlint.findings import (
    Finding,
    apply_baseline,
    load_baseline,
    write_baseline,
)

PASSES = {
    "affinity": affinity.run,
    "blocking": blocking.run,
    "lockorder": lockorder.run,
}


def default_baseline_path(target: str) -> str | None:
    """Walk up from the analyzed path looking for a committed baseline."""
    cur = os.path.abspath(target)
    if os.path.isfile(cur):
        cur = os.path.dirname(cur)
    for _ in range(6):
        cand = os.path.join(cur, "graftlint_baseline.json")
        if os.path.exists(cand):
            return cand
        parent = os.path.dirname(cur)
        if parent == cur:
            break
        cur = parent
    return None


def analyze(paths: list[str], passes=None) -> tuple[PackageIndex, list[Finding]]:
    index = PackageIndex(paths)
    findings: list[Finding] = []
    for name, fn in PASSES.items():
        if passes and name not in passes:
            continue
        findings.extend(fn(index))
    findings = [
        f for f in findings if not index.ignored(f.file, f.line, f.code)
    ]
    findings.sort(key=lambda f: (f.file, f.line, f.code, f.detail))
    return index, findings


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftlint",
        description="concurrency static analysis for the ray_tpu runtime "
        "(loop affinity / blocking-in-async / lock order)",
    )
    parser.add_argument("paths", nargs="*", default=None, help="files/dirs to analyze")
    parser.add_argument(
        "--baseline",
        default=None,
        help="baseline json (default: nearest graftlint_baseline.json above "
        "the analyzed path)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true", help="report baselined findings too"
    )
    parser.add_argument(
        "--write-baseline",
        metavar="PATH",
        default=None,
        help="write ALL current findings to PATH as the new baseline",
    )
    parser.add_argument(
        "--passes",
        default=None,
        help="comma-separated subset of passes (affinity,blocking,lockorder)",
    )
    parser.add_argument(
        "--stats", action="store_true", help="print per-pass violation counts"
    )
    parser.add_argument(
        "--fix-annotations",
        action="store_true",
        help="report unannotated functions whose affinity is implied by how "
        "they are scheduled (suggested @loop_only/@any_thread sites)",
    )
    args = parser.parse_args(argv)

    paths = args.paths or ["ray_tpu"]
    for p in paths:
        if not os.path.exists(p):
            print(f"graftlint: no such path: {p}", file=sys.stderr)
            return 2
    passes = set(args.passes.split(",")) if args.passes else None
    if passes and passes - set(PASSES):
        print(f"graftlint: unknown passes: {sorted(passes - set(PASSES))}",
              file=sys.stderr)
        return 2

    t0 = time.monotonic()
    index, findings = analyze(paths, passes)
    for err in index.errors:
        print(f"graftlint: parse error: {err}", file=sys.stderr)

    if args.write_baseline:
        write_baseline(args.write_baseline, findings)
        print(
            f"graftlint: wrote {len({f.key for f in findings})} baseline "
            f"entries to {args.write_baseline}"
        )
        return 0

    baseline_path = args.baseline or default_baseline_path(paths[0])
    baseline = set() if args.no_baseline else load_baseline(baseline_path or "")
    apply_baseline(findings, baseline)

    new = [f for f in findings if not f.baselined]
    for f in findings if args.no_baseline else new:
        print(f.render())

    if args.fix_annotations:
        suggestions = affinity.suggest_annotations(index)
        if suggestions:
            print(f"\n--fix-annotations: {len(suggestions)} suggestion(s)")
            for s in suggestions:
                print("  " + s)

    if args.stats:
        nfiles = len(index.modules)
        nfuncs = len(index.by_key)
        print(
            f"\ngraftlint: {nfiles} files, {nfuncs} functions, "
            f"{time.monotonic() - t0:.2f}s"
            + (f", baseline: {baseline_path}" if baseline_path else "")
        )
        for name in PASSES:
            sub = [f for f in findings if f.pass_name == name]
            nsub = [f for f in sub if not f.baselined]
            by_code: dict[str, int] = {}
            for f in sub:
                by_code[f.code] = by_code.get(f.code, 0) + 1
            codes = ", ".join(f"{c}={n}" for c, n in sorted(by_code.items()))
            print(
                f"  {name}: {len(sub)} finding(s), {len(nsub)} new"
                + (f" ({codes})" if codes else "")
            )

    if new:
        print(
            f"\ngraftlint: {len(new)} new violation(s)"
            + (f" ({len(findings) - len(new)} baselined)" if baseline else ""),
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
