"""Finding record + baseline file handling."""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field


@dataclass
class Finding:
    pass_name: str   # "affinity" | "blocking" | "lockorder"
    code: str        # stable violation code, e.g. "affinity-leak"
    file: str        # repo-relative path of the violating site
    line: int
    symbol: str      # enclosing function qualname (or lock-cycle id)
    detail: str      # target qualname / lock id / callee — part of the key
    message: str = ""
    baselined: bool = field(default=False, compare=False)

    @property
    def key(self) -> str:
        # Line numbers are deliberately NOT part of the key: refactors move
        # code; a baseline entry tracks the violation, not its coordinates.
        return f"{self.pass_name}:{self.file}:{self.symbol}:{self.code}:{self.detail}"

    def render(self) -> str:
        mark = " [baselined]" if self.baselined else ""
        return (
            f"{self.file}:{self.line}: [{self.pass_name}/{self.code}]{mark} "
            f"{self.message}"
        )


def load_baseline(path: str) -> set[str]:
    if not path or not os.path.exists(path):
        return set()
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return {e["key"] for e in data.get("entries", [])}


def write_baseline(path: str, findings: list[Finding]) -> None:
    entries = sorted(
        {f.key: f for f in findings}.values(), key=lambda f: f.key
    )
    data = {
        "version": 1,
        "comment": (
            "graftlint suppression baseline: committed findings that predate "
            "the linter or whose fix is risky enough to deserve its own PR. "
            "CI fails only on NEW violations. Never baseline the warm-lease "
            "hot path (_private/rpc.py, _private/lease_manager.py, "
            "_private/worker_main.py)."
        ),
        "entries": [
            {"key": f.key, "message": f.message} for f in entries
        ],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=False)
        f.write("\n")


def apply_baseline(findings: list[Finding], baseline: set[str]) -> None:
    for f in findings:
        if f.key in baseline:
            f.baselined = True
