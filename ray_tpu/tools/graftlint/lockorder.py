"""Pass 3 — sync-lock order.

Extracts the ``with <lock>:`` nesting relation per class/module into a
global lock-order graph:

- direct nesting (``with A: with B:``) gives an A→B edge;
- interprocedural edges via per-function acquired-lock summaries computed to
  a fixpoint over locally-resolvable calls (``self.x()``, same-module
  functions): holding A and calling a function that (transitively) takes B
  also gives A→B;
- cycles in the graph are potential deadlocks (``lock-cycle``);
- an A→A edge on a non-reentrant ``Lock`` is a self-deadlock
  (``lock-self-nest``); documented RLocks are exempt;
- an ``await`` lexically reachable while a sync lock is held parks the ONLY
  thread that can release it (``await-under-lock``).
"""

from __future__ import annotations

from ray_tpu.tools.graftlint.core import PackageIndex, resolve_call
from ray_tpu.tools.graftlint.findings import Finding

PASS = "lockorder"


def run(index: PackageIndex) -> list[Finding]:
    findings: list[Finding] = []

    # ---- per-function transitive acquired-lock summaries (fixpoint) ------
    acquired: dict[str, set] = {
        fi.key: set(fi.direct_locks) for fi in index.all_functions()
    }
    callees: dict[str, list] = {}
    for fi in index.all_functions():
        resolved = []
        for cs in fi.calls:
            target = resolve_call(index, fi, cs.name, cs.receiver, local_only=True)
            if target is not None and target.key != fi.key:
                resolved.append((cs, target))
        callees[fi.key] = resolved
    for _ in range(6):  # call chains deeper than 6 don't exist here
        changed = False
        for key, pairs in callees.items():
            acc = acquired[key]
            before = len(acc)
            for _cs, target in pairs:
                acc |= acquired[target.key]
            changed = changed or len(acc) != before
        if not changed:
            break

    # ---- edges: direct nesting + held-at-call-site × callee summary ------
    # edge -> (file, line, via-symbol)
    edges: dict[tuple, tuple] = {}
    rlocks = set()
    for mod in index.modules.values():
        for lock_id, ctor in mod.sync_locks.items():
            if ctor == "RLock":
                rlocks.add(lock_id)
    for fi in index.all_functions():
        for outer, inner, lineno in fi.lock_edges:
            edges.setdefault((outer, inner), (fi.relpath, lineno, fi.qualname))
        for cs, target in callees[fi.key]:
            for inner in acquired[target.key]:
                for outer in cs.held_locks:
                    edges.setdefault(
                        (outer, inner),
                        (fi.relpath, cs.lineno, f"{fi.qualname} -> {target.qualname}"),
                    )

    # ---- self-nesting of non-reentrant locks -----------------------------
    for (outer, inner), (relpath, lineno, symbol) in sorted(edges.items()):
        if outer == inner and outer not in rlocks:
            findings.append(
                Finding(
                    pass_name=PASS,
                    code="lock-self-nest",
                    file=relpath,
                    line=lineno,
                    symbol=symbol,
                    detail=outer,
                    message=(
                        f"{outer} is re-acquired while already held (via "
                        f"{symbol}); threading.Lock self-deadlocks — use an "
                        "RLock or split the critical section"
                    ),
                )
            )

    # ---- cycles (Tarjan SCC over the lock graph) -------------------------
    graph: dict[str, set] = {}
    for (outer, inner) in edges:
        if outer != inner:
            graph.setdefault(outer, set()).add(inner)
            graph.setdefault(inner, set())
    for scc in _sccs(graph):
        if len(scc) < 2:
            continue
        cyc = sorted(scc)
        sites = [
            f"{relpath}:{lineno} ({symbol})"
            for (o, i), (relpath, lineno, symbol) in sorted(edges.items())
            if o in scc and i in scc
        ]
        relpath, lineno, _ = next(
            v for (o, i), v in sorted(edges.items()) if o in scc and i in scc
        )
        findings.append(
            Finding(
                pass_name=PASS,
                code="lock-cycle",
                file=relpath,
                line=lineno,
                symbol="<cycle>",
                detail="<->".join(cyc),
                message=(
                    "lock-order cycle (potential deadlock): "
                    + " <-> ".join(cyc)
                    + "; acquisition sites: "
                    + "; ".join(sites[:6])
                ),
            )
        )

    # ---- await while holding a sync lock ---------------------------------
    for fi in index.all_functions():
        if not fi.is_async:
            continue
        for lock_ids, lineno in fi.awaits_under:
            findings.append(
                Finding(
                    pass_name=PASS,
                    code="await-under-lock",
                    file=fi.relpath,
                    line=lineno,
                    symbol=fi.qualname,
                    detail=",".join(lock_ids),
                    message=(
                        f"await in {fi.qualname} while holding sync lock(s) "
                        f"{', '.join(lock_ids)}: parks the loop thread inside "
                        "the critical section — every other acquirer (any "
                        "thread) blocks until this coroutine resumes"
                    ),
                )
            )
    return findings


def _sccs(graph: dict[str, set]):
    """Tarjan strongly-connected components (iterative)."""
    index_counter = [0]
    stack: list[str] = []
    lowlink: dict[str, int] = {}
    number: dict[str, int] = {}
    on_stack: set = set()
    result = []

    for start in graph:
        if start in number:
            continue
        work = [(start, iter(sorted(graph.get(start, ()))))]
        number[start] = lowlink[start] = index_counter[0]
        index_counter[0] += 1
        stack.append(start)
        on_stack.add(start)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in number:
                    number[nxt] = lowlink[nxt] = index_counter[0]
                    index_counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(graph.get(nxt, ())))))
                    advanced = True
                    break
                if nxt in on_stack:
                    lowlink[node] = min(lowlink[node], number[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                lowlink[parent] = min(lowlink[parent], lowlink[node])
            if lowlink[node] == number[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                result.append(comp)
    return result
