"""Flight recorder — an always-on, crash-surviving ring of runtime events.

TPU-native analog of the reference's debug-state dumpers (``ray timeline`` +
the GCS task-event plane + the per-component DebugString() dumps stitched into
``debug_state.txt``): every runtime process keeps a fixed-size ring of typed,
monotonic-stamped events covering the hot paths that logs cannot afford to
narrate — lease grant/reuse/release, task ship/exec/complete/fail, RPC
connect/reset/write-HWM stall, store seal/evict/spill, channel
write/block/poison/close, actor restarts.

The ring lives in an **mmap'd per-process file** (tmpfs under
``/dev/shm/ray_tpu_flight/<session>/`` when available — no disk writeback
can stall a record — else ``<session_dir>/flight/``) rather than process
memory: a worker SIGKILLed by the memory monitor (or the kernel) leaves its
final events in the file, so the postmortem (`ray_tpu debug dump`) actually
works — no signal handler can run under SIGKILL, and a purely in-memory
ring would die with the process.
Every ``record()`` writes straight through the mapping (two ``pack_into``
calls + a dict-free tuple), cheap enough to leave on in production; disable
with ``RAY_TPU_FLIGHT_RECORDER=0``.

Collection:

- ``CoreWorker.rpc_debug_dump`` returns the calling process's own ring;
- ``Raylet.rpc_debug_dump`` returns every ring on the node (it scans the
  flight dir, which covers processes that are already dead);
- ``GlobalState.flight_recorder_dump`` (state.py) fans out over alive
  raylets and merges rings cluster-wide ordered by stamp;
- ``ray_tpu debug dump`` (CLI) merges the rings with the GCS task events
  into one Chrome-trace JSON; the dashboard head serves the merged events
  at ``GET /api/v0/debug/flight_recorder``.

Slot format (fixed ``SLOT_SIZE`` bytes): ``<d``monotonic seconds, ``<H``
event-type code, ``<H`` detail length, then the utf-8 detail. The header
carries a (monotonic, wall) anchor pair taken at attach so readers convert
stamps to wall-clock without trusting the dead process's clock discipline.
"""

from __future__ import annotations

import collections
import mmap
import os
import struct
import threading
import time

from ray_tpu._private.concurrency import any_thread

MAGIC = 0x464C5431  # "FLT1"
VERSION = 1
HEADER_SIZE = 256
SLOT_SIZE = 96
_DETAIL_MAX = SLOT_SIZE - 12  # 8 (f64 ts) + 2 (code) + 2 (len)

# Header layout: magic u32, version u32, slots u32, slot_size u32, pid u32,
# pad u32, write_count u64, anchor_mono f64, anchor_wall f64, role 64s,
# ident 64s.
_HDR = struct.Struct("<IIIIIIQdd64s64s")
# Precompiled slot/count structs: record() is the hot path, and a dynamic
# format string would re-parse per call.
_SLOT_HDR = struct.Struct("<dHH")
_COUNT = struct.Struct("<Q")
_COUNT_OFF = 24

# Typed events. Codes are wire format — append only, never renumber.
EVENT_TYPES = (
    "mark",            # 0: free-form marker
    "lease_grant",     # 1
    "lease_reuse",     # 2
    "lease_release",   # 3
    "lease_revoked",   # 4
    "task_ship",       # 5
    "task_exec",       # 6
    "task_done",       # 7
    "task_fail",       # 8
    "rpc_connect",     # 9
    "rpc_reset",       # 10
    "rpc_hwm_stall",   # 11
    "store_seal",      # 12
    "store_evict",     # 13
    "store_spill",     # 14
    "store_restore",   # 15
    "channel_write",   # 16
    "channel_block",   # 17
    "channel_poison",  # 18
    "channel_close",   # 19
    "actor_restart",   # 20
    "worker_death",    # 21
    "fatal_signal",    # 22
    "exit",            # 23
    # Device object plane (experimental/device_object/).
    "devobj_create",   # 24
    "devobj_transfer", # 25
    "devobj_spill",    # 26
    "devobj_restore",  # 27
    "devobj_free",     # 28
    # Transfer plane (pull_manager.py / push_manager.py, PR 10).
    "transfer_pull",   # 29: pull sealed (detail oid:bytes:sources:frame)
    "transfer_push",   # 30: outbound push committed (detail oid:bytes:frame)
    "transfer_relay",  # 31: cut-through relay began forwarding pre-seal
    "admission_stall", # 32: pull queued on pull_admission_budget_bytes
    "pull_source_demoted",  # 33: pull source errored; ranked last
    # Continuous-batching LLM serving engine (serve/llm/, PR 11).
    "llm_admit",       # 34: prompt admitted into a decode slot (detail rid:T:hit:slot)
    "llm_preempt",     # 35: sequence preempted for KV blocks (recompute on readmit)
    "llm_prefix_hit",  # 36: admission reused prefix-cache blocks (detail rid:Nblk)
    "llm_evict",       # 37: refs-0 prefix-cache block evicted under pressure
    # Descriptor channel plane (device payloads through channel slots, PR 12).
    "chan_devobj_send",  # 38: channel payload eager-pushed out of band (detail cid:seq:bytes)
    "chan_devobj_recv",  # 39: descriptor slot resolved to the live value (detail cid:seq:path)
    # Chaos fault-injection plane (chaos.py, PR 13).
    "chaos_inject",    # 40: fault injected at the rpc seam (detail kind:peer:method)
    # Crash-fault dimension + self-healing serving (PR 14).
    "chaos_kill",      # 41: this process SIGKILLs itself at a frame (detail peer:method) — last words, ring survives
    "llm_migrate",     # 42: mid-stream LLM request migrated to another replica (detail deployment:ntok)
    "replica_drain",   # 43: serve replica drain begin/done (detail replica_id:phase)
    # Group collectives on the device-object plane (PR 15).
    "coll_broadcast",  # 44: holder fanned a device object to a group (detail oid:group:ok/targets:bytes)
    # Relay-tree collectives (PR 16).
    "coll_relay",      # 45: this member relayed a tree-broadcast payload to its children (detail tag:group:rank:children:bytes)
    "coll_reduce",     # 46: holder fed a device object into a group reduce/allreduce (detail oid:group:mode:rank:replaced)
    # Elastic collective groups (PR 17).
    "coll_member_change",  # 47: roster epoch advanced — join/rejoin/leave/death/advance (detail group:reason:rank:epoch:nmembers)
    # Control-plane scale hardening (PR 19).
    "locality_hit",    # 48: placement chose a node already holding the task's reference args (detail task:node)
    "gcs_overload",    # 49: GCS task-event ring dropped oldest entries under fan-in (detail dropped:total)
    # Disaggregated LLM serving (PR 20).
    "llm_kv_handoff",  # 50: prefill→decode sealed-KV import landed on the decode side (detail oid:blocks:bytes:src->dst; ':failed:' arm on fetch error)
    "llm_prefix_import",  # 51: cluster-prefix-tier KV import (detail oid:blocks:bytes:src->dst; ':error:' arm when the row's payload is gone)
)
_CODE = {name: i for i, name in enumerate(EVENT_TYPES)}


def flight_dir(session_dir: str) -> str:
    """Where this session's rings live. Prefer tmpfs (/dev/shm) keyed by the
    session name: a tmpfs mapping has no disk writeback, so a record can
    never stall on an ext4 stable-page write while the kernel flushes the
    ring — and SIGKILL durability is identical (tmpfs outlives the process,
    same guarantee class as the shm object arena; only a host reboot loses
    it, at which point the cluster is gone anyway). Falls back beside the
    session dir when /dev/shm is unavailable. Both attach() and the
    raylet's node-wide scan derive the path from session_dir through this
    one function."""
    if os.path.isdir("/dev/shm") and os.access("/dev/shm", os.W_OK):
        return os.path.join(
            "/dev/shm", "ray_tpu_flight", os.path.basename(session_dir.rstrip("/"))
        )
    return os.path.join(session_dir, "flight")


class FlightRecorder:
    """One per process. ``record()`` is safe from any thread (RLock: a
    signal handler recording mid-record on the same thread must not
    deadlock); everything writes through the mmap so the bytes survive
    SIGKILL."""

    def __init__(self, path: str, slots: int, role: str, ident: str):
        self.path = path
        self.slots = slots
        self.role = role
        self.ident = ident
        self._lock = threading.RLock()
        self._count = 0
        self._anchor_mono = time.monotonic()
        self._anchor_wall = time.time()
        size = HEADER_SIZE + slots * SLOT_SIZE
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            os.ftruncate(fd, size)
            self._mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self._write_header()
        # Hot-path locals (avoid attr lookups per record).
        self._mono = time.monotonic

    def _write_header(self):
        _HDR.pack_into(
            self._mm, 0,
            MAGIC, VERSION, self.slots, SLOT_SIZE, os.getpid(), 0,
            self._count, self._anchor_mono, self._anchor_wall,
            self.role.encode()[:64], self.ident.encode()[:64],
        )

    def set_role(self, role: str):
        with self._lock:
            self.role = role
            self._write_header()

    @any_thread
    def record(self, code: int, detail: str = ""):
        self.record_at(self._mono(), code, detail)

    @any_thread
    def record_at(self, mono: float, code: int, detail: str = ""):
        """Record with an explicit stamp (pre-attach replay keeps the
        original event times this way)."""
        data = detail.encode("utf-8", "replace")[:_DETAIL_MAX] if detail else b""
        mm = self._mm
        try:
            with self._lock:
                off = HEADER_SIZE + (self._count % self.slots) * SLOT_SIZE
                _SLOT_HDR.pack_into(mm, off, mono, code, len(data))
                if data:
                    mm[off + 12 : off + 12 + len(data)] = data
                self._count += 1
                # Publish AFTER the slot is fully written (a crash between
                # the two leaves the previous consistent count).
                _COUNT.pack_into(mm, _COUNT_OFF, self._count)
        except (ValueError, OSError):
            # A racing re-home (shutdown/init cycle) closed this mapping
            # while we held a stale reference: drop the event, never fail
            # the caller's runtime path over telemetry.
            pass

    def dump(self) -> list[dict]:
        try:
            with self._lock:
                return _read_events(
                    self._mm, self.slots, self._count,
                    self._anchor_mono, self._anchor_wall,
                )
        except (ValueError, OSError):
            return []  # mapping closed by a racing re-home

    def meta(self) -> dict:
        return {"pid": os.getpid(), "role": self.role, "ident": self.ident}

    def close(self):
        with self._lock:
            try:
                self._mm.flush()
                self._mm.close()
            except (ValueError, OSError):
                pass


def _read_events(buf, slots: int, count: int, anchor_mono: float, anchor_wall: float) -> list[dict]:
    """Decode the ring oldest-first. ``ts`` is wall-clock reconstructed from
    the writer's (monotonic, wall) anchor so rings from different processes
    merge on a comparable axis."""
    out = []
    start = 0 if count <= slots else count - slots
    for seq in range(start, count):
        off = HEADER_SIZE + (seq % slots) * SLOT_SIZE
        mono, code, dlen = struct.unpack_from("<dHH", buf, off)
        detail = bytes(buf[off + 12 : off + 12 + min(dlen, _DETAIL_MAX)]).decode(
            "utf-8", "replace"
        )
        out.append(
            {
                "seq": seq,
                "mono": mono,
                "ts": anchor_wall + (mono - anchor_mono),
                "type": EVENT_TYPES[code] if code < len(EVENT_TYPES) else f"type_{code}",
                "detail": detail,
            }
        )
    return out


def parse_file(path: str) -> dict | None:
    """Read a flight file written by any process (alive or dead). Returns
    {"pid", "role", "ident", "events": [...]}, or None if the file is not a
    valid ring (truncated header, wrong magic)."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return None
    if len(data) < HEADER_SIZE:
        return None
    (magic, _ver, slots, slot_size, pid, _pad, count, anchor_mono,
     anchor_wall, role, ident) = _HDR.unpack_from(data, 0)
    if magic != MAGIC or slot_size != SLOT_SIZE or slots <= 0:
        return None
    if len(data) < HEADER_SIZE + slots * SLOT_SIZE:
        return None
    return {
        "pid": pid,
        "role": role.rstrip(b"\x00").decode("utf-8", "replace"),
        "ident": ident.rstrip(b"\x00").decode("utf-8", "replace"),
        "events": _read_events(data, slots, count, anchor_mono, anchor_wall),
    }


def collect_dir(session_dir: str) -> list[dict]:
    """Parse every ring in the session's flight dir — this is what makes the
    postmortem work: a SIGKILLed worker can't answer an RPC, but its mmap
    file is still here with the final events."""
    d = flight_dir(session_dir)
    out = []
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return out
    for name in names:
        if not name.startswith("flight-"):
            continue
        parsed = parse_file(os.path.join(d, name))
        if parsed is not None:
            out.append(parsed)
    return out


def merge_events(processes: list[dict]) -> list[dict]:
    """Flatten per-process dumps into one stream ordered by stamp. Events
    gain pid/role (and node_id when the collector attached one — pids alone
    collide across nodes/containers) so interleavings stay attributable."""
    merged = []
    for proc in processes:
        pid, role = proc.get("pid"), proc.get("role")
        node_id = proc.get("node_id")
        for ev in proc.get("events", []):
            out = {**ev, "pid": pid, "role": role}
            if node_id is not None:
                out["node_id"] = node_id
            merged.append(out)
    merged.sort(key=lambda e: e["ts"])
    return merged


# ---------------------------------------------------------------------------
# Process-global recorder
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_recorder: FlightRecorder | None = None
# Events recorded before attach() (the GCS boots before the raylet knows the
# session dir) buffer here and replay into the ring at attach.
_pre_attach: collections.deque = collections.deque(maxlen=1024)
_enabled: bool | None = None
_atexit_registered = False


def enabled() -> bool:
    global _enabled
    if _enabled is None:
        _enabled = os.environ.get("RAY_TPU_FLIGHT_RECORDER", "1") != "0"
    return _enabled


def set_enabled(on: bool):
    """Runtime toggle (used by the overhead A/B bench; normal operation
    leaves the recorder on)."""
    global _enabled
    _enabled = bool(on)


def attach(session_dir: str, role: str, ident: str = "") -> None:
    """Bind this process's ring to a session. First caller wins the file;
    a later attach for the SAME session only refines the role label (the
    head process hosts gcs+raylet+driver), while a NEW session re-homes the
    ring (test suites init/shutdown repeatedly in one process)."""
    global _recorder
    if not enabled():
        return
    from ray_tpu._private.config import get_config

    _prune_stale_sessions(flight_dir(session_dir))
    path = os.path.join(flight_dir(session_dir), f"flight-{os.getpid()}-{role}.bin")
    with _lock:
        if _recorder is not None:
            if os.path.dirname(_recorder.path) == flight_dir(session_dir):
                if role not in _recorder.role:
                    _recorder.set_role(f"{_recorder.role}+{role}")
                return
            _recorder.close()
            _recorder = None
        try:
            rec = FlightRecorder(
                path, max(16, get_config().flight_ring_slots), role, ident
            )
        except OSError:
            return
        while _pre_attach:
            code, detail, mono = _pre_attach.popleft()
            rec.record_at(mono, code, detail)  # keep the original stamps
        _recorder = rec
    global _atexit_registered
    if not _atexit_registered:
        # Once per process: a re-homing attach must not stack registrations,
        # or the final ring ends in N duplicate 'exit' markers and muddies
        # the where-does-the-ring-end postmortem signal.
        _atexit_registered = True
        import atexit

        atexit.register(_at_exit)


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except OSError:
        return True  # EPERM etc.: exists


def _prune_stale_sessions(current_dir: str, max_age_s: float = 24 * 3600.0):
    """Rings live on tmpfs (RAM): drop sibling session dirs so long-lived
    hosts don't accumulate dead sessions' rings. A dir is pruned only when
    its mtime is old AND no ring's writer pid is still alive — mmap writes
    never refresh mtime, so age alone would delete a >24h-old LIVE
    session's rings and break its postmortem. Recent or live dirs stay —
    they are exactly the postmortem material."""
    parent = os.path.dirname(current_dir)
    try:
        names = os.listdir(parent)
    except OSError:
        return
    now = time.time()
    for name in names:
        full = os.path.join(parent, name)
        if full == current_dir:
            continue
        try:
            if now - os.path.getmtime(full) < max_age_s or not os.path.isdir(full):
                continue
            files = os.listdir(full)
            writer_pids = []
            for f in files:
                parts = f.split("-")
                if len(parts) >= 2 and parts[0] == "flight" and parts[1].isdigit():
                    writer_pids.append(int(parts[1]))
            if any(_pid_alive(p) for p in writer_pids):
                continue  # session (or a pid-reuse lookalike) still running
            for f in files:
                os.unlink(os.path.join(full, f))
            os.rmdir(full)
        except OSError:
            continue


def _at_exit():
    rec = _recorder
    if rec is not None:
        try:
            rec.record(_CODE["exit"], "")
            rec._mm.flush()
        except (ValueError, OSError):
            pass


@any_thread
def record(etype: str, detail: str = ""):
    """The one hot-path entry point. Cost when attached: one encode, two
    pack_into, an RLock round trip — leave it on. Never blocks (the RLock
    only guards two pack_into calls), so it is safe from the IO loop, the
    exec thread, and signal handlers alike."""
    if not enabled():
        return
    rec = _recorder
    code = _CODE[etype]
    if rec is None:
        _pre_attach.append((code, detail, time.monotonic()))
        # Re-check: an attach() that published between our None-read and
        # the append already drained the buffer — without this drain the
        # event would sit invisible until (wrongly) replayed into the NEXT
        # session's ring.
        if _recorder is not None:
            _drain_pre_attach()
        return
    rec.record(code, detail)


def _drain_pre_attach():
    with _lock:
        rec = _recorder
        if rec is None:
            return
        while _pre_attach:
            code, detail, mono = _pre_attach.popleft()
            rec.record_at(mono, code, detail)


@any_thread
def dump() -> dict | None:
    """This process's ring as a parse_file()-shaped dict (None when the
    recorder is disabled or unattached)."""
    rec = _recorder
    if rec is None:
        return None
    return {**rec.meta(), "events": rec.dump()}


def install_signal_dump(signums) -> None:
    """Chain a handler that records a fatal_signal event (and flushes the
    mapping) before the previous disposition runs. SIGKILL needs no handler
    — the mmap file already holds everything."""
    import signal as _signal

    for signum in signums:
        prev = _signal.getsignal(signum)

        def _handler(num, frame, _prev=prev):
            try:
                record("fatal_signal", _signal.Signals(num).name)
                rec = _recorder
                if rec is not None:
                    rec._mm.flush()
            except Exception:
                pass
            if callable(_prev):
                _prev(num, frame)
            elif _prev == _signal.SIG_DFL:
                _signal.signal(num, _signal.SIG_DFL)
                _signal.raise_signal(num)

        try:
            _signal.signal(signum, _handler)
        except (ValueError, OSError):
            pass  # not the main thread / unsupported signal


def _reset_for_tests():
    """Drop the process-global recorder (unit tests re-attach per tmpdir)."""
    global _recorder
    with _lock:
        if _recorder is not None:
            _recorder.close()
        _recorder = None
        _pre_attach.clear()
