"""Wire-level chaos plane — seeded fault injection in the RPC transport.

TPU-native analog of the reference's chaos tooling (python/ray/tests/
test_chaos.py + test_utils.py NodeKillerActor, and the gRPC-level fault
injection its network tests lean on): the coarse levers this repo already
had (SIGKILL a process tree, ``Cluster.remove_node``, GCS restart) can kill
a *component*, but none of them can produce the failure modes a real
network produces — a lost one-way frame, a duplicated chunk, a connection
reset mid-frame, a partition that heals. Every protocol above the frame
seam (acall request/response, ``send_nowait`` one-way frames, push/pull
chunk streams, cut-through relays, p2p direct mailbox, GCS calls) claims to
recover from those; this module makes the claims testable.

Design:

- A per-process :class:`FaultPlan` holds an ordered rule list plus a
  partition table. ``rpc.py`` consults it at the frame WRITE seam (client
  sends and server responses) and at connect time — one ``is None`` check
  per frame when no plan is installed, which is the entire production cost.
- Rules are **deterministic and seeded**: matching is by (peer, method,
  side) and firing is governed by ``after``/``every``/``times`` counters
  plus an optional probability ``p`` drawn from the plan's own
  ``random.Random(seed)``. The same seed over the same frame stream yields
  the same injection sequence (``plan.log`` records it for replay
  assertions).
- Faults: **drop** (frame vanishes, connection stays up — the silent-loss
  model), **delay** (frame written after a bounded jitter; delaying one
  frame past its successors IS reordering), **dup** (frame written twice —
  at-least-once delivery made concrete), **reset** (the first ``reset_at``
  bytes are written, then the transport is torn — a mid-frame tear,
  including mid-raw-frame), **partition** (sends/connects between two
  endpoints fail with ``ConnectionLost`` until healed; symmetric or
  asymmetric, pairwise or a node **membrane**), and **kill** (the process
  SIGKILLs ITSELF at the Nth matching frame — the crash-fault model; the
  dying side stamps a ``chaos_kill`` flight event first, and the mmap
  flight ring survives SIGKILL, so the kill point stays replayable).
- Install paths: config/env (``RAY_TPU_CHAOS_SEED``/``RAY_TPU_CHAOS_PLAN``,
  read at CoreWorker/Raylet boot so spawned workers inherit the plan), or
  at runtime via the ``chaos_set_plan`` RPC every raylet and worker serves
  (tests flip faults mid-workload; a raylet can fan a plan out to its
  registered workers).

Partition model: an endpoint is an address key (``host:port`` or a unix
socket path) as produced by :func:`rpc.addr_key`. Client sends know their
target address and an optional ``chaos_scope`` (the raylet stamps its own
address on the clients it owns, so "this node's outbound traffic" is
matchable); a **pair** rule blocks (src→dst) with ``*`` wildcards, and a
**membrane** blocks any link crossing an inside/outside boundary (the
in-process network tear ``Cluster.partition_node`` uses — node-local links
stay up, cross-membrane links drop). Partitions are enforced at clients
and connects only: the first blocked send also tears the live socket, so
the peer's half of the conversation dies with it, and worker processes get
their own plan pushed when a whole node is severed.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import random
import threading

from ray_tpu._private import flight_recorder
from ray_tpu._private.concurrency import any_thread

logger = logging.getLogger(__name__)

FAULT_KINDS = ("drop", "delay", "dup", "reset", "partition", "kill")

# Methods never injected: the chaos control plane itself must stay
# reachable (a plan that drops chaos_set_plan frames could never be
# cleared remotely).
_DEFAULT_EXCLUDE = frozenset({"chaos_set_plan"})


class _ChaosStats:
    """Plain-int injection counters (same pattern as rpc.WIRE): the seam
    runs on the IO loop, bare ``+=`` is race-free there; folded into the
    ``ray_tpu_chaos_injected_total`` instrument by the flush-time
    collector (self_metrics._collect_chaos_stats)."""

    __slots__ = (
        "injected", "drops", "delays", "dups", "resets", "partition_blocks",
        "kills",
    )

    def __init__(self):
        self.injected = 0
        self.drops = 0
        self.delays = 0
        self.dups = 0
        self.resets = 0
        self.partition_blocks = 0
        self.kills = 0


CHAOS_STATS = _ChaosStats()


class Action:
    """One injection decision, handed to the rpc seam to apply."""

    __slots__ = ("kind", "delay_s", "reset_at")

    def __init__(self, kind: str, delay_s: float = 0.0, reset_at: int = 8):
        self.kind = kind
        self.delay_s = delay_s
        self.reset_at = reset_at


class FaultRule:
    """One match-and-fire rule. Matching is structural (peer substring,
    method set, side); firing is counted (``after`` skipped matches, then
    every ``every``-th match fires, at most ``times`` times) and optionally
    thinned by probability ``p`` drawn from the plan's seeded RNG."""

    __slots__ = (
        "kind", "peer", "methods", "side", "p", "after", "every", "times",
        "delay_ms", "reset_at", "matched", "fired",
    )

    def __init__(self, spec: dict):
        kind = spec.get("kind")
        if kind not in ("drop", "delay", "dup", "reset", "kill"):
            raise ValueError(f"unknown fault kind {kind!r}")
        self.kind = kind
        self.peer = spec.get("peer")  # substring of client label OR addr key
        methods = spec.get("method")
        if methods is None:
            self.methods = None
        elif isinstance(methods, str):
            self.methods = frozenset((methods,))
        else:
            self.methods = frozenset(methods)
        self.side = spec.get("side")  # "send" | "resp" | None (both)
        self.p = float(spec.get("p", 1.0))
        self.after = int(spec.get("after", 0))
        self.every = max(1, int(spec.get("every", 1)))
        times = spec.get("times")
        self.times = None if times is None else int(times)
        lo, hi = spec.get("delay_ms", (5, 50)) or (5, 50)
        self.delay_ms = (float(lo), float(hi))
        self.reset_at = int(spec.get("reset_at", 8))
        self.matched = 0
        self.fired = 0

    def to_dict(self) -> dict:
        return {
            "kind": self.kind, "peer": self.peer,
            "method": sorted(self.methods) if self.methods else None,
            "side": self.side, "p": self.p, "after": self.after,
            "every": self.every, "times": self.times,
            "delay_ms": list(self.delay_ms), "reset_at": self.reset_at,
        }

    def matches(self, label: str, addr: str, method: str, side: str) -> bool:
        if self.times is not None and self.fired >= self.times:
            return False
        if self.side is not None and self.side != side:
            return False
        if self.methods is not None and method not in self.methods:
            return False
        if self.peer is not None and self.peer not in label and self.peer not in addr:
            return False
        return True


class _Membrane:
    __slots__ = ("inside", "local_inside")

    def __init__(self, inside, local_inside: bool):
        self.inside = frozenset(inside)
        self.local_inside = bool(local_inside)


class FaultPlan:
    """The active per-process fault schedule. All decision entry points run
    on the IO loop (the frame seam), so rule counters and the RNG need no
    lock; installation swaps the whole plan atomically (module global)."""

    def __init__(
        self,
        spec: dict | None = None,
        seed: int | None = None,
        allow_kill: bool = False,
    ):
        spec = spec or {}
        if seed is None:
            seed = int(spec.get("seed", 0))
        self.seed = seed
        # kill rules SIGKILL the INSTALLING process. The remote install
        # paths (chaos_set_plan RPC, env inheritance at worker boot) arm
        # them — they target the process that is meant to die. A direct
        # in-process install() refuses them so a driver/test process can't
        # SIGKILL itself (and everything an in-process cluster hosts) by
        # installing a plan written for its workers.
        self.allow_kill = bool(allow_kill)
        self.rng = random.Random(seed)
        self.rules: list[FaultRule] = []
        self.exclude = frozenset(spec.get("exclude", ())) | _DEFAULT_EXCLUDE
        # Deterministic injection record (kind:method:peer), for the
        # same-seed-same-sequence assertion and for debugging a cell.
        self.log: collections.deque = collections.deque(maxlen=1024)
        # Partition state. Pairs are directed (src_scope, dst_addr) with
        # "*" wildcards; membranes are inside/outside boundary sets.
        self._pairs: set[tuple] = set()
        self._membranes: dict[int, _Membrane] = {}
        self._next_membrane = 1
        self._mutate = threading.Lock()  # partition edits from user threads
        for rule in spec.get("rules", ()):
            if rule.get("kind") == "kill" and not self.allow_kill:
                raise ValueError(
                    "kill rules are refused on direct in-process install: "
                    "they SIGKILL THIS process. Push the plan into the "
                    "target process via the chaos_set_plan RPC / env "
                    "inheritance, or pass allow_kill=True if this process "
                    "really is the victim."
                )
            if rule.get("kind") == "partition":
                if "inside" in rule:
                    # Membrane form: sever every link crossing the
                    # inside/outside boundary (node tears).
                    self.add_membrane(
                        rule["inside"], bool(rule.get("local_inside", False))
                    )
                else:
                    self.add_partition(
                        rule.get("a", "*"), rule.get("b", "*"),
                        symmetric=bool(rule.get("symmetric", True)),
                    )
            else:
                self.rules.append(FaultRule(rule))

    # ---- partitions ----

    @any_thread
    def add_partition(self, a: str, b: str = "*", symmetric: bool = True):
        with self._mutate:
            self._pairs.add((a, b))
            if symmetric:
                self._pairs.add((b, a))

    @any_thread
    def heal_partition(self, a: str, b: str = "*", symmetric: bool = True):
        with self._mutate:
            self._pairs.discard((a, b))
            if symmetric:
                self._pairs.discard((b, a))

    @any_thread
    def add_membrane(self, inside, local_inside: bool = False) -> int:
        with self._mutate:
            mid = self._next_membrane
            self._next_membrane += 1
            self._membranes[mid] = _Membrane(inside, local_inside)
            return mid

    @any_thread
    def remove_membrane(self, mid: int):
        with self._mutate:
            self._membranes.pop(mid, None)

    @any_thread
    def heal_all(self):
        with self._mutate:
            self._pairs.clear()
            self._membranes.clear()

    @any_thread
    def has_partitions(self) -> bool:
        return bool(self._pairs or self._membranes)

    @any_thread
    def blocked(self, local_scope: str | None, remote: str) -> bool:
        """Is the (local endpoint -> remote address) link severed?
        ``local_scope`` is None for unscoped clients (driver/worker user
        clients), which membranes classify by their ``local_inside``
        default and pairs match only via the ``*`` wildcard."""
        if not self._pairs and not self._membranes:
            return False
        for m in self._membranes.values():
            li = (local_scope in m.inside) if local_scope is not None else m.local_inside
            if li != (remote in m.inside):
                return True
        for src, dst in self._pairs:
            if (src == "*" or src == local_scope) and (dst == "*" or dst == remote):
                return True
        return False

    # ---- the frame-seam decision (rpc.py calls this; IO loop only) ----

    def on_send(
        self, local_scope: str | None, label: str, addr: str, method: str,
        side: str = "send",
    ) -> Action | None:
        """Decide the fault (if any) for one outbound frame. First matching
        rule that fires wins; partition outranks rules (a severed link
        delivers nothing, not a delayed something). Partitions are enforced
        at CLIENT sends/connects only — a response-side hit here would be
        recorded but never applied (rpc._send_resp delivers it), so the
        check is skipped entirely for side="resp" to keep the injection
        log and counters truthful."""
        if method in self.exclude:
            return None
        if side != "resp" and self.blocked(local_scope, addr):
            self._record("partition", method, label)
            CHAOS_STATS.partition_blocks += 1
            return Action("partition")
        for rule in self.rules:
            if not rule.matches(label, addr, method, side):
                continue
            rule.matched += 1
            if rule.matched <= rule.after:
                continue
            if (rule.matched - rule.after) % rule.every != 0:
                continue
            if rule.p < 1.0 and self.rng.random() >= rule.p:
                continue
            rule.fired += 1
            self._record(rule.kind, method, label)
            if rule.kind == "drop":
                CHAOS_STATS.drops += 1
                return Action("drop")
            if rule.kind == "dup":
                CHAOS_STATS.dups += 1
                return Action("dup")
            if rule.kind == "reset":
                CHAOS_STATS.resets += 1
                return Action("reset", reset_at=rule.reset_at)
            if rule.kind == "kill":
                # Crash fault: the rpc seam SIGKILLs this process at this
                # frame. Stamp the dedicated chaos_kill flight event NOW —
                # the mmap ring survives SIGKILL, so the injection point
                # stays replayable from the node's flight dir postmortem.
                CHAOS_STATS.kills += 1
                flight_recorder.record("chaos_kill", f"{label[:24]}:{method}")
                return Action("kill")
            lo, hi = rule.delay_ms
            CHAOS_STATS.delays += 1
            return Action("delay", delay_s=(lo + (hi - lo) * self.rng.random()) / 1000.0)
        return None

    def check_connect(self, local_scope: str | None, label: str, addr: str) -> bool:
        """Connect-time partition gate (rpc._ensure_connected): True means
        the connect must fail fast with ConnectionLost — a partitioned peer
        is unroutable NOW, not after a 10s connect spin."""
        if not self.blocked(local_scope, addr):
            return False
        CHAOS_STATS.partition_blocks += 1
        self._record("partition", "connect", label)
        return True

    def _record(self, kind: str, method: str, label: str):
        CHAOS_STATS.injected += 1
        self.log.append(f"{kind}:{method}:{label}")
        flight_recorder.record("chaos_inject", f"{kind}:{label[:24]}:{method}")


# ---------------------------------------------------------------------------
# Process-global plan
# ---------------------------------------------------------------------------

_install_lock = threading.Lock()


def _publish(plan: FaultPlan | None):
    from ray_tpu._private import rpc

    rpc._CHAOS = plan


@any_thread
def active() -> FaultPlan | None:
    from ray_tpu._private import rpc

    return rpc._CHAOS


@any_thread
def install(
    spec: dict | FaultPlan | None,
    seed: int | None = None,
    allow_kill: bool = False,
) -> FaultPlan | None:
    """Install (or, with None, clear) the process fault plan. ``spec`` is
    the JSON-able plan grammar (see CHAOS.md) or a prebuilt FaultPlan.
    ``allow_kill`` arms ``kill`` rules (SIGKILL of THIS process); the
    remote install paths pass it, direct installs refuse by default."""
    with _install_lock:
        if spec is None:
            _publish(None)
            return None
        plan = (
            spec
            if isinstance(spec, FaultPlan)
            else FaultPlan(spec, seed=seed, allow_kill=allow_kill)
        )
        _publish(plan)
        return plan


@any_thread
def clear():
    install(None)


@any_thread
def ensure_plan() -> FaultPlan:
    """The active plan, installing an empty one if none is active (the
    partition helpers need a plan object to hang state on)."""
    with _install_lock:
        plan = active()
        if plan is None:
            plan = FaultPlan({})
            _publish(plan)
        return plan


@any_thread
def partition(a: str, b: str = "*", symmetric: bool = True) -> FaultPlan:
    """Sever the (a -> b) link (and b -> a when symmetric) until healed.
    Endpoints are rpc.addr_key strings or "*"."""
    plan = ensure_plan()
    plan.add_partition(a, b, symmetric=symmetric)
    return plan


@any_thread
def heal(a: str, b: str = "*", symmetric: bool = True):
    plan = active()
    if plan is not None:
        plan.heal_partition(a, b, symmetric=symmetric)


def maybe_install_from_env():
    """Boot-time env install (RAY_TPU_CHAOS_PLAN json + RAY_TPU_CHAOS_SEED):
    how spawned worker processes inherit the cluster's fault plan. A parse
    failure disables chaos loudly rather than running half a plan."""
    if active() is not None:
        return
    from ray_tpu._private.config import get_config

    # config.chaos_plan already folds in the RAY_TPU_CHAOS_PLAN env var
    # (apply_overrides) AND accepts _system_config={"chaos_plan": ...}.
    raw = get_config().chaos_plan or os.environ.get("RAY_TPU_CHAOS_PLAN")
    if not raw:
        return
    try:
        spec = json.loads(raw)
        if isinstance(spec, list):
            spec = {"rules": spec}
        seed_env = os.environ.get("RAY_TPU_CHAOS_SEED")
        # Env inheritance is a remote install path: a process booted under
        # a kill plan IS the intended victim.
        install(spec, seed=int(seed_env) if seed_env else None, allow_kill=True)
        logger.warning("chaos: installed fault plan from env (seed=%s)",
                       active().seed if active() else None)
    except Exception:
        logger.exception("chaos: RAY_TPU_CHAOS_PLAN is invalid; chaos disabled")
