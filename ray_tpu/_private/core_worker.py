"""CoreWorker — the per-process runtime.

TPU-native analog of the reference's CoreWorker
(src/ray/core_worker/core_worker.h:284) plus its Cython binding
(python/ray/_raylet.pyx:2625): lives in every driver and worker process and
implements

- task submission (core_worker.cc:1893 SubmitTask) through the local raylet
- actor creation via GCS + direct actor task transport
  (direct_actor_task_submitter.h:67) — actor calls go straight to the actor
  process over its own RPC server, the raylet is not involved after creation
- Put/Get/Wait over the two-tier object store: small objects in the owner's
  in-process store (memory_store.h:43), large objects in the node's shm arena
  (plasma_store_provider.h:88)
- ownership + distributed reference counting (reference_count.h:61, simplified
  borrower protocol: every materialised ObjectRef increfs its owner, task args
  are pinned for the task's lifetime)
- task retry + lineage reconstruction (task_manager.h:164,
  object_recovery_manager.h:41): specs of completed tasks are retained so a
  lost object can be rebuilt by re-executing its creating task
- the task execution loop for worker processes (core_worker.cc:2512), including
  the ordered actor scheduling queue (actor_scheduling_queue.h:40) and
  concurrency groups via thread pools.
"""

from __future__ import annotations

import asyncio
import collections
import contextvars
import hashlib
import logging
import os
import threading
import time
from concurrent.futures import Future as ConcurrentFuture
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

import cloudpickle

from ray_tpu._private import flight_recorder, self_metrics, serialization
from ray_tpu._private.concurrency import any_thread, blocking, loop_only
from ray_tpu._private.config import get_config
from ray_tpu._private.ids import ActorID, BoundedIdSet, JobID, ObjectID, TaskID, WorkerID
from ray_tpu._private.rpc import ConnectionLost, EventLoopThread, RpcClient, RpcError, RpcServer
from ray_tpu._private.store.object_store import StoreClient
from ray_tpu._private.task_spec import ACTOR_CREATION_TASK, ACTOR_TASK, NORMAL_TASK, TaskSpec
from ray_tpu.cross_language import CppFunctionInvoker
from ray_tpu.exceptions import (
    ActorDiedError,
    GetTimeoutError,
    ObjectLostError,
    OutOfMemoryError,
    OwnerDiedError,
    TaskCancelledError,
    TaskError,
    WorkerCrashedError,
)

logger = logging.getLogger(__name__)

# Current executing task: (TaskID, TaskSpec). A contextvar (not a
# threading.local) so async actor methods — which hop to the shared
# actor-async loop thread — keep their task attribution per asyncio Task.
_exec_ctx: contextvars.ContextVar = contextvars.ContextVar("ray_tpu_exec_ctx", default=None)

DRIVER = "driver"
WORKER = "worker"


def _maybe_jax_array(obj) -> bool:
    """True iff obj is a jax.Array — without importing jax for non-jax
    values (the module-name probe keeps cold paths jax-free)."""
    mod = type(obj).__module__
    if not (mod.startswith("jax") or mod.startswith("jaxlib")):
        return False
    try:
        import jax

        return isinstance(obj, jax.Array)
    except ImportError:
        return False


@dataclass
class PendingTask:
    spec: TaskSpec
    retries_left: int
    arg_refs: list = field(default_factory=list)
    # Cancellation state (reference: task_manager.cc MarkTaskCanceled):
    # a cancel-requested task is never retried, and completion payloads
    # arriving later are folded into TaskCancelledError.
    cancel_requested: bool = False
    # "resolving" = still owner-local (waiting on ObjectRef args);
    # "submitted" = handed to the raylet / lease transport / actor.
    phase: str = "resolving"
    # First completion claims the task (duplicate completion payloads are
    # routine: cancel races, lease failover double-delivery) so arg unpin /
    # borrowed decref run exactly once.
    done_claimed: bool = False
    # Task that submitted this one (the executing task's id when submitted
    # from inside a worker) — drives recursive cancellation.
    parent_task_id: str = ""
    # Lost-task sweep bookkeeping (raylet-path tasks only): server-side
    # spillback means a spec can die WITH a node and be held by nobody;
    # the owner sweeps alive raylets (locate_tasks) and resubmits specs
    # found nowhere twice in a row. via_lease tasks are excluded — the
    # lease manager owns their failover.
    via_lease: bool = False
    submitted_ts: float = 0.0
    sweep_misses: int = 0
    sweep_resubmits: int = 0


@dataclass
class OwnedObject:
    ref_count: int = 0
    pinned: int = 0  # pins from in-flight tasks that use this object as an arg
    in_plasma: bool = False
    location_hint: str | None = None
    # Serialization format when known ("x" = cross-language msgpack): the
    # native-routing gate for cpp tasks with ref args — only provably
    # native-decodable objects may ship to the C++ worker runtime.
    format: str | None = None
    # Refs nested inside this object's value (reference: nested-ref borrow
    # handoff, reference_count.h). The producer increfs each on our behalf;
    # we decref them when this object itself is freed.
    contained: list = field(default_factory=list)  # [(oid hex, owner addr)]
    # Device object (experimental/device_object/): the payload lives on the
    # HOLDER process's devices, only a descriptor is stored here.
    # {"addr": [h, p], "id": holder id} — freeing this object releases the
    # holder's device buffers through the ownership protocol.
    device: dict | None = None


class CoreWorker:
    def __init__(
        self,
        mode: str,
        gcs_address,
        raylet_address,
        arena_name: str,
        node_id: str,
        session_dir: str,
        job_id: JobID | None = None,
        worker_id: str | None = None,
        namespace: str = "",
        job_runtime_env: dict | None = None,
    ):
        self.mode = mode
        self.cfg = get_config()
        self.node_id = node_id
        self.session_dir = session_dir
        self.namespace = namespace
        # Job-level runtime env (ray.init(runtime_env=...)): merged under
        # every task/actor-level env at submit time (reference: job_config).
        self.job_runtime_env = dict(job_runtime_env or {})
        self.worker_id = worker_id or WorkerID.from_random().hex()
        _bt = os.environ.get("RAY_TPU_BOOT_TRACE")
        _t0 = time.monotonic()

        def _mark(label):
            if _bt:
                import sys as _sys

                print(
                    f"[cw-trace {os.getpid()}] {label} +{(time.monotonic() - _t0) * 1e3:.1f}ms",
                    file=_sys.stderr, flush=True,
                )

        self._io = EventLoopThread.get()
        _mark("io-loop")
        # Always-on observability plane: the crash-surviving event ring
        # (flight_recorder.py) plus the ray_tpu_* runtime instruments
        # (self_metrics.py) that flow through the /metrics KV path.
        flight_recorder.attach(session_dir, role=mode, ident=self.worker_id)
        self._metrics = self_metrics.instruments()
        # 1-in-N dispatch sampling counter (config.hop_sample_n): feeds the
        # dispatch-latency histogram and timeline flow spans in production
        # without full hop-timing cost.
        self._hop_sample_ctr = 0
        # task_done ring events are sampled 1-in-64: completion is implied
        # by the NEXT task_exec on this worker, and a ring that ends with a
        # task_exec (no later exec) is precisely the "died mid-task"
        # postmortem signal — so per-task done events bought latency on the
        # exec critical path without adding information. task_ship is
        # sampled the same way (first ship after init always records): the
        # driver ring's unique value is driver-death postmortems — for the
        # common worker-death case the live driver's pending_tasks + task
        # events already name every in-flight task exactly. task_exec and
        # task_fail stay per-event.
        self._done_event_ctr = 0
        self._ship_event_ctr = 0

        # Chaos plane: spawned workers inherit the cluster's fault plan
        # through the environment (chaos_set_plan flips it at runtime).
        from ray_tpu._private import chaos

        chaos.maybe_install_from_env()

        self.gcs = RpcClient(tuple(gcs_address), label="gcs")
        self.raylet = RpcClient(tuple(raylet_address), label="raylet")
        self.store = StoreClient(arena_name, self.raylet)
        _mark("store-attach")

        if job_id is None:
            job_hex = self.gcs.call("next_job_id", timeout=15)["job_id"]
            job_id = JobID.from_hex(job_hex)
        self.job_id = job_id
        self._default_task_id = TaskID.for_driver(job_id)
        # Per-execution-thread task context: threaded actors
        # (max_concurrency > 1) run execute_task concurrently, so the current
        # spec/id must not be shared process state.
        # Process-wide registry of currently-executing tasks, insertion
        # ordered — the fallback for threads the user spawned inside a task
        # (contextvars don't cross thread creation) is the most recently
        # started still-running task.
        self._active_exec: dict[int, tuple] = {}
        self._active_exec_lock = threading.Lock()
        self._active_exec_seq = 0
        self._task_counter = 0

        # Own RPC server (the "core worker service").
        self.server = RpcServer(f"core-{self.worker_id[:8]}")
        self.server.register_all(self)
        _mark("register_all")
        self.server.start("127.0.0.1", 0)
        self.address = self.server.address
        _mark("server-start")

        # Object bookkeeping (all guarded by _lock; events live on the IO loop).
        self._lock = threading.Lock()
        self._submit_lock = threading.Lock()
        self._submit_buf: list = []
        self._submit_flush_scheduled = False
        # Streaming generator returns (reference: StreamingObjectRefGenerator,
        # _raylet.pyx:227): task_id -> {"items": {index: oid}, "count": int|None,
        # "error": bytes|None, "cond": threading.Condition}
        self._streams: dict[str, dict] = {}
        self.in_process_store: dict[str, dict] = {}  # oid -> {data | value}
        self.owned: dict[str, OwnedObject] = {}
        self._object_events: dict[str, asyncio.Event] = {}
        # Synchronous get() waiters: oid -> [threading.Event]. The warm-path
        # result wake — a completion handler on the IO loop sets the event
        # and the blocked user thread runs, ONE handoff — replacing the old
        # run_coroutine_threadsafe + asyncio.Event + cf.Future chain (three
        # serial loop ticks + two thread handoffs per sync call).
        self._sync_waiters: dict[str, list] = {}
        # Hop-level dispatch records (config.hop_timing): per-task stage
        # timestamp dicts, merged owner+worker sides at completion. Ring
        # buffer; microbench --hop-budget and util/tracing read it.
        self._hop_log: collections.deque = collections.deque(maxlen=4096)
        self._hop_by_task: dict[str, dict] = {}
        self._owner_client_cache: dict[tuple, RpcClient] = {}
        # Compiled-graph channel plane (experimental/channel/): reader gates
        # for every channel this process consumes; the rpc_channel_* handlers
        # below dispatch doorbells / side-channel chunks / poison into it.
        from ray_tpu.experimental.channel.channel import ChannelRegistry

        self.channels = ChannelRegistry()
        # Direct p2p mailbox (util/collective/p2p.py): landing zone for
        # eager-pushed channel payloads (descriptor slots resolve from it
        # without a pull round trip) — rpc_p2p_data deposits into it.
        from ray_tpu.util.collective.p2p import ChunkStreams, P2PInbox, RelayTable

        self.p2p_inbox = P2PInbox()
        # Tree-collective planes: relay sessions forwarding broadcast
        # chunks down the binomial tree (cut-through), and reduce partial
        # streams combined chunk-at-a-time at each hop.
        self.p2p_relays = RelayTable()
        self.p2p_streams = ChunkStreams()
        self.pending_tasks: dict[str, PendingTask] = {}
        # Tombstones for cancelled tasks that may not have reached this
        # process yet (cancel racing submission); checked at execution
        # entry. Bounded FIFO — cancellation is rare.
        self._cancelled_tasks = BoundedIdSet()
        # Completion-payload ids already processed (task_done/tasks_done are
        # delivered at-least-once: resends after a connection failure can
        # duplicate a payload that DID arrive). Without this filter a
        # duplicate ERROR payload double-decrements the retry budget in
        # _handle_task_done's retry branch. Sized to cover the resend
        # horizon (worker _flush_done retries for up to ~60s) at multi-k/s
        # completion rates: 64k ids ≈ a few MB, and a filter miss degrades
        # to the pre-filter behavior (a wasted retry), never corrupts.
        self._seen_completions = BoundedIdSet(65536)
        self.lineage: collections.OrderedDict[str, TaskSpec] = collections.OrderedDict()
        self._borrowed_decref_queue: list = []

        # Function table cache (reference: _private/function_manager.py).
        self._function_cache: dict[str, object] = {}
        self._exported_functions: set[str] = set()
        import weakref

        self._fn_key_by_obj: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()

        # Direct task transport (lease_manager.py), created on first
        # eligible submit.
        self._lease_mgr = None
        # Lost-task sweep (raylet-path orphan recovery), started on first
        # non-lease submit.
        self._lost_sweep_task = None
        self._sweep_clients: dict[tuple, RpcClient] = {}
        # Last (job, task name) announced to the log pipeline (in-band
        # attribution).
        self._log_attr_name: tuple | None = None

        # Actor-call transport state.
        self._actor_clients: dict[str, RpcClient] = {}
        self._actor_addrs: dict[str, tuple] = {}
        self._actor_seq: dict[str, int] = collections.defaultdict(int)
        self._actor_pending: dict[str, set] = collections.defaultdict(set)
        self._actor_submit_locks: dict[str, asyncio.Lock] = collections.defaultdict(asyncio.Lock)

        # Execution state (worker mode).
        self._executor = ThreadPoolExecutor(max_workers=1, thread_name_prefix="task-exec")
        # Worker processes execute tasks on the process MAIN thread
        # (worker_main.main() swaps _executor for a main-thread drain loop
        # and records its ident here). Running on the main thread is what
        # lets a non-force cancel interrupt C-blocked calls like
        # time.sleep: CPython only runs signal handlers on the main thread,
        # and a raising handler aborts the blocking call (PEP 475). The
        # reference executes tasks on the worker main thread and interrupts
        # with KeyboardInterrupt for the same reason (core_worker.cc
        # CancelTask → PyErr_SetInterrupt path in _raylet.pyx).
        self._main_thread_ident: int | None = None
        self._main_task_id: str | None = None  # task now running on main thread
        self._main_cancel_target: str | None = None  # read by SIGUSR2 handler
        self._actor_instance = None
        self._actor_id: str | None = None
        self._actor_creation_spec: TaskSpec | None = None
        # Device object plane (experimental/device_object/): tensor_transport
        # declared by this actor's class (returns of jax.Arrays stay
        # device-resident); the manager is created on first device put/return.
        self._tensor_transport: str = ""
        self._device_objects = None
        # Short-connect clients for devobj_pull: a dead holder must surface
        # as DeviceObjectLostError in seconds, not after the default
        # connect budget (same rationale as _actor_client's 2s timeout).
        self._devobj_clients: dict[tuple, RpcClient] = {}
        self._actor_exec_queue: asyncio.Queue | None = None
        self._actor_concurrency_pool: ThreadPoolExecutor | None = None
        self._actor_async_loop: asyncio.AbstractEventLoop | None = None
        self._shutdown = False

        # Task-event buffer (reference: task_event_buffer.h:41 — periodically
        # flushed to the GCS task manager; powers `ray timeline` / state API).
        self._task_events: list[dict] = []
        self._task_events_lock = threading.Lock()
        self._task_events_flusher: threading.Thread | None = None

        # Log pipeline: drivers subscribe to worker stdout/stderr lines
        # published by each raylet's LogMonitor (reference: print_logs in
        # _private/worker.py; disable with RAY_TPU_LOG_TO_DRIVER=0).
        self.log_to_driver = (
            mode == DRIVER and os.environ.get("RAY_TPU_LOG_TO_DRIVER", "1") != "0"
        )
        if self.log_to_driver:
            try:
                self.gcs.call(
                    "subscribe", {"channel": "worker_logs", "address": list(self.address)}
                )
                # Periodic re-subscribe: subscription state is not persisted
                # by the GCS, so a restarted GCS regains subscribers within
                # one period (subscribe is idempotent per address).
                threading.Thread(
                    target=self._resubscribe_loop, name="log-resubscribe", daemon=True
                ).start()
            except Exception:
                self.log_to_driver = False

    def _resubscribe_loop(self):
        while not self._shutdown:
            time.sleep(10.0)
            if self._shutdown:
                return
            try:
                self.gcs.call(
                    "subscribe", {"channel": "worker_logs", "address": list(self.address)}
                )
            except Exception:
                pass

    def _fallback_ctx(self) -> tuple | None:
        with self._active_exec_lock:
            if not self._active_exec:
                return None
            return next(reversed(self._active_exec.values()))

    @property
    def current_task_id(self) -> TaskID:
        ctx = _exec_ctx.get() or self._fallback_ctx()
        return ctx[0] if ctx is not None else self._default_task_id

    @property
    def current_task_spec(self) -> TaskSpec | None:
        ctx = _exec_ctx.get() or self._fallback_ctx()
        return ctx[1] if ctx is not None else None

    # ==================================================================
    # Task events (reference: src/ray/core_worker/task_event_buffer.h:41)
    # ==================================================================

    def record_task_event(self, spec: TaskSpec, state: str, **extra):
        """Buffer one task state transition; flushed in batches to GCS."""
        if not self.cfg.task_events_enabled:
            return
        event = {
            "task_id": spec.task_id,
            "name": spec.name,
            "job_id": spec.job_id,
            "task_type": spec.task_type,
            "actor_id": spec.actor_id or "",
            "state": state,
            "ts": time.time(),
            "worker_id": self.worker_id,
            "node_id": self.node_id,
        }
        if spec.trace_ctx:
            event["trace_ctx"] = spec.trace_ctx
        event.update(extra)
        with self._task_events_lock:
            self._task_events.append(event)
            if self._task_events_flusher is None:
                self._task_events_flusher = threading.Thread(
                    target=self._task_events_flush_loop,
                    name="task-events-flush",
                    daemon=True,
                )
                self._task_events_flusher.start()

    def _task_events_flush_loop(self):
        interval = self.cfg.task_events_flush_interval_s
        while not self._shutdown:
            time.sleep(interval)
            self.flush_task_events()

    def flush_task_events(self):
        with self._task_events_lock:
            batch, self._task_events = self._task_events, []
        if not batch:
            return
        try:
            self.gcs.call("record_task_events", {"events": batch})
        except Exception:
            logger.debug("task-event flush failed", exc_info=True)

    # ==================================================================
    # Submission-side API
    # ==================================================================

    def _next_task_id(self) -> TaskID:
        self._task_counter += 1
        return TaskID.for_task(ActorID(self.current_task_id.binary()[:16]))

    def _hop_stamp_start(self) -> dict:
        """Initial hop-stamp dict for a submission: every task under full
        hop timing, 1-in-``hop_sample_n`` otherwise (always-on production
        sampling — makes the PR 2 hop budget a live metric instead of an
        opt-in microbench artifact). Empty dict = unstamped."""
        if self.cfg.hop_timing:
            return {"submit": time.monotonic()}
        n = self.cfg.hop_sample_n
        if n > 0:
            self._hop_sample_ctr += 1
            if self._hop_sample_ctr >= n:
                self._hop_sample_ctr = 0
                return {"submit": time.monotonic()}
        return {}

    def _export_function(self, func) -> str:
        # Hot path: @ray_tpu.remote functions are submitted thousands of
        # times — cache the pickle/hash per function object (weak so
        # dynamically-created functions don't leak).
        try:
            cached = self._fn_key_by_obj.get(func)
        except TypeError:  # unhashable/unweakrefable callables
            cached = None
        if cached is not None:
            return cached
        pickled = cloudpickle.dumps(func)
        key = "fn:" + hashlib.sha1(pickled).hexdigest()
        if key not in self._exported_functions:
            # Bounded + retried (kv_put with overwrite=False is idempotent):
            # a silently lost export frame must not hang .remote() forever.
            self.gcs.call(
                "kv_put", {"key": key, "value": pickled, "overwrite": False},
                timeout=15,
            )
            self._exported_functions.add(key)
            self._function_cache[key] = func
        try:
            self._fn_key_by_obj[func] = key
        except TypeError:
            pass
        return key

    def _prepare_args(self, args: tuple, kwargs: dict) -> tuple[list, list]:
        """Serialize positional+keyword args into wire form; returns
        (wire_args, referenced_refs). kwargs ride as a trailing marker."""
        from ray_tpu.object_ref import ObjectRef

        wire = []
        refs = []
        flat = list(args) + [("__kwargs__", kwargs)] if kwargs else list(args)
        for arg in flat:
            if isinstance(arg, ObjectRef):
                refs.append(arg)
                wire.append(["r", arg.hex(), list(arg.owner_addr or self.address)])
            else:
                ser = serialization.serialize(arg)
                refs.extend(ser.contained_refs)
                data = ser.to_bytes()
                if len(data) > self.cfg.max_direct_call_object_size:
                    ref = self.put_serialized(ser)
                    refs.append(ref)
                    wire.append(["r", ref.hex(), list(self.address)])
                else:
                    wire.append(["v", data])
        return wire, refs

    def submit_task(self, func, args=(), kwargs=None, **opts):
        """Submit a normal task; returns list[ObjectRef]."""
        from ray_tpu.object_ref import ObjectRef

        kwargs = kwargs or {}
        task_id = self._next_task_id()
        num_returns = opts.get("num_returns", 1)
        # Cross-language tasks: args wrapped as format-"x" objects so the
        # native worker runtime (cpp/ray_tpu_worker.cc) decodes them
        # without Python; the Python ctypes path decodes them identically.
        is_cpp = isinstance(func, CppFunctionInvoker)
        if is_cpp:
            if kwargs:
                raise ValueError(
                    "cpp_function tasks take positional args only (they cross "
                    "the C ABI as a msgpack array)"
                )
            import msgpack

            from ray_tpu._private.serialization import XLangBytes
            from ray_tpu.object_ref import ObjectRef as _Ref

            args = tuple(
                a if isinstance(a, _Ref) else XLangBytes(msgpack.packb(a, use_bin_type=True))
                for a in args
            )
        wire_args, arg_refs = self._prepare_args(args, kwargs)
        # Native routing when every arg is native-decodable: inline "v"
        # entries always are (wrapped as format-"x" above); ObjectRef args
        # qualify when this owner can PROVE the object is format "x" —
        # the C++ worker fetches those itself (local shm zero-copy, or
        # owner get_inline / raylet store_get over the wire). Pickle-format
        # refs and multi-return stay on the Python ctypes path — identical
        # results, different hosting runtime. Deciding AFTER _prepare_args
        # makes the check exact (the spill threshold applies to the framed
        # object, not the raw payload).
        def _native_arg(w) -> bool:
            if w[0] == "v":
                return True
            return self._known_xlang_object(w[1])

        language = (
            "cpp"
            if is_cpp and num_returns == 1 and all(_native_arg(w) for w in wire_args)
            else "py"
        )
        spec = TaskSpec(
            task_id=task_id.hex(),
            job_id=self.job_id.hex(),
            name=opts.get("name") or getattr(func, "__name__", "task"),
            task_type=NORMAL_TASK,
            language=language,
            function_key=(
                f"cpp!{func.library_path}!{func.symbol}"
                if language == "cpp"
                else self._export_function(func)
            ),
            args=wire_args,
            num_returns=num_returns,
            resources=opts.get("resources") or {"CPU": 1},
            max_retries=opts.get("max_retries", self.cfg.default_max_retries),
            retry_exceptions=bool(opts.get("retry_exceptions", False)),
            owner_addr=list(self.address),
            owner_worker_id=self.worker_id,
            placement_group_id=opts.get("placement_group_id", ""),
            placement_group_bundle_index=opts.get("placement_group_bundle_index", -1),
            scheduling_strategy=opts.get("scheduling_strategy", "DEFAULT"),
            runtime_env=self._merged_runtime_env(opts.get("runtime_env")),
            trace_ctx=self._trace_ctx(),
            hop_ts=self._hop_stamp_start(),
        )
        if spec.is_streaming():
            with self._lock:
                # Bound the registry like lineage: prune oldest COMPLETED
                # streams (never-consumed generators would otherwise leak
                # their state forever in a long-lived driver).
                if len(self._streams) > 1000:
                    now = time.monotonic()
                    for tid in [
                        t for t, s in self._streams.items()
                        if s["count"] is not None and now - s["created"] > 600.0
                    ][: len(self._streams) - 1000]:
                        self._drop_stream_locked(tid)
                self._streams[spec.task_id] = {
                    "items": {}, "count": None, "error": None,
                    "created": time.monotonic(),
                    "cond": threading.Condition(),
                }
        self._register_pending(spec, arg_refs)
        self.record_task_event(spec, "PENDING_ARGS_AVAIL")
        self._submit_when_ready(spec, arg_refs)
        if spec.is_streaming():
            from ray_tpu.object_ref import ObjectRefGenerator

            return ObjectRefGenerator(self, spec.task_id)
        return [
            ObjectRef(ObjectID.for_return(task_id, i), self.address)
            for i in range(num_returns)
        ]

    @staticmethod
    def _trace_ctx() -> dict:
        from ray_tpu.util import tracing

        # Chain spans when tracing is enabled locally OR when the currently
        # executing task arrived with a span (a worker spawned before the
        # cluster-wide flag propagated must still not break its parent's
        # trace).
        if tracing.tracing_enabled() or tracing.get_current_span_context() is not None:
            return tracing.child_span_context()
        return {}

    def _merged_runtime_env(self, task_env: dict | None) -> dict:
        """Task/actor env over the job-level env; env_vars dicts merge."""
        if not self.job_runtime_env:
            merged = dict(task_env or {})
        elif not task_env:
            merged = dict(self.job_runtime_env)
        else:
            merged = dict(self.job_runtime_env)
            for key, value in task_env.items():
                if key == "env_vars" and isinstance(merged.get("env_vars"), dict):
                    merged["env_vars"] = {**merged["env_vars"], **(value or {})}
                else:
                    merged[key] = value
        from ray_tpu._private import runtime_env_plugins
        from ray_tpu.runtime_env import UNSUPPORTED_FIELDS

        # A registered plugin makes its field supported (reference:
        # RuntimeEnvPlugin seam — pip/conda/container are themselves
        # plugins there).
        unsupported = (set(merged) & UNSUPPORTED_FIELDS) - runtime_env_plugins.plugin_fields()
        if unsupported:
            # Fail at submission, not in a crash-looping worker: provisioning
            # packages needs network access this environment doesn't have.
            raise ValueError(
                f"runtime_env fields {sorted(unsupported)} require package "
                "installation, which is not supported; pre-install "
                "dependencies on the node image instead (or register a "
                "runtime-env plugin that provisions them)"
            )
        runtime_env_plugins.validate_with_plugins(merged)
        merged = runtime_env_plugins.attach_plugin_classes(merged)
        # Validate paths here too — a worker that dies in env setup before
        # registering would otherwise crash-loop while the task hangs.
        import os as _os

        wd = merged.get("working_dir")
        if wd and not _os.path.isdir(wd):
            raise ValueError(f"runtime_env working_dir {wd!r} is not a directory")
        for p in merged.get("py_modules") or []:
            if not _os.path.exists(p):
                raise ValueError(f"runtime_env py_modules path {p!r} does not exist")
        return merged

    def _submit_when_ready(self, spec: TaskSpec, arg_refs: list):
        """Submitter-side dependency resolution (reference:
        dependency_resolver.h:29 LocalDependencyResolver): hold the task until
        every ObjectRef argument is available, so leased workers never block
        on unproduced inputs. Owned refs wait on completion events; borrowed
        refs poll the owner."""
        unready = [ref for ref in arg_refs if not self._arg_available(ref)]
        if not unready:
            # Fire-and-forget: the ObjectRef already exists and results flow
            # back through completion events — blocking on the raylet's ack
            # here would serialize every submission on an RPC round-trip
            # (the reference's SubmitTask is asynchronous for the same
            # reason, core_worker.cc:1893). Errors fail the task instead.
            # Bursts coalesce into ONE submit_tasks RPC per IO-loop tick
            # (the reference pipelines leases similarly) — per-task RPCs were
            # the microbenchmark's dominant cost at 100-in-flight.
            self._enqueue_submit(spec)
            return

        async def _wait_and_submit():
            # Runs ON the IO loop: only async RPC here — a blocking .call()
            # would deadlock every socket in the process.
            try:
                for ref in unready:
                    oid_hex = ref.hex()
                    if self._is_own(ref):
                        await self._wait_event(oid_hex, None)
                    else:
                        while not await self._arg_available_async(ref):
                            await asyncio.sleep(0.02)
                with self._lock:
                    p = self.pending_tasks.get(spec.task_id)
                    # A missing entry means the task was already failed out
                    # of pending_tasks — for a not-yet-submitted task the
                    # only path that does that is cancel. Treating it as
                    # "not cancelled" would submit (and execute) a task
                    # whose get() already raised TaskCancelledError.
                    cancelled = p is None or p.cancel_requested
                if cancelled:
                    self._fail_task(
                        spec.task_id,
                        TaskCancelledError(
                            f"task {spec.name} ({spec.task_id[:8]}) was cancelled "
                            "before submission"
                        ),
                    )
                    return
                self._enqueue_submit(spec)
            except Exception as e:
                logger.exception("deferred submit of %s failed", spec.task_id[:8])
                self._fail_task(spec.task_id, WorkerCrashedError(f"submit failed: {e!r}"))

        self._io.spawn(_wait_and_submit())

    def _lease_eligible(self, spec: TaskSpec) -> bool:
        """Normal tasks with default placement ride the direct lease
        transport (lease_manager.py); everything placement-sensitive (PGs,
        node affinity, SPREAD) and streaming generators keep the classic
        raylet submit path."""
        return (
            self.cfg.direct_task_leases
            and spec.task_type == NORMAL_TASK
            and spec.language == "py"  # cpp tasks route to native workers
            and not spec.is_streaming()
            and (spec.scheduling_strategy or "DEFAULT") == "DEFAULT"
            and not spec.placement_group_id
        )

    def _get_lease_manager(self):
        lm = self._lease_mgr
        if lm is None:
            from ray_tpu._private.lease_manager import LeaseManager

            with self._lock:
                if self._lease_mgr is None:
                    self._lease_mgr = LeaseManager(self)
                lm = self._lease_mgr
        return lm

    def _enqueue_submit(self, spec: TaskSpec) -> None:
        with self._lock:
            p = self.pending_tasks.get(spec.task_id)
            if p is None or p.cancel_requested:
                # Cancelled between registration and submission: the
                # resolving-phase cancel branch already failed the task
                # (get() raises TaskCancelledError) — shipping it now would
                # execute it anyway, unreachable by any further cancel.
                # Checked under the same lock that flips phase so the
                # cancel driver sees either "resolving" (we skip here) or
                # "submitted" (it recalls from the transport).
                return
            p.phase = "submitted"
            p.submitted_ts = time.monotonic()
            p.via_lease = self._lease_eligible(spec)
        self._ship_event_ctr += 1
        if self._ship_event_ctr & 63 == 1:  # records at 1, 65, 129, ...
            flight_recorder.record(
                "task_ship", f"{spec.name}:{spec.task_id[:8]}:n={self._ship_event_ctr}"
            )
        if p.via_lease:
            self._get_lease_manager().submit(spec)
            return
        self._ensure_lost_task_sweeper()
        with self._submit_lock:
            self._submit_buf.append(spec)
            if self._submit_flush_scheduled:
                return
            self._submit_flush_scheduled = True
        self._io.spawn(self._flush_submits())

    # ---- lost-task sweep (raylet-path orphan recovery) -------------------
    #
    # Server-side spillback forwards a spec raylet-to-raylet and forgets
    # it; a node that dies holding the spec leaves the owner waiting on
    # its returns forever (no raylet will ever report task_done /
    # task_failed for it). The reference avoids this shape by owner-side
    # spillback replies (direct_task_transport.cc) — our lease path has
    # the same owner-owned failover, but SPREAD/affinity/PG/streaming
    # tasks ride the classic raylet queue. This sweep is their safety
    # net: aged submitted tasks are located across alive raylets
    # (locate_tasks) and resubmitted when found nowhere twice in a row.

    def _ensure_lost_task_sweeper(self):
        # Under the lock: submit_task runs on user threads, and two racing
        # spawns would double the sweep cadence — a single transient
        # "not found" could then reach the two-miss confirm in one window.
        with self._lock:
            if self._lost_sweep_task is None and not self._shutdown:
                self._lost_sweep_task = self._io.spawn(self._lost_task_sweep_loop())

    async def _lost_task_sweep_loop(self):
        interval = getattr(self.cfg, "lost_task_sweep_interval_s", 15.0)
        while not self._shutdown:
            await asyncio.sleep(interval)
            try:
                await self._sweep_lost_tasks()
            except Exception:
                logger.debug("lost-task sweep iteration failed", exc_info=True)

    async def _sweep_lost_tasks(self):
        now = time.monotonic()
        with self._lock:
            cands = [
                p
                for p in self.pending_tasks.values()
                if p.phase == "submitted"
                and not p.via_lease
                and not p.cancel_requested
                and p.spec.task_type == NORMAL_TASK
                and now - p.submitted_ts > getattr(self.cfg, "lost_task_age_s", 30.0)
            ]
        if not cands:
            return
        resp = await self.gcs.acall("get_nodes", {}, timeout=10)
        raylets = [
            tuple(info["address"])
            for info in resp.get("nodes", {}).values()
            if info.get("state") == "ALIVE" and info.get("address")
        ]
        ids = [p.spec.task_id for p in cands]
        found: set = set()
        for addr in raylets:
            client = self._sweep_clients.get(addr)
            if client is None:
                client = self._sweep_clients[addr] = RpcClient(
                    addr, label="sweep-raylet"
                )
            try:
                r = await client.acall("locate_tasks", {"task_ids": ids}, timeout=5)
                found.update(r.get("found", []))
            except Exception:
                # Unreachable raylet: absence is unprovable this round —
                # treat everything as found rather than double-execute.
                found.update(ids)
                self._sweep_clients.pop(addr, None)
                client.close()
                break
        for p in cands:
            tid = p.spec.task_id
            # Re-verify under the lock: the task may have COMPLETED during
            # the get_nodes/locate awaits above (done pops it from
            # pending_tasks; locate then reports it nowhere) — resubmitting
            # a finished task would re-run its side effects.
            with self._lock:
                live = self.pending_tasks.get(tid)
            if live is not p or p.phase != "submitted":
                continue
            if tid in found or p.cancel_requested:
                p.sweep_misses = 0
                continue
            p.sweep_misses += 1
            if p.sweep_misses < 2:
                continue  # could be mid-spillback; confirm next sweep
            p.sweep_misses = 0
            if p.sweep_resubmits >= 5:
                from ray_tpu.exceptions import WorkerCrashedError

                self._fail_task(
                    tid,
                    WorkerCrashedError(
                        f"task {p.spec.name} ({tid[:8]}) was lost repeatedly "
                        "(no alive raylet holds it after resubmission)"
                    ),
                )
                continue
            p.sweep_resubmits += 1
            logger.warning(
                "task %s (%s) held by no alive raylet; resubmitting (%d/5)",
                tid[:8], p.spec.name, p.sweep_resubmits,
            )
            self._reset_stream_for_retry(tid)
            try:
                await self.raylet.acall("submit_task", {"spec": p.spec.to_wire()})
            except Exception:
                logger.warning("lost-task resubmit of %s failed", tid[:8])

    async def _flush_submits(self) -> None:
        await asyncio.sleep(0)  # let the submitting thread's burst accumulate
        with self._submit_lock:
            batch, self._submit_buf = self._submit_buf, []
            self._submit_flush_scheduled = False
        if not batch:
            return
        if self.cfg.hop_timing:
            now = time.monotonic()
            for s in batch:
                if s.hop_ts:
                    s.hop_ts["ship"] = now
        try:
            if len(batch) == 1:
                await self.raylet.acall("submit_task", {"spec": batch[0].to_wire()})
            else:
                resp = await self.raylet.acall(
                    "submit_tasks", {"specs": [s.to_wire() for s in batch]}
                )
                # Per-spec failures: the rest of the batch is queued and
                # runs; only the reported specs actually failed.
                for f in resp.get("failed") or []:
                    self._fail_task(
                        f["task_id"], WorkerCrashedError(f"submit failed: {f['error']}")
                    )
        except Exception as e:
            # Transport-level failure (after the RPC client's own retries):
            # unknown which specs the raylet saw; fail all for visibility.
            logger.exception("batched submit of %d tasks failed", len(batch))
            for s in batch:
                self._fail_task(s.task_id, WorkerCrashedError(f"submit failed: {e!r}"))

    async def _arg_available_async(self, ref) -> bool:
        """Non-blocking (IO-loop-safe) version of _arg_available for
        borrowed refs."""
        oid_hex = ref.hex()
        with self._lock:
            if oid_hex in self.in_process_store:
                return True
        try:
            resp = await self.raylet.acall("store_contains", {"object_id": oid_hex})
            if resp.get("found"):
                return True
        except Exception:
            pass
        try:
            client = self._owner_client(tuple(ref.owner_addr))
            resp = await client.acall("get_inline", {"object_id": oid_hex, "wait": False}, timeout=2)
            return resp.get("kind") in ("inline", "plasma")
        except Exception:
            return False

    def _is_own(self, ref) -> bool:
        return ref.owner_addr is None or tuple(ref.owner_addr) == tuple(self.address)

    def _arg_available(self, ref) -> bool:
        oid_hex = ref.hex()
        with self._lock:
            if oid_hex in self.in_process_store:
                return True
            if self._is_own(ref):
                task_id = oid_hex[: TaskID.SIZE * 2]
                if task_id in self.pending_tasks:
                    return False
                obj = self.owned.get(oid_hex)
                return obj is not None and (obj.in_plasma or oid_hex in self.in_process_store)
        # Borrowed: only cheap local checks on the submit path — a remote
        # owner probe here would block .remote() for seconds when the owner
        # is slow; the deferred async waiter handles the remote case.
        return self.store.contains(oid_hex)

    def _owner_client(self, addr: tuple) -> RpcClient:
        """Cached connection to another worker/driver (owner of a borrowed
        ref). One connection per peer, reused across gets/probes/decrefs."""
        with self._lock:
            client = self._owner_client_cache.get(addr)
            if client is None:
                client = RpcClient(addr, label=f"owner-{addr}")
                self._owner_client_cache[addr] = client
            return client

    def _register_pending(self, spec: TaskSpec, arg_refs: list):
        ctx = _exec_ctx.get()
        parent = ctx[1].task_id if ctx is not None else ""
        with self._lock:
            self.pending_tasks[spec.task_id] = PendingTask(
                spec=spec,
                retries_left=spec.max_retries,
                arg_refs=list(arg_refs),
                parent_task_id=parent,
            )
            for oid in spec.return_object_ids():
                self.owned.setdefault(oid, OwnedObject())
                self._ensure_event(oid)
        for ref in arg_refs:
            self._pin_arg(ref)

    def _pin_arg(self, ref):
        if ref.owner_addr is None or tuple(ref.owner_addr) == tuple(self.address):
            with self._lock:
                obj = self.owned.setdefault(ref.hex(), OwnedObject())
                obj.pinned += 1
        else:
            self._push_to_owner(ref, "incref")

    def _unpin_args(self, arg_refs):
        for ref in arg_refs:
            if ref.owner_addr is None or tuple(ref.owner_addr) == tuple(self.address):
                with self._lock:
                    obj = self.owned.get(ref.hex())
                    if obj is not None:
                        obj.pinned = max(0, obj.pinned - 1)
                        self._maybe_free_locked(ref.hex(), obj)
            else:
                self._push_to_owner(ref, "decref")

    def _push_to_owner(self, ref, method: str):
        async def _push():
            try:
                client = self._owner_client(tuple(ref.owner_addr))
                await client.apush(method, {"object_id": ref.hex()})
            except Exception:
                pass

        self._io.spawn(_push())

    # ---- puts ----

    def put(self, value, tensor_transport: str | None = None) -> "object":
        if tensor_transport:
            return self.put_device(value, tensor_transport)
        ser = serialization.serialize(value)
        return self.put_serialized(ser)

    # ---- device object plane (experimental/device_object/) ----

    def _device_manager(self):
        mgr = self._device_objects
        if mgr is None:
            from ray_tpu.experimental.device_object.manager import DeviceObjectManager

            with self._lock:
                if self._device_objects is None:
                    self._device_objects = DeviceObjectManager(self)
                mgr = self._device_objects
        return mgr

    def _holder_identity(self) -> tuple[str, str]:
        if self._actor_id:
            return self._actor_id, "actor"
        return self.worker_id, "driver" if self.mode == DRIVER else "worker"

    def put_device(self, value, transport: str):
        """put() with tensor_transport=: the jax.Array stays resident on
        this process's devices; only a small descriptor enters the store.
        The returned ObjectRef is first-class (refcounted/waitable/passable)
        and resolves out of band (same-process live array / collective p2p /
        host fallback — see experimental/device_object/resolve.py)."""
        from ray_tpu.experimental.device_object.descriptor import validate_transport
        from ray_tpu.object_ref import ObjectRef

        validate_transport(transport)
        if not _maybe_jax_array(value):
            raise TypeError(
                "tensor_transport= requires a top-level jax.Array, got "
                f"{type(value).__name__}; use a plain put() for host values"
            )
        oid = ObjectID.for_put(self.current_task_id)
        oid_hex = oid.hex()
        holder_id, holder_kind = self._holder_identity()
        meta = self._device_manager().create_resident(oid_hex, value, transport, holder_id, holder_kind)
        data = serialization.serialize(meta).to_bytes()
        with self._lock:
            entry = self.owned.setdefault(oid_hex, OwnedObject())
            entry.device = {"addr": list(self.address), "id": holder_id}
            self.in_process_store[oid_hex] = {"data": data, "value": meta}
        self._set_event(oid_hex)
        return ObjectRef(oid, self.address)

    def _package_device(self, oid_hex: str, value) -> list:
        """Actor-task return under tensor_transport=: keep the array here
        (this actor is the holder), ship the descriptor as the inline result
        plus the holder coordinates the owner's refcounting needs."""
        holder_id, holder_kind = self._holder_identity()
        meta = self._device_manager().create_resident(
            oid_hex, value, self._tensor_transport, holder_id, holder_kind
        )
        data = serialization.serialize(meta).to_bytes()
        return [oid_hex, "inline", data, [], {"addr": list(self.address), "id": holder_id}]

    def _devobj_client(self, addr: tuple) -> RpcClient:
        """Cached connection to a device-object holder with a SHORT connect
        timeout: resolution probes holders that may be dead, and the typed
        loss must surface quickly (the host-copy fallback runs after it)."""
        with self._lock:
            client = self._devobj_clients.get(addr)
            if client is None:
                client = RpcClient(addr, label=f"devobj-{addr}", connect_timeout=2.0)
                self._devobj_clients[addr] = client
            return client

    @any_thread
    def _free_device_object(self, oid: str, dev: dict):
        """Owner-side release reached zero refs: tell the holder to drop the
        device buffers (and any host copy it spilled)."""
        addr = tuple(dev.get("addr") or ())
        if addr == tuple(self.address):
            mgr = self._device_objects
            if mgr is not None:
                mgr.free(oid)
            return

        async def _push():
            try:
                await self._owner_client(addr).apush("devobj_free", {"object_id": oid})
            except Exception:
                pass

        self._io.spawn(_push())

    def put_serialized(self, ser: serialization.SerializedObject):
        from ray_tpu.object_ref import ObjectRef

        oid = ObjectID.for_put(self.current_task_id)
        oid_hex = oid.hex()
        contained = self._incref_contained(ser.contained_refs)
        with self._lock:
            entry = self.owned.setdefault(oid_hex, OwnedObject())
            entry.contained = contained
            entry.format = ser.format
        if ser.total_size > self.cfg.max_direct_call_object_size:
            self.store.put_serialized(oid_hex, ser)
            with self._lock:
                self.owned[oid_hex].in_plasma = True
                self.owned[oid_hex].location_hint = self.node_id
        else:
            with self._lock:
                self.in_process_store[oid_hex] = {"data": ser.to_bytes()}
        self._set_event(oid_hex)
        return ObjectRef(oid, self.address)

    # ---- gets ----

    def _ensure_event(self, oid_hex: str) -> asyncio.Event:
        ev = self._object_events.get(oid_hex)
        if ev is None:
            ev = asyncio.Event()
            self._object_events[oid_hex] = ev
        return ev

    def _set_event(self, oid_hex: str):
        self._set_events((oid_hex,))

    @any_thread
    def _set_events(self, oid_hexes):
        """Signal completion of one or more objects, coalesced.

        Sync get() waiters wake directly (threading.Event.set is safe from
        any thread — no loop round-trip); asyncio waiters are set inline
        when already on the IO loop (a batch of results then costs ZERO
        extra loop ticks) and via one call_soon_threadsafe for the whole
        batch otherwise."""
        if not oid_hexes:
            return
        with self._lock:
            waiter_lists = [
                w for o in oid_hexes for w in (self._sync_waiters.pop(o, None),) if w
            ]
        for lst in waiter_lists:
            for ev in lst:
                ev.set()

        def _set_all():
            with self._lock:
                evs = [self._ensure_event(o) for o in oid_hexes]
            for ev in evs:
                ev.set()

        if threading.current_thread() is self._io._thread:
            _set_all()
        else:
            self._io.loop.call_soon_threadsafe(_set_all)

    async def _wait_event(self, oid_hex: str, timeout: float | None):
        with self._lock:
            ev = self._ensure_event(oid_hex)
        if timeout is None:
            await ev.wait()
        else:
            await asyncio.wait_for(ev.wait(), timeout)

    @staticmethod
    def _raise_if_error(value):
        """The one error surface for materialized values (shared by get()
        and get_device_meta so new error types never diverge)."""
        if isinstance(value, TaskError):
            if isinstance(value.cause, (TaskCancelledError, ActorDiedError)):
                raise value.cause
            raise value
        if isinstance(
            value,
            (ObjectLostError, WorkerCrashedError, ActorDiedError, TaskCancelledError, OutOfMemoryError),
        ):
            raise value

    @blocking
    def get(self, refs, timeout: float | None = None):
        single = not isinstance(refs, list)
        ref_list = [refs] if single else refs
        deadline = None if timeout is None else time.monotonic() + timeout
        values = [self._get_one(ref, deadline) for ref in ref_list]
        for v in values:
            self._raise_if_error(v)
        return values[0] if single else values

    def _remaining(self, deadline) -> float | None:
        if deadline is None:
            return None
        rem = deadline - time.monotonic()
        if rem <= 0:
            raise GetTimeoutError("ray_tpu.get() timed out")
        return rem

    @blocking
    def get_device_meta(self, ref, timeout: float | None = None):
        """The RAW DeviceObjectMeta behind a device-object ref, WITHOUT
        resolving the payload (device_object.broadcast needs the holder
        coordinates, not the array). Waits for the descriptor to
        materialize exactly like get(); raises TypeError for refs that are
        not device objects."""
        deadline = None if timeout is None else time.monotonic() + timeout
        value = self._get_one_raw(ref, deadline)
        self._raise_if_error(value)
        if type(value).__name__ == "DeviceObjectMeta":
            return value
        raise TypeError(
            f"object {ref.hex()[:12]} is not a device object (resolved to "
            f"{type(value).__name__}); group broadcast applies to "
            "tensor_transport= refs"
        )

    def _get_one(self, ref, deadline):
        value = self._get_one_raw(ref, deadline)
        # Device object descriptors resolve out of band (live array /
        # collective transfer / host fallback). Name probe first so the
        # ordinary get path never imports the device plane.
        if type(value).__name__ == "DeviceObjectMeta":
            from ray_tpu.experimental.device_object import DeviceObjectMeta, resolve_meta

            if isinstance(value, DeviceObjectMeta):
                return resolve_meta(self, value, deadline)
        return value

    def _get_one_raw(self, ref, deadline):
        oid_hex = ref.hex()
        is_owner = ref.owner_addr is None or tuple(ref.owner_addr) == tuple(self.address)
        attempts = 0
        missing_probes = 0  # CONSECUTIVE no-location probes (not loop passes)
        while True:
            attempts += 1
            # 1. In-process store.
            with self._lock:
                entry = self.in_process_store.get(oid_hex)
            if entry is not None:
                return self._materialize(oid_hex, entry)
            # 2. Pending task we own: wait for completion. Direct threading
            # waiter — the completion handler (on the IO loop) sets it and
            # this thread runs: one handoff, no loop scheduling. Registration
            # re-checks completion under the lock so a result landing between
            # the pending probe and the registration can't strand the waiter.
            task_id = oid_hex[: TaskID.SIZE * 2]
            with self._lock:
                pending = task_id in self.pending_tasks
            if pending and is_owner:
                waiter = threading.Event()
                with self._lock:
                    # Unlike the persistent asyncio.Event this replaced, a
                    # threading waiter registered AFTER the signal would miss
                    # it — so availability (inline result, or a plasma copy:
                    # streaming items of a still-running task land there) is
                    # re-checked under the same lock every producer stores
                    # under before it signals.
                    obj = self.owned.get(oid_hex)
                    if (
                        task_id in self.pending_tasks
                        and oid_hex not in self.in_process_store
                        and not (obj is not None and obj.in_plasma)
                    ):
                        self._sync_waiters.setdefault(oid_hex, []).append(waiter)
                    else:
                        waiter = None
                if waiter is not None:
                    rem = self._remaining(deadline)
                    if not waiter.wait(rem):
                        with self._lock:
                            lst = self._sync_waiters.get(oid_hex)
                            if lst is not None and waiter in lst:
                                lst.remove(waiter)
                                if not lst:
                                    self._sync_waiters.pop(oid_hex, None)
                        raise GetTimeoutError("ray_tpu.get() timed out")
                    rec = self._hop_by_task.get(task_id)
                    if rec is not None and "wake" not in rec:
                        rec["wake"] = time.monotonic()
                continue
            # 3. Local/remote plasma.
            with self._lock:
                obj = self.owned.get(oid_hex)
                in_plasma = obj.in_plasma if obj else None
            if is_owner and in_plasma is False and entry is None:
                # Owned, not in plasma, not in-process => lost; try lineage.
                if self._try_reconstruct(oid_hex):
                    continue
                raise ObjectLostError(oid_hex)
            # Local plasma fast path: only block in the store when the copy
            # is already local, or when we know it lives in plasma somewhere
            # (owner's in_plasma flag). Borrowers must NOT speculatively pull
            # — small results live inline at the owner, not in any store.
            local = self.store.contains(oid_hex)
            if local or (is_owner and in_plasma):
                try:
                    rem = self._remaining(deadline)
                    view = self.store.get_view(oid_hex, timeout=min(rem, 5.0) if rem else 5.0)
                    try:
                        return serialization.deserialize(view)
                    finally:
                        self.store.release(oid_hex)
                except GetTimeoutError:
                    raise
                except Exception:
                    pass
            # 4. Borrower path: ask the owner directly (blocks until the task
            # finishes; returns inline bytes or points us at plasma).
            if not is_owner:
                result = self._fetch_from_owner(ref, deadline)
                if result is not _MISSING:
                    return result
                # Owner reports a plasma copy: pull it through our raylet.
                try:
                    rem = self._remaining(deadline)
                    view = self.store.get_view(oid_hex, timeout=min(rem, 30.0) if rem else 30.0)
                    try:
                        return serialization.deserialize(view)
                    finally:
                        self.store.release(oid_hex)
                except GetTimeoutError:
                    raise
                except Exception:
                    pass
            else:
                # Only reconstruct when no copy exists anywhere (a slow pull
                # must not trigger a spurious re-execution). Location rows
                # are registered asynchronously at seal time, so one missing
                # probe is not proof of loss — require two CONSECUTIVE
                # missing probes (a counter of its own: the overall loop
                # counter also ticks on waits that never probed locations)
                # before re-executing.
                if not self._has_any_location(oid_hex):
                    missing_probes += 1
                    if missing_probes >= 2 and self._try_reconstruct(oid_hex):
                        missing_probes = 0
                        continue
                    if missing_probes >= 4:
                        raise ObjectLostError(oid_hex)
                else:
                    missing_probes = 0
            time.sleep(0.05)
            self._remaining(deadline)

    def _materialize(self, oid_hex: str, entry: dict):
        if "value" not in entry:
            entry["value"] = serialization.deserialize(entry["data"])
        return entry["value"]

    def _fetch_from_owner(self, ref, deadline):
        try:
            client = self._owner_client(tuple(ref.owner_addr))
            # get_inline with wait=True is an idempotent LONG-POLL, so wait
            # in bounded slices and simply re-poll on a slice timeout OR an
            # in-slice "missing" (= still pending) answer: a request/reply
            # frame silently lost on the wire costs one slice (it used to
            # park this borrower for the caller's whole deadline — forever
            # for task-arg resolution, which has none), and the server
            # parks its wait for at most the slice too, so abandoned
            # slices cannot accumulate parked handler tasks on the owner.
            # The overall wait envelope stays the pre-slicing one:
            # worker_lease_timeout_s total, then "missing" falls through.
            wait_deadline = time.monotonic() + self.cfg.worker_lease_timeout_s
            while True:
                rem = self._remaining(deadline)  # raises at the deadline
                per = min(
                    10.0,
                    max(0.5, wait_deadline - time.monotonic()),
                    rem if rem is not None else 10.0,
                )
                try:
                    resp = client.call(
                        "get_inline",
                        {"object_id": ref.hex(), "wait": True, "timeout": per},
                        timeout=per + 2.0,
                        retries=0,
                    )
                except (asyncio.TimeoutError, TimeoutError):
                    resp = None  # slice lost on the wire; re-poll
                if resp is not None and resp.get("kind") != "missing":
                    break
                if time.monotonic() >= wait_deadline:
                    resp = resp or {"kind": "missing"}
                    break
        except GetTimeoutError:
            raise
        except Exception:
            raise OwnerDiedError(ref.hex(), f"owner of {ref.hex()} is unreachable")
        kind = resp.get("kind")
        if kind == "inline":
            return serialization.deserialize(resp["data"])
        if kind == "plasma":
            return _MISSING  # loop will pull via local raylet
        raise ObjectLostError(ref.hex())

    def _has_any_location(self, oid_hex: str) -> bool:
        try:
            resp = self.gcs.call("get_object_locations", {"object_id": oid_hex}, timeout=5)
            return bool(resp.get("locations"))
        except Exception:
            return False

    def _try_reconstruct(self, oid_hex: str) -> bool:
        """Lineage reconstruction (reference: object_recovery_manager.h:90)."""
        task_id = oid_hex[: TaskID.SIZE * 2]
        with self._lock:
            spec = self.lineage.get(task_id)
            if spec is None or spec.max_retries <= 0:
                return False
            if task_id in self.pending_tasks:
                return True
            self.lineage.pop(task_id, None)
            for oid in spec.return_object_ids():
                ev = self._object_events.get(oid)
                if ev is not None:
                    self._io.loop.call_soon_threadsafe(ev.clear)
                obj = self.owned.get(oid)
                if obj is not None:
                    obj.in_plasma = False
        logger.info("reconstructing object %s by re-executing task %s", oid_hex[:8], task_id[:8])
        self._register_pending(spec, [])
        self.raylet.call("submit_task", {"spec": spec.to_wire()})
        return True

    # ---- wait ----

    @blocking
    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        deadline = None if timeout is None else time.monotonic() + timeout
        pending = list(refs)
        ready: list = []
        while True:
            still = []
            for ref in pending:
                if self._is_ready(ref, fetch_local):
                    ready.append(ref)
                else:
                    still.append(ref)
            pending = still
            if len(ready) >= num_returns or not pending:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            time.sleep(0.02)
        # reference semantics (worker.py:2587): at most num_returns in the
        # ready list; ready-but-surplus refs stay in the remaining list
        return ready[:num_returns], ready[num_returns:] + pending

    def _is_ready(self, ref, fetch_local: bool) -> bool:
        oid_hex = ref.hex()
        with self._lock:
            if oid_hex in self.in_process_store:
                return True
            task_id = oid_hex[: TaskID.SIZE * 2]
            if task_id in self.pending_tasks:
                return False
            obj = self.owned.get(oid_hex)
        if obj is not None and obj.in_plasma:
            if not fetch_local:
                return True
            return self.store.contains(oid_hex)
        if ref.owner_addr is not None and tuple(ref.owner_addr) != tuple(self.address):
            if self.store.contains(oid_hex):
                return True
            try:
                client = self._owner_client(tuple(ref.owner_addr))
                resp = client.call("get_inline", {"object_id": oid_hex, "wait": False}, timeout=2)
                return resp.get("kind") in ("inline", "plasma")
            except Exception:
                return False
        return obj is not None and (obj.in_plasma or oid_hex in self.in_process_store)

    def as_future(self, ref) -> ConcurrentFuture:
        fut: ConcurrentFuture = ConcurrentFuture()

        def _resolve():
            try:
                fut.set_result(self.get(ref))
            except BaseException as e:
                fut.set_exception(e)

        threading.Thread(target=_resolve, daemon=True).start()
        return fut

    # ==================================================================
    # Actor submission (reference: direct_actor_task_submitter.h:67)
    # ==================================================================

    def create_actor(self, cls, args, kwargs, **opts):
        actor_id = ActorID.of(self.job_id)
        if opts.get("tensor_transport"):
            from ray_tpu.experimental.device_object.descriptor import validate_transport

            validate_transport(opts["tensor_transport"])
        wire_args, arg_refs = self._prepare_args(args, kwargs or {})
        spec = TaskSpec(
            task_id=TaskID.for_task(actor_id).hex(),
            job_id=self.job_id.hex(),
            name=f"{cls.__name__}.__init__",
            task_type=ACTOR_CREATION_TASK,
            function_key=self._export_function(cls),
            args=wire_args,
            num_returns=0,
            # Actors hold no CPU while alive (reference semantics: num_cpus=0
            # default for actor lifetime) so many actors can share a node.
            resources=opts.get("resources") or {},
            owner_addr=list(self.address),
            owner_worker_id=self.worker_id,
            actor_id=actor_id.hex(),
            max_restarts=opts.get("max_restarts", self.cfg.default_actor_max_restarts),
            max_task_retries=opts.get("max_task_retries", 0),
            max_concurrency=opts.get("max_concurrency", 1),
            actor_name=opts.get("name") or "",
            namespace=opts.get("namespace") or self.namespace,
            get_if_exists=opts.get("get_if_exists", False),
            tensor_transport=opts.get("tensor_transport") or "",
            placement_group_id=opts.get("placement_group_id", ""),
            placement_group_bundle_index=opts.get("placement_group_bundle_index", -1),
            scheduling_strategy=opts.get("scheduling_strategy", "DEFAULT"),
            runtime_env=self._merged_runtime_env(opts.get("runtime_env")),
            trace_ctx=self._trace_ctx(),
        )
        for ref in arg_refs:
            self._pin_arg(ref)
        # Bounded per-attempt ack (acall retries on TimeoutError): a
        # register_actor request/reply silently lost on the wire used to
        # park .remote() FOREVER — no timeout, no backstop, not even the
        # 2-minute kind. The GCS handler is idempotent under the retry
        # (remembered outcome; see gcs.rpc_register_actor). Transport
        # exhaustion surfaces as the TYPED unavailability error naming the
        # component, not a bare TimeoutError.
        from ray_tpu.exceptions import ActorUnavailableError

        try:
            resp = self.gcs.call(
                "register_actor", {"spec": spec.to_wire()}, timeout=15
            )
        except (TimeoutError, ConnectionLost) as e:
            raise ActorUnavailableError(
                f"could not register actor {cls.__name__} with the GCS at "
                f"{self.gcs.address}: {type(e).__name__}: {e}"
            ) from e
        if not resp.get("ok"):
            err = resp.get("error", "actor registration failed")
            if "no feasible node" in err:
                # Placement exhaustion is a (possibly transient) cluster
                # condition, not a caller bug: surface the TYPED
                # unavailability error; name collisions etc. stay ValueError.
                raise ActorUnavailableError(f"actor {cls.__name__}: {err}")
            raise ValueError(err)
        return {
            "actor_id": resp["actor_id"],
            "max_task_retries": spec.max_task_retries,
            "name": spec.actor_name,
        }

    @blocking
    def _resolve_actor(self, actor_id: str, timeout: float | None = None) -> tuple:
        """Wait for the actor's address. Reference semantics: calls to an
        actor still being created BUFFER until it is ready (creation can
        legitimately take long under load — worker spawn + heavy imports), so
        the timeout clock only runs while the actor is NOT progressing
        through PENDING_CREATION/RESTARTING."""
        timeout = timeout if timeout is not None else self.cfg.worker_lease_timeout_s
        deadline = time.monotonic() + timeout
        creation_deadline = time.monotonic() + self.cfg.actor_creation_timeout_s
        while True:
            addr = self._actor_addrs.get(actor_id)
            if addr is not None:
                return addr
            # Bounded read (idempotent): a lost reply costs one retry, not
            # the resolve loop wedged forever inside its own deadline.
            resp = self.gcs.call("get_actor", {"actor_id": actor_id}, timeout=10)
            if not resp.get("found"):
                raise ActorDiedError(f"actor {actor_id[:8]} not found")
            info = resp["info"]
            if info["state"] == "ALIVE" and info.get("address"):
                addr = tuple(info["address"])
                self._actor_addrs[actor_id] = addr
                return addr
            if info["state"] == "DEAD":
                raise ActorDiedError(
                    f"actor {actor_id[:8]} is dead: {info.get('death_cause', '')}",
                    actor_id=actor_id,
                )
            in_creation = info["state"] in ("PENDING_CREATION", "RESTARTING")
            limit = creation_deadline if in_creation else deadline
            if time.monotonic() > limit:
                raise ActorDiedError(
                    f"timed out resolving actor {actor_id[:8]} (state {info['state']})"
                )
            time.sleep(0.05)

    @blocking
    def _actor_client(self, actor_id: str) -> RpcClient:
        addr = self._resolve_actor(actor_id)
        client = self._actor_clients.get(actor_id)
        if client is None or client.address != addr:
            if client is not None:
                client.close()
            # Short connect timeout: a dead actor should surface as
            # ActorDiedError quickly; restarts re-resolve through GCS anyway.
            client = RpcClient(addr, label=f"actor-{actor_id[:8]}", connect_timeout=2.0)
            self._actor_clients[actor_id] = client
        return client

    def submit_actor_task(self, actor_id: str, method_name: str, args, kwargs, num_returns=1, max_task_retries=0):
        from ray_tpu.object_ref import ObjectRef

        if not isinstance(num_returns, int):
            raise ValueError(
                "num_returns='streaming' is not supported for actor tasks yet; "
                "use a normal @ray_tpu.remote task"
            )
        task_id = self._next_task_id()
        wire_args, arg_refs = self._prepare_args(args, kwargs or {})
        self._actor_seq[actor_id] += 1
        spec = TaskSpec(
            task_id=task_id.hex(),
            job_id=self.job_id.hex(),
            name=method_name,
            task_type=ACTOR_TASK,
            args=wire_args,
            num_returns=num_returns,
            owner_addr=list(self.address),
            owner_worker_id=self.worker_id,
            actor_id=actor_id,
            method_name=method_name,
            seq_no=self._actor_seq[actor_id],
            max_task_retries=max_task_retries,
            trace_ctx=self._trace_ctx(),
            hop_ts=self._hop_stamp_start(),
        )
        self._register_pending(spec, arg_refs)
        self._actor_pending[actor_id].add(spec.task_id)
        self._io.spawn(self._drive_actor_call(spec, attempts_left=max(0, max_task_retries)))
        return [
            ObjectRef(ObjectID.for_return(task_id, i), self.address)
            for i in range(num_returns)
        ]

    @loop_only
    def _actor_client_cached(self, actor_id: str) -> RpcClient | None:
        """Loop-safe fast path: the already-resolved, address-matching client
        for an actor, or None. Skips the run_in_executor round trip (two
        thread handoffs) that the cold resolve path needs for its blocking
        GCS lookup — on the warm sync-call loop that round trip was the
        single largest owner-side cost."""
        addr = self._actor_addrs.get(actor_id)
        if addr is None:
            return None
        client = self._actor_clients.get(actor_id)
        if client is None or client.address != addr:
            return None
        return client

    async def _await_actor_resp(self, client, spec: TaskSpec, wire, fut):
        """Await an actor call's response with LOSS detection. An actor
        method may legitimately run for hours, so there is no result
        timeout — but a silently lost request or response frame (the
        connection stays up, so no ConnectionLost ever fires and no sweep
        covers actor calls) used to park the call FOREVER. Every ack
        interval with no response, probe the worker over the same FIFO
        connection: 'never received' is proof of request loss (the probe
        cannot overtake the request frame) -> resend, deduped worker-side
        by task id; a cached result means the RESPONSE frame was lost ->
        the probe re-delivers it."""
        ack = max(2.0, self.cfg.task_done_ack_timeout_s)
        futs = {fut}
        try:
            while True:
                if not futs:
                    # Every outstanding seq answered dup: the seq carrying
                    # the real answer died with a reset connection while
                    # the method still runs. PACE on the probe (an
                    # immediate resend would spin dup/resend at round-trip
                    # rate for the method's whole runtime) — completion
                    # re-delivers through the worker's result cache.
                    await asyncio.sleep(min(1.0, ack))
                    probe = await client.acall(
                        "actor_has_task", {"task_id": spec.task_id},
                        timeout=5, retries=1,
                    )
                    if probe.get("result") is not None:
                        return probe["result"]
                    if probe.get("has"):
                        continue  # still executing; keep pacing
                    resent = client.send_nowait("actor_call", wire)
                    if resent is None:
                        resent = await client.astart_call("actor_call", wire)
                    futs.add(resent)
                done, _pending = await asyncio.wait(
                    futs, timeout=ack, return_when=asyncio.FIRST_COMPLETED
                )
                for f in done:
                    resp = await f  # done: instant; raises ConnectionLost up
                    futs.discard(f)
                    if not (isinstance(resp, dict) and resp.get("dup")):
                        return resp
                    # dup marker: the real answer rides another pending seq.
                if done:
                    continue
                probe = await client.acall(
                    "actor_has_task", {"task_id": spec.task_id}, timeout=5, retries=1
                )
                if probe.get("result") is not None:
                    return probe["result"]
                if not probe.get("has"):
                    resent = client.send_nowait("actor_call", wire)
                    if resent is None:
                        resent = await client.astart_call("actor_call", wire)
                    futs.add(resent)
                # has=True, no result yet: the method is genuinely running —
                # keep waiting with no bound, as before.
        finally:
            # Abandoned duplicates (we returned/raised with sends still
            # pending) must not surface never-retrieved exceptions when the
            # connection eventually resolves them.
            for f in futs:
                if not f.done():
                    f.add_done_callback(lambda x: x.cancelled() or x.exception())

    async def _drive_actor_call(self, spec: TaskSpec, attempts_left: int):
        actor_id = spec.actor_id
        loop = asyncio.get_event_loop()
        # Per-actor FIFO lock: resolve + send under the lock so calls hit the
        # wire in submission order (reference: sequential_actor_submit_queue.h);
        # responses are awaited outside so calls still pipeline.
        lock = self._actor_submit_locks[actor_id]
        while True:
            try:
                async with lock:
                    client = self._actor_client_cached(actor_id)
                    if client is None:
                        client = await loop.run_in_executor(None, self._actor_client, actor_id)
                    if spec.hop_ts:
                        spec.hop_ts["ship"] = time.monotonic()
                    wire = {"spec": spec.to_wire()}
                    fut = client.send_nowait("actor_call", wire)
                    if fut is None:
                        fut = await client.astart_call("actor_call", wire)
                resp = await self._await_actor_resp(client, spec, wire, fut)
                if spec.hop_ts:
                    resp.setdefault("hop", {})["owner_recv"] = time.monotonic()
                self._handle_task_done(spec.task_id, resp)
                return
            except ActorDiedError as e:
                self._fail_task(spec.task_id, e)
                return
            except (ConnectionLost, RpcError, OSError) as e:
                # Actor process may be restarting; drop the cached address and
                # re-resolve (reference: GCS-driven actor restart, client resubmit).
                self._actor_addrs.pop(actor_id, None)
                old = self._actor_clients.pop(actor_id, None)
                if old is not None:
                    old.close()
                if attempts_left <= 0:
                    self._fail_task(
                        spec.task_id,
                        ActorDiedError(f"actor {actor_id[:8]} died during call: {e}", actor_id=actor_id),
                    )
                    return
                attempts_left -= 1
                await asyncio.sleep(0.1)

    # ==================================================================
    # Cancellation (reference: worker.py:2773 ray.cancel +
    # core_worker.cc CancelTask / task_manager.cc MarkTaskCanceled)
    # ==================================================================

    def cancel(self, ref, force: bool = False, recursive: bool = True):
        """Cancel the task that produces ``ref``. Best-effort and async like
        the reference: returns immediately; a successful cancel surfaces as
        TaskCancelledError from ``get`` on the task's returns."""
        task_id = ref.id.task_id().hex()
        if (
            ref.owner_addr is not None
            and tuple(ref.owner_addr) != tuple(self.address)
        ):
            # Borrowed ref: only the owner tracks the producing task —
            # forward (reference: RemoteCancelTask to the owner).
            msg = {"task_id": task_id, "force": force, "recursive": recursive}
            if force:
                # force=True can be invalid (actor tasks) and the reference
                # surfaces that as ValueError at the call site — so this one
                # path is synchronous: wait for the owner's verdict instead
                # of discarding it in a fire-and-forget coroutine.
                resp = self._owner_client(tuple(ref.owner_addr)).call(
                    "cancel_task", msg, timeout=30
                )
                if (resp or {}).get("error"):
                    raise ValueError(resp["error"])
                return

            async def _fwd():
                try:
                    resp = await self._owner_client(tuple(ref.owner_addr)).acall(
                        "cancel_task", msg, timeout=30
                    )
                    if (resp or {}).get("error"):
                        logger.warning(
                            "cancel of %s rejected by owner: %s",
                            task_id[:8], resp["error"],
                        )
                except Exception:
                    logger.warning("forwarding cancel of %s to owner failed", task_id[:8])

            self._io.spawn(_fwd())
            return
        self.cancel_owned(task_id, force=force, recursive=recursive)

    def cancel_owned(self, task_id: str, force: bool = False, recursive: bool = True) -> bool:
        """Owner-side cancel. Returns False if the task already finished."""
        with self._lock:
            pending = self.pending_tasks.get(task_id)
        if pending is None:
            return False
        if pending.spec.is_actor_task() and force:
            raise ValueError(
                "force=True is not supported for actor tasks (reference "
                "semantics: kill the actor with ray_tpu.kill instead)"
            )
        pending.cancel_requested = True
        self._io.spawn(self._drive_cancel(pending, force, recursive))
        return True

    def _cancel_error(self, spec: TaskSpec) -> TaskCancelledError:
        return TaskCancelledError(
            f"task {spec.name} ({spec.task_id[:8]}) was cancelled"
        )

    async def _drive_cancel(self, pending: PendingTask, force: bool, recursive: bool):
        spec = pending.spec
        task_id = spec.task_id
        msg = {"task_id": task_id, "force": bool(force), "recursive": bool(recursive)}
        loop = asyncio.get_event_loop()
        try:
            if spec.is_actor_task():
                # Queued or running at the actor process: its executor
                # dequeues pre-dispatch calls and interrupts the running one.
                try:
                    client = await loop.run_in_executor(None, self._actor_client, spec.actor_id)
                    await client.acall("cancel_exec", msg, timeout=30)
                except Exception:
                    # Actor unreachable (dead/restarting): the call will fail
                    # through the normal actor-death path; nothing to recall.
                    pass
                return
            if pending.phase == "resolving":
                # Still owner-local, waiting on args: the deferred submitter
                # checks cancel_requested and aborts; fail the task now.
                self._fail_task(task_id, self._cancel_error(spec))
                return
            # Drain owner-local submit buffers (classic path).
            with self._submit_lock:
                for s in self._submit_buf:
                    if s.task_id == task_id:
                        self._submit_buf.remove(s)
                        self._fail_task(task_id, self._cancel_error(spec))
                        return
            lm = self._lease_mgr
            if lm is not None and self._lease_eligible(spec):
                if lm.cancel_queued(task_id):
                    # Recalled from owner-side lease staging, never shipped.
                    self._fail_task(task_id, self._cancel_error(spec))
                    return
                lease = lm.lease_for(task_id)
                if lease is not None:
                    try:
                        await lease.client.acall("cancel_exec", msg, timeout=30)
                    except Exception:
                        pass  # worker death → lease failover sees cancel_requested
                    return
                # Not staged, not in flight: completion raced us; if still
                # pending, fall through to the raylet probe below.
            resp = {}
            try:
                resp = await self.raylet.acall("cancel_task", msg, timeout=30)
            except Exception:
                pass
            with self._lock:
                still_pending = task_id in self.pending_tasks
            if still_pending and (resp.get("dequeued") or not resp.get("found")):
                # Dequeued before dispatch, or nowhere in the cluster
                # (pre-arrival tombstones drop it if it shows up late).
                self._fail_task(task_id, self._cancel_error(spec))
        except Exception:
            logger.exception("cancel of task %s failed", task_id[:8])

    @any_thread
    def mark_cancelled(self, task_id: str):
        """Tombstone: drop this task if it arrives for execution later."""
        self._cancelled_tasks.add(task_id)

    def cancelled_payload(self, spec: TaskSpec) -> dict:
        err = self._cancel_error(spec)
        return {
            "task_id": spec.task_id,
            "results": [],
            "error": serialization.serialize(err).to_bytes(),
            "cancelled": True,
            "duration_s": 0.0,
        }

    def interrupt_running_task(self, task_id: str, force: bool = False) -> bool:
        """Interrupt the thread currently executing ``task_id``. Non-force
        raises TaskCancelledError at the next bytecode boundary (analog of
        the reference's KeyboardInterrupt into the executing thread); force
        kills the worker process like the reference's force-kill."""
        with self._active_exec_lock:
            ident = None
            for entry in self._active_exec.values():
                if len(entry) > 2 and entry[1].task_id == task_id:
                    ident = entry[2]
                    break
            if ident is None:
                return False
            self.mark_cancelled(task_id)  # lets execute_task tag the payload
            if force:
                import signal as _signal

                os.kill(os.getpid(), _signal.SIGKILL)
                return True  # unreachable
            if ident == self._main_thread_ident:
                # Main-thread task: deliver via SIGUSR2 so the raising
                # handler (installed by worker_main) aborts even C-blocked
                # calls — time.sleep, socket waits — per PEP 475. An
                # async-exc alone only lands on a bytecode boundary, which a
                # C-level block never reaches. The handler re-checks that
                # _main_task_id still equals the target so a late signal
                # can't cancel a subsequent task.
                import signal as _signal

                self._main_cancel_target = task_id
                try:
                    _signal.pthread_kill(ident, _signal.SIGUSR2)
                    return True
                except Exception:
                    pass  # handler unavailable: fall back to async-exc
            import ctypes

            # Fired while holding _active_exec_lock: execute_task's finally
            # must take this lock before the thread can move on to another
            # task, so the async-exc cannot land inside an unrelated task's
            # body (the reference re-checks the executing task id the same
            # way before raising into the thread).
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(ident), ctypes.py_object(TaskCancelledError)
            )
        return True

    def cancel_children_of(self, parent_task_id: str, force: bool, recursive: bool):
        """Cancel every pending task THIS process owns that was submitted by
        ``parent_task_id`` (recursive cancellation: children of a task are
        owned by the worker that executed it)."""
        with self._lock:
            children = [
                tid
                for tid, p in self.pending_tasks.items()
                if p.parent_task_id == parent_task_id
            ]
        for tid in children:
            try:
                self.cancel_owned(tid, force=force, recursive=recursive)
            except ValueError:
                pass  # force on an actor-task child: skip, cancel the rest

    async def rpc_cancel_task(self, req):
        """Owner-side handler for forwarded cancels (borrower → owner)."""
        try:
            found = self.cancel_owned(
                req["task_id"],
                force=bool(req.get("force")),
                recursive=req.get("recursive", True),
            )
        except ValueError as e:
            return {"found": True, "error": str(e)}
        return {"found": found}

    @any_thread
    def _fail_task(self, task_id: str, error: BaseException):
        with self._lock:
            pending = self.pending_tasks.get(task_id)
            if pending is None or pending.done_claimed:
                return
            pending.done_claimed = True
        ser = serialization.serialize(error).to_bytes()
        with self._lock:
            stream = self._streams.get(task_id)
            for oid in pending.spec.return_object_ids():
                self.in_process_store[oid] = {"data": ser, "value": error}
            # Pop only after the error entries are visible (same ordering
            # contract as _handle_task_done).
            self.pending_tasks.pop(task_id, None)
        if stream is not None:
            with stream["cond"]:
                stream["error"] = ser
                stream["cond"].notify_all()
        self._set_events(pending.spec.return_object_ids())
        if pending.spec.actor_id:
            self._actor_pending[pending.spec.actor_id].discard(task_id)
        self._unpin_args(pending.arg_refs)

    # ==================================================================
    # Owner-side RPC handlers
    # ==================================================================

    def _duplicate_completion(self, payload: dict) -> bool:
        cid = payload.get("cid")
        if not cid:
            return False
        if cid in self._seen_completions:
            return True
        self._seen_completions.add(cid)
        return False

    async def rpc_task_done(self, req):
        if self._duplicate_completion(req):
            return {"ok": True}
        if req.get("hop") is not None:
            req["hop"]["owner_recv"] = time.monotonic()
        self._handle_task_done(req["task_id"], req)
        return {"ok": True}

    async def rpc_tasks_done(self, req):
        """Batched completions from a leased worker (lease_manager.py).

        Runs on the IO loop, so _handle_task_done's event sets are inline —
        the whole batch of future wakeups costs zero extra loop ticks
        (sync getters wake directly off their threading.Event)."""
        now = time.monotonic()
        lm = self._lease_mgr
        shapes = set()
        for payload in req["batch"]:
            if self._duplicate_completion(payload):
                continue
            if payload.get("hop") is not None:
                payload["hop"]["owner_recv"] = now
            if lm is not None:
                shapes.add(lm.on_task_done(payload["task_id"], payload.get("duration_s")))
            self._handle_task_done(payload["task_id"], payload)
        if lm is not None:
            lm.topup(shapes)
        return {"ok": True}

    async def rpc_lease_revoked(self, req):
        if self._lease_mgr is not None:
            self._lease_mgr.on_lease_revoked(
                req["lease_id"],
                oom=bool(req.get("oom")),
                reason=req.get("reason") or "revoked by raylet",
            )
        return {"ok": True}

    async def rpc_stream_item(self, req):
        self._record_stream_item(req["task_id"], req["index"], req["result"])
        return {"ok": True}

    def _record_stream_item(self, task_id: str, index: int, result: list):
        oid, kind, data = result[0], result[1], result[2]
        contained = result[3] if len(result) > 3 else []
        with self._lock:
            obj = self.owned.setdefault(oid, OwnedObject())
            if contained:
                obj.contained = contained
            if len(result) > 4 and result[4]:
                # Streaming actor tasks don't exist yet, but a device-object
                # item must never lose its holder coordinates — that's the
                # free protocol (see _handle_task_done).
                obj.device = result[4]
            if kind == "inline":
                self.in_process_store[oid] = {"data": data}
            else:
                obj.in_plasma = True
                obj.location_hint = data
            stream = self._streams.get(task_id)
        self._set_event(oid)
        if stream is not None:
            # Index-keyed (not append): item delivery is pipelined, so
            # robustness can't depend on arrival order.
            with stream["cond"]:
                stream["items"][index] = oid
                stream["cond"].notify_all()

    def _drop_stream_locked(self, task_id: str):
        """Remove stream state and free its never-wrapped items (oids the
        consumer never turned into ObjectRefs sit at ref_count 0 and would
        otherwise leak in the owner forever). Caller holds self._lock."""
        stream = self._streams.pop(task_id, None)
        if stream is None:
            return
        for oid in stream["items"].values():
            obj = self.owned.get(oid)
            if obj is not None and obj.ref_count == 0 and obj.pinned == 0:
                self._maybe_free_locked(oid, obj)

    def _reset_stream_for_retry(self, task_id: str):
        """A retried streaming task re-yields from index 0: clear delivered
        items so the re-execution's (same-oid) items replace them instead of
        duplicating, and the consumer just blocks until re-production
        catches up with its position."""
        with self._lock:
            stream = self._streams.get(task_id)
        if stream is not None:
            with stream["cond"]:
                stream["items"].clear()
                stream["error"] = None
                stream["count"] = None

    @blocking
    def stream_next(self, task_id: str, index: int, timeout: float | None = None):
        """Block until stream item `index` exists; returns its oid hex.
        Raises StopIteration past the end and re-raises task errors."""
        from ray_tpu.exceptions import GetTimeoutError

        with self._lock:
            stream = self._streams.get(task_id)
        if stream is None:
            if index == 0:
                raise StopIteration  # unknown/never-streamed task
            # Mid-iteration loss (state evicted or re-iteration of a
            # consumed stream): an explicit error beats silent truncation.
            raise ObjectLostError(
                f"stream state for task {task_id[:8]} is gone (consumed or evicted)"
            )
        deadline = time.monotonic() + timeout if timeout is not None else None
        complete_since = None  # when count became known with this item missing
        with stream["cond"]:
            while True:
                if index in stream["items"]:
                    return stream["items"][index]
                if stream["error"] is not None:
                    err = serialization.loads(stream["error"])
                    with self._lock:
                        self._drop_stream_locked(task_id)  # single consumption
                    raise err
                if stream["count"] is not None:
                    if index >= stream["count"]:
                        with self._lock:
                            self._drop_stream_locked(task_id)  # exhausted
                        raise StopIteration
                    # Task finished but this item never arrived (its
                    # fire-and-forget delivery was lost): bounded wait, then
                    # a typed error instead of hanging forever.
                    if complete_since is None:
                        complete_since = time.monotonic()
                    elif time.monotonic() - complete_since > 60.0:
                        raise ObjectLostError(
                            f"stream item {index} of task {task_id[:8]} was "
                            "never delivered (producer finished)"
                        )
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise GetTimeoutError(f"stream item {index} of {task_id[:8]} timed out")
                stream["cond"].wait(timeout=min(remaining, 1.0) if remaining else 1.0)

    @loop_only
    def _handle_task_done(self, task_id: str, payload: dict):
        with self._lock:
            pending = self.pending_tasks.get(task_id)
            if pending is None or pending.done_claimed:
                return
        error = payload.get("error")
        if (
            error is not None
            and pending.spec.retry_exceptions
            and pending.retries_left > 0
            and not pending.cancel_requested
            and not payload.get("cancelled")
        ):
            pending.retries_left -= 1
            self._reset_stream_for_retry(task_id)
            # May run on the IO loop (rpc handler) — must not block.
            self._io.spawn(self.raylet.acall("submit_task", {"spec": pending.spec.to_wire()}))
            return
        with self._lock:
            if pending.done_claimed:
                return  # duplicate completion raced us past the first check
            pending.done_claimed = True
            stream = self._streams.get(task_id)
        if stream is not None:
            with stream["cond"]:
                if error is not None:
                    stream["error"] = bytes(error)
                else:
                    stream["count"] = payload.get("stream_count", len(stream["items"]))
                stream["cond"].notify_all()
        with self._lock:
            for result in payload.get("results", []):
                oid, kind, data = result[0], result[1], result[2]
                contained = result[3] if len(result) > 3 else []
                obj = self.owned.setdefault(oid, OwnedObject())
                if contained:
                    obj.contained = contained
                if len(result) > 4 and result[4]:
                    # Device object: result is a descriptor; the holder's
                    # coordinates drive the free-on-last-ref protocol.
                    obj.device = result[4]
                if kind == "inline":
                    self.in_process_store[oid] = {"data": data}
                else:  # plasma
                    obj.in_plasma = True
                    obj.location_hint = data
                if pending.spec.language == "cpp":
                    # Native results are format-"x" by construction — makes
                    # them eligible as ref args of further native tasks.
                    obj.format = "x"
            if error is not None:
                for oid in pending.spec.return_object_ids():
                    self.in_process_store[oid] = {"data": error}
            # Retain lineage for reconstruction.
            self.lineage[task_id] = pending.spec
            while len(self.lineage) > 10_000:
                self.lineage.popitem(last=False)
            # Pop LAST, after results are visible: a getter observing the
            # task gone from pending_tasks must find its results (the old
            # pop-first ordering had a window where a concurrent get() saw
            # neither and misread the object as lost).
            self.pending_tasks.pop(task_id, None)
        if pending.spec.hop_ts or payload.get("hop"):
            self._record_hops(pending, payload)
        self._set_events(pending.spec.return_object_ids())
        if pending.spec.actor_id:
            self._actor_pending[pending.spec.actor_id].discard(task_id)
        self._unpin_args(pending.arg_refs)

    def _record_hops(self, pending: PendingTask, payload: dict):
        """Merge owner-side stamps (kept on the local spec object) with the
        worker-side stamps returned in the completion payload."""
        spec = pending.spec
        rec = {"task_id": spec.task_id, "name": spec.name}
        rec["path"] = (
            "actor" if spec.is_actor_task()
            else ("lease" if pending.via_lease else "classic")
        )
        rec.update(spec.hop_ts)
        rec.update(payload.get("hop") or {})
        rec["owner_done"] = time.monotonic()
        self._hop_log.append(rec)
        submit = rec.get("submit")
        if submit is not None:
            # Sampled dispatch-latency histogram (submit -> completion
            # visible at owner); one observe per sampled task keeps the
            # instrument lock off the unsampled hot path entirely.
            try:
                self._metrics["dispatch_latency"].observe(
                    rec["owner_done"] - submit, tags={"path": rec["path"]}
                )
            except Exception:
                pass
        if len(self._hop_by_task) > 8192:
            self._hop_by_task.clear()
        self._hop_by_task[spec.task_id] = rec

    def hop_records(self) -> list[dict]:
        """Completed-dispatch hop records (config.hop_timing); each maps
        stage name -> monotonic seconds. Consumed by tracing.summarize_hop_records."""
        return list(self._hop_log)

    def drain_hop_records(self) -> list[dict]:
        """hop_records() + clear. Harvest per measurement phase — the ring
        buffer holds 4096 records, so a multi-phase run that only collects
        at the end would have its earliest phase evicted by the later ones."""
        recs = list(self._hop_log)
        self._hop_log.clear()
        self._hop_by_task.clear()
        return recs

    async def rpc_task_failed(self, req):
        """Raylet tells us a worker died mid-task (reference: retry path)."""
        task_id = req["task_id"]
        with self._lock:
            pending = self.pending_tasks.get(task_id)
        if pending is None:
            return {"ok": True}
        if pending.cancel_requested:
            # Worker died while (or because) this task was being cancelled —
            # e.g. force-kill. Surface cancellation, never retry.
            self._fail_task(task_id, self._cancel_error(pending.spec))
            return {"ok": True}
        if req.get("retriable", True) and pending.retries_left > 0:
            pending.retries_left -= 1
            logger.info(
                "task %s failed (%s); retrying (%d left)",
                task_id[:8],
                req.get("message", ""),
                pending.retries_left,
            )
            self._reset_stream_for_retry(pending.spec.task_id)
            await self.raylet.acall("submit_task", {"spec": pending.spec.to_wire()})
        else:
            message = req.get("message", "worker crashed")
            if req.get("error") == "OutOfMemoryError":
                self._fail_task(task_id, OutOfMemoryError(message))
            else:
                self._fail_task(task_id, WorkerCrashedError(message))
        return {"ok": True}

    async def rpc_get_inline(self, req):
        """Serve an owned object to a borrower."""
        oid_hex = req["object_id"]
        with self._lock:
            entry = self.in_process_store.get(oid_hex)
            obj = self.owned.get(oid_hex)
        if entry is not None:
            return {"kind": "inline", "data": entry["data"]}
        if obj is not None and obj.in_plasma:
            return {"kind": "plasma", "location": obj.location_hint}
        task_id = oid_hex[: TaskID.SIZE * 2]
        with self._lock:
            pending = task_id in self.pending_tasks
        if pending and req.get("wait"):
            # Honor the caller's slice bound when it sends one: borrowers
            # long-poll in short re-poll slices (loss healing), and a
            # handler parked past its slice serves a seq nobody awaits.
            bound = min(
                float(req.get("timeout") or self.cfg.worker_lease_timeout_s),
                self.cfg.worker_lease_timeout_s,
            )
            await self._wait_event(oid_hex, bound)
            with self._lock:
                entry = self.in_process_store.get(oid_hex)
                obj = self.owned.get(oid_hex)
            if entry is not None:
                return {"kind": "inline", "data": entry["data"]}
            if obj is not None and obj.in_plasma:
                return {"kind": "plasma", "location": obj.location_hint}
        return {"kind": "missing"}

    # ---- device object plane (experimental/device_object/) ----

    async def rpc_devobj_pull(self, req):
        """Consumer asks the holder for a device object's payload. Decides
        the transfer in one round trip: a shared collective group (named by
        the consumer) kicks off a p2p send the consumer recv()s; otherwise
        small arrays ship inline and large ones are sealed into this node's
        arena under the same object id for the store pull path."""
        mgr = self._device_objects
        oid = req["object_id"]
        entry = mgr.entry(oid) if mgr is not None else None
        if entry is None:
            return {"kind": "missing"}
        loop = asyncio.get_event_loop()
        dkey = req.get("direct_key")
        if dkey is not None:
            # Direct-mailbox reply (serve.llm KV handoff / prefix tier): the
            # consumer named its own inbox key in the request, so ONE round
            # trip decides the transfer and the payload streams straight to
            # its p2p inbox — no group membership, no store seal, no arena
            # copy. Serialization runs off-loop; the entry may be freed
            # concurrently (LRU eviction racing an import), in which case
            # host_bytes reads None and the consumer gets a typed miss —
            # never a torn payload.
            data = await loop.run_in_executor(None, mgr.host_bytes, oid)
            if data is None:
                return {"kind": "missing"}
            from ray_tpu.util.collective.p2p import direct_send

            direct_send(self, tuple(req["direct_addr"]), dkey, data)
            return {"kind": "direct", "nbytes": len(data)}
        group = req.get("group")
        if group is not None and entry.meta.transport == "collective":
            from ray_tpu.util.collective import get_group, is_group_initialized

            if is_group_initialized(group):
                src_rank = get_group(group).rank
                # Send on an executor thread: serialization + the mailbox
                # round trips must not stall this process's IO loop.
                loop.run_in_executor(
                    None, mgr.send_via_group, oid, group, req["dst_rank"], req["tag"]
                )
                return {"kind": "collective", "group": group, "src_rank": src_rank}
        # Spilled entries already have an arena copy under this oid: point
        # the consumer at the store instead of restoring device-side just to
        # re-serialize (the restore would also re-pin memory that pressure
        # evicted).
        if (
            entry.array is not None
            and entry.meta.nbytes <= self.cfg.max_direct_call_object_size
        ):
            data = await loop.run_in_executor(None, mgr.host_bytes, oid)
            if data is not None:
                return {"kind": "inline", "data": data}
        ok = await loop.run_in_executor(None, mgr.materialize_to_store, oid)
        if ok:
            return {"kind": "plasma", "location": self.node_id}
        return {"kind": "missing"}

    async def rpc_devobj_free(self, req):
        """Owner's last ref dropped: release the device buffers here."""
        mgr = self._device_objects
        if mgr is not None:
            mgr.free(req["object_id"])
        return {"ok": True}

    async def rpc_devobj_release(self, req):
        """A channel-payload consumer resolved its descriptor slot: drop
        one pin; the last pin frees (device_envelope.release)."""
        mgr = self._device_objects
        if mgr is not None:
            mgr.release_pin(req["object_id"])
        return {"ok": True}

    async def rpc_p2p_data(self, req):
        """Direct-mailbox payload chunk (one-way): an eager-pushed channel
        payload or any address-directed p2p transfer lands here for a
        blocked direct_recv to take. A channel payload's deposit doubles as
        the channel doorbell (the producer skipped the separate wakeup
        frame: the payload lands right after the slot publish, so ONE frame
        both delivers the bytes and wakes the blocked reader)."""
        key = req["key"]
        if key.startswith("collred/"):
            # Tree-reduce partials: consumed chunk-at-a-time by a combiner
            # on an executor thread — never reassembled, so they land in
            # the stream pads instead of the inbox.
            self.p2p_streams.deposit(key, req.get("idx", 0), req["data"])
            return {"ok": True}
        done = self.p2p_inbox.deposit(
            key, req.get("idx", 0), req.get("total", 1), req["data"]
        )
        if req.get("relay"):
            # Mid-tree member of a tree broadcast: forward this chunk to
            # our own children the moment the contiguous prefix reaches it
            # (cut-through; the inbox keeps its copy for the local take).
            self.p2p_relays.feed(
                self, key, req.get("idx", 0), req.get("total", 1),
                req["data"], req["relay"],
            )
        if done and key.startswith("chdev/"):
            self.channels.ring_doorbell(key.split("/", 2)[1])
        return {"ok": True}

    async def rpc_p2p_ack(self, req):
        """Delivery receipt for a direct-mailbox payload: True once every
        chunk of ``key`` has landed (including already-taken payloads — the
        tombstone remembers). The group-broadcast fan-out acalls this after
        its chunk pushes, turning the one-way frames into a confirmed
        delivery and a dead member into a NAMED failure."""
        timeout = min(float(req.get("timeout", 2.0)), 30.0)
        if self.p2p_inbox.completed(req["key"]):
            return {"ok": True}
        loop = asyncio.get_event_loop()
        ok = await loop.run_in_executor(
            None, self.p2p_inbox.wait_complete, req["key"], timeout
        )
        return {"ok": bool(ok)}

    async def rpc_devobj_broadcast(self, req):
        """Driver asks this HOLDER to fan a device object out: with a
        ``group`` (one this process initialized), one group operation
        delivers to every member's direct mailbox
        (manager.broadcast_via_group); without one, materialize the host
        copy into this node's arena so the caller can relay it cluster-wide
        over the cut-through push tree (the cross-node fallback)."""
        mgr = self._device_objects
        oid = req["object_id"]
        entry = mgr.entry(oid) if mgr is not None else None
        if entry is None:
            return {"kind": "missing"}
        loop = asyncio.get_event_loop()
        group = req.get("group")
        if group is not None:
            from ray_tpu.util.collective import is_group_initialized

            if not is_group_initialized(group):
                return {
                    "kind": "error",
                    "error": f"holder has no collective group {group!r}",
                }
            try:
                result = await loop.run_in_executor(
                    None, mgr.broadcast_via_group, oid, group,
                    float(req.get("timeout", 30.0)),
                )
            except KeyError:
                return {"kind": "missing"}
            return {"kind": "collective", **result}
        ok = await loop.run_in_executor(None, mgr.materialize_to_store, oid)
        if ok:
            return {"kind": "plasma", "location": self.node_id}
        return {"kind": "missing"}

    async def rpc_devobj_reduce(self, req):
        """One HOLDER's share of a device-object group reduce/allreduce:
        feed the resident array into the tree combine on an executor
        thread (chunk waits + elementwise math must not stall the IO
        loop). The gang is concurrent by construction — the driver
        dispatches every holder's RPC in parallel and each holder blocks
        in the collective until its children/parent move."""
        mgr = self._device_objects
        oid = req["object_id"]
        entry = mgr.entry(oid) if mgr is not None else None
        if entry is None:
            return {"kind": "missing"}
        group = req.get("group")
        from ray_tpu.util.collective import is_group_initialized

        if group is None or not is_group_initialized(group):
            return {
                "kind": "error",
                "error": f"holder has no collective group {group!r}",
            }
        loop = asyncio.get_event_loop()
        try:
            result = await loop.run_in_executor(
                None, mgr.reduce_via_group, oid, group,
                req.get("mode", "allreduce"), req.get("op", "SUM"),
                int(req.get("dst_rank", 0)), req["tag"],
                float(req.get("timeout", 60.0)),
            )
        except KeyError:
            return {"kind": "missing"}
        except Exception as e:
            # The collective itself failed (timeout naming a silent child,
            # shape disagreement, ...): the object is intact — answer with
            # the error instead of severing the connection.
            return {"kind": "error", "error": repr(e)}
        return {"kind": "collective", **result}

    async def rpc_devobj_stats(self, req):
        from ray_tpu.experimental.device_object.manager import device_object_stats

        return device_object_stats()

    # ---- compiled-graph channel plane (experimental/channel/) ----

    async def rpc_channel_doorbell(self, req):
        """One-way producer wakeup: the reader blocked on this channel
        re-checks its ring/side-channel now instead of at the next poll."""
        self.channels.ring_doorbell(req["cid"])
        return {"ok": True}

    async def rpc_channel_data(self, req):
        """Side-channel envelope chunk (oversize payloads and the cross-node
        fallback ride this, chunked like the object push path)."""
        gate = self.channels.gate_if_live(req["cid"])
        if gate is None or gate.closed:
            return {"ok": False, "closed": True}
        gate.add_chunk(req["seq"], req["idx"], req["total"], req["data"])
        return {"ok": True}

    async def rpc_channel_query(self, req):
        """Remote-mode backpressure probe: the producer bounds its in-flight
        envelopes by the reader's queue depth."""
        gate = self.channels.gate_if_live(req["cid"])
        if gate is None:
            return {"queued": 0, "closed": True}
        return {"queued": gate.queued(), "closed": gate.closed}

    async def rpc_channel_poison(self, req):
        """Plant a sticky error envelope (actor death propagation): every
        subsequent read on this channel returns the typed error."""
        gate = self.channels.gate_if_live(req["cid"])
        if gate is not None:
            gate.poison(req["env"])
            flight_recorder.record("channel_poison", req["cid"][:12])
        return {"ok": True}

    async def rpc_channel_close(self, req):
        """Teardown: blocked readers raise ChannelClosedError promptly."""
        gate = self.channels.gate_if_live(req["cid"])
        if gate is not None:
            gate.close()
            flight_recorder.record("channel_close", req["cid"][:12])
        return {"ok": True}

    @any_thread
    def record_compiled_hop(self, rec: dict):
        """Append a compiled-iteration hop record (path='compiled'); read by
        tracing.summarize_hop_records like every other dispatch path."""
        self._hop_log.append(rec)
        submit, wake = rec.get("submit"), rec.get("wake")
        if submit is not None and wake is not None:
            try:
                self._metrics["dispatch_latency"].observe(
                    wake - submit, tags={"path": "compiled"}
                )
            except Exception:
                pass

    async def rpc_chaos_set_plan(self, req):
        """Runtime chaos-plan install/clear for this process (chaos.py) —
        how a test severs or degrades a WORKER's wire mid-workload (the
        raylet's handler fans out to workers with broadcast=True)."""
        from ray_tpu._private import chaos

        plan = req.get("plan")
        if plan is None:
            chaos.clear()
        else:
            # Remote install path: kill rules are armed — the pusher chose
            # THIS process as the crash victim.
            chaos.install(plan, seed=req.get("seed"), allow_kill=True)
        return {"ok": True}

    async def rpc_debug_dump(self, req):
        """This process's flight-recorder ring (the raylet's debug_dump
        aggregates node-wide, including rings of already-dead processes)."""
        proc = flight_recorder.dump()
        return {"processes": [proc] if proc is not None else []}

    async def rpc_pubsub(self, req):
        """GCS pubsub push (driver: worker_logs echo)."""
        if req.get("channel") == "worker_logs" and self.log_to_driver:
            from ray_tpu._private.log_monitor import print_worker_logs

            print_worker_logs(req.get("message") or {}, self.job_id.hex())
        return {"ok": True}

    async def rpc_incref(self, req):
        with self._lock:
            self.owned.setdefault(req["object_id"], OwnedObject()).ref_count += 1
        return {"ok": True}

    async def rpc_decref(self, req):
        oid = req["object_id"]
        with self._lock:
            obj = self.owned.get(oid)
            if obj is not None:
                obj.ref_count -= 1
                self._maybe_free_locked(oid, obj)
        return {"ok": True}

    def register_ref(self, ref):
        oid = ref.hex()
        if ref.owner_addr is None or tuple(ref.owner_addr) == tuple(self.address):
            with self._lock:
                self.owned.setdefault(oid, OwnedObject()).ref_count += 1
        else:
            self._push_to_owner(ref, "incref")

    def deregister_ref(self, ref):
        if self._shutdown:
            return
        oid = ref.hex()
        if ref.owner_addr is None or tuple(ref.owner_addr) == tuple(self.address):
            with self._lock:
                obj = self.owned.get(oid)
                if obj is not None:
                    obj.ref_count -= 1
                    self._maybe_free_locked(oid, obj)
        else:
            self._push_to_owner(ref, "decref")

    def _incref_contained(self, refs) -> list:
        """Incref nested refs on behalf of a containing object; returns the
        (id, owner) list to store on the container's OwnedObject."""
        contained = []
        for ref in refs or []:
            owner = tuple(ref.owner_addr) if ref.owner_addr else tuple(self.address)
            contained.append((ref.hex(), list(owner)))
            if owner == tuple(self.address):
                with self._lock:
                    self.owned.setdefault(ref.hex(), OwnedObject()).ref_count += 1
            else:
                self._push_to_owner(ref, "incref")
        return contained

    def _decref_contained(self, contained: list):
        from ray_tpu.object_ref import ObjectRef as _Ref

        for cid, owner in contained:
            if tuple(owner) == tuple(self.address):
                with self._lock:
                    obj = self.owned.get(cid)
                    if obj is not None:
                        obj.ref_count -= 1
                        self._maybe_free_locked(cid, obj)
            else:
                self._push_to_owner(_Ref(ObjectID.from_hex(cid), owner, _register=False), "decref")

    def _maybe_free_locked(self, oid: str, obj: OwnedObject):
        """Free the object once all refs + pins are gone. Caller holds _lock."""
        if obj.ref_count > 0 or obj.pinned > 0:
            return
        task_id = oid[: TaskID.SIZE * 2]
        if task_id in self.pending_tasks:
            return
        self.in_process_store.pop(oid, None)
        self.owned.pop(oid, None)
        self._object_events.pop(oid, None)
        if obj.device is not None:
            dev, obj.device = obj.device, None
            # Release the holder's device buffers (and any spilled copy).
            # Async push / manager-internal lock only — we hold self._lock.
            self._free_device_object(oid, dev)
        if obj.contained:
            contained, obj.contained = obj.contained, []
            # Decref outside any recursion concerns via the same thread; the
            # inner call re-takes the lock per entry.
            self._io.loop.call_soon_threadsafe(self._decref_contained, contained)
        if obj.in_plasma:
            async def _free():
                try:
                    await self.raylet.acall("free_object", {"object_id": oid})
                except Exception:
                    pass

            self._io.spawn(_free())

    # ==================================================================
    # Execution side (worker mode; reference: core_worker.cc:2512 loop)
    # ==================================================================

    def _load_function(self, key: str):
        fn = self._function_cache.get(key)
        if fn is None and key.startswith("cpp!"):
            # Self-describing native function key — no GCS table entry.
            # Python-worker fallback for cpp tasks (e.g. the C++ worker
            # binary failed to build): same C ABI via ctypes.
            from ray_tpu.cross_language import CppFunctionInvoker

            library, symbol = key[4:].rsplit("!", 1)
            fn = CppFunctionInvoker(library, symbol)
            self._function_cache[key] = fn
        if fn is None:
            resp = self.gcs.call("kv_get", {"key": key}, timeout=15)
            if not resp.get("found"):
                raise RuntimeError(f"function {key} not in GCS function table")
            fn = cloudpickle.loads(resp["value"])
            self._function_cache[key] = fn
        return fn

    def _known_xlang_object(self, oid_hex: str) -> bool:
        """True iff this worker can PROVE the object is format-"x" (owned
        with a recorded format, or in-process with a parseable header)."""
        with self._lock:
            obj = self.owned.get(oid_hex)
            entry = self.in_process_store.get(oid_hex)
        if obj is not None and obj.format == "x":
            return True
        if entry is not None:
            return serialization.peek_format(entry["data"]) == "x"
        return False

    def _resolve_args(self, wire_args: list):
        from ray_tpu.object_ref import ObjectRef

        args = []
        kwargs = {}
        for arg in wire_args:
            if arg[0] == "r":
                ref = ObjectRef(ObjectID.from_hex(arg[1]), tuple(arg[2]))
                value = self.get(ref)
            else:
                value = serialization.deserialize(arg[1])
            if isinstance(value, tuple) and len(value) == 2 and value[0] == "__kwargs__":
                kwargs = value[1]
            else:
                args.append(value)
        return args, kwargs

    def _package_one(self, spec: TaskSpec, value, index: int) -> list:
        """Package a single indexed return (shared by fixed and streaming)."""
        from ray_tpu._private.ids import ObjectID, TaskID

        oid = ObjectID.for_return(TaskID.from_hex(spec.task_id), index).hex()
        if self._tensor_transport and spec.is_actor_task() and _maybe_jax_array(value):
            # Device object plane: the array never leaves this actor's
            # devices; the owner gets a descriptor + holder coordinates.
            return self._package_device(oid, value)
        ser = serialization.serialize(value)
        contained = self._incref_contained(ser.contained_refs)
        if ser.total_size > self.cfg.max_direct_call_object_size:
            self.store.put_serialized(oid, ser)
            return [oid, "plasma", self.node_id, contained]
        return [oid, "inline", ser.to_bytes(), contained]

    def _package_results(self, spec: TaskSpec, values: list) -> list:
        """Serialize return values; small inline, large to plasma. Refs
        nested in a result are incref'd here on the result's behalf and
        shipped so the caller (the result's owner) holds them until the
        result itself is freed (reference: nested-ref borrow handoff)."""
        return [self._package_one(spec, value, i) for i, value in enumerate(values)]

    def execute_task(self, spec: TaskSpec) -> dict:
        """Run one task; returns the task_done payload."""
        if (
            self.mode == WORKER
            and spec.task_type == NORMAL_TASK
            and (spec.job_id, spec.name) != self._log_attr_name
        ):
            # In-band log attribution for the driver's log pipeline: leased
            # tasks never pass through the raylet, so the "(name pid=...)"
            # prefix source must travel with the stdout stream itself
            # (log_monitor.py parses and strips this control line). Keyed by
            # (job, name): a reused worker crossing jobs must re-announce
            # even when the task name repeats.
            self._log_attr_name = (spec.job_id, spec.name)
            print(f"\x01attr:{spec.job_id}:{spec.name}", flush=True)
        if spec.task_id in self._cancelled_tasks:
            # Cancelled before execution started (cancel raced delivery).
            self._cancelled_tasks.discard(spec.task_id)
            self.record_task_event(spec, "CANCELLED")
            return self.cancelled_payload(spec)
        ctx = (TaskID.from_hex(spec.task_id), spec)
        token = _exec_ctx.set(ctx)
        on_main = threading.get_ident() == self._main_thread_ident
        if on_main:
            # Single writer (the main thread itself); read lock-free by the
            # SIGUSR2 cancel handler to decide whether to raise.
            self._main_task_id = spec.task_id
        with self._active_exec_lock:
            self._active_exec_seq += 1
            exec_key = self._active_exec_seq
            # Thread ident rides along so cancellation can interrupt the
            # executing thread (interrupt_running_task).
            self._active_exec[exec_key] = (ctx[0], ctx[1], threading.get_ident())
        from ray_tpu.util import tracing

        trace_token = tracing.set_task_context(spec.trace_ctx)
        start = time.time()
        if spec.hop_ts:
            spec.hop_ts["exec_start"] = time.monotonic()
        task_tag = f"{spec.name}:{spec.task_id[:8]}"  # shared by exec/done/fail events
        flight_recorder.record("task_exec", task_tag)
        self.record_task_event(spec, "RUNNING", start_ts=start)
        try:
            if spec.is_actor_task():
                fn = getattr(self._actor_instance, spec.method_name)
            else:
                fn = self._load_function(spec.function_key)
            args, kwargs = self._resolve_args(spec.args)
            if spec.is_actor_creation():
                instance = fn(*args, **kwargs)
                self._actor_instance = instance
                self._actor_id = spec.actor_id
                self._actor_creation_spec = spec
                self._tensor_transport = spec.tensor_transport
                values = []
            else:
                out = fn(*args, **kwargs)
                import inspect as _inspect

                # inspect.iscoroutine, NOT asyncio.iscoroutine: on
                # Python <= 3.10 the latter also matches plain generators
                # (legacy generator-based coroutines), which would route
                # num_returns="streaming" generators into the async-actor
                # loop and blow up on `await <generator>`.
                if _inspect.iscoroutine(out):
                    out = self._run_actor_coroutine(out)
                if spec.is_streaming():
                    if not _inspect.isgenerator(out) and not hasattr(out, "__iter__"):
                        raise TypeError(
                            f"num_returns='streaming' task {spec.name} must "
                            f"return a generator/iterable, got {type(out).__name__}"
                        )
                    # Each yielded value ships to the owner AS PRODUCED — the
                    # caller iterates while this task is still running
                    # (reference: StreamingObjectRefGenerator). Sends are
                    # pipelined (fire-and-forget on the IO loop) so producer
                    # throughput isn't one item per network round trip; the
                    # final task_done travels the same client/connection, so
                    # it serializes after every item write.
                    owner = self._owner_client(tuple(spec.owner_addr))
                    n = 0

                    def _log_lost(fut, idx):
                        exc = fut.exception()
                        if exc is not None:
                            logger.warning(
                                "stream item %d of %s failed to deliver: %r",
                                idx, spec.task_id[:8], exc,
                            )

                    for value in out:
                        item = self._package_one(spec, value, n)
                        fut = self._io.spawn(owner.acall(
                            "stream_item",
                            {"task_id": spec.task_id, "index": n, "result": item},
                        ))
                        fut.add_done_callback(lambda f, i=n: _log_lost(f, i))
                        n += 1
                    values = []
                    stream_count = n
                elif spec.num_returns == 0:
                    values = []
                elif spec.num_returns == 1:
                    values = [out]
                else:
                    values = list(out)
                    if len(values) != spec.num_returns:
                        raise ValueError(
                            f"task {spec.name} declared num_returns={spec.num_returns} "
                            f"but returned {len(values)} values"
                        )
            results = self._package_results(spec, values)
            payload = {"task_id": spec.task_id, "results": results, "error": None}
            if spec.is_streaming() and not spec.is_actor_creation():
                payload["stream_count"] = stream_count
            self._done_event_ctr += 1
            if self._done_event_ctr & 63 == 0:
                flight_recorder.record(
                    "task_done", f"{task_tag}:n={self._done_event_ctr}"
                )
            self.record_task_event(spec, "FINISHED", start_ts=start, end_ts=time.time())
        except BaseException as e:  # noqa: BLE001 — errors ship to the caller
            # CANCELLED only when THIS task was the target of a cancel
            # (interrupt_running_task tombstones before firing). A bare
            # isinstance check would also swallow a stray late async-exc
            # aimed at a previous task on this thread, or user code
            # re-raising a child's TaskCancelledError — both of those are
            # ordinary task failures (retries still apply).
            cancelled = spec.task_id in self._cancelled_tasks
            if cancelled:
                # Interrupted by cancel (or raised it itself): ship the bare
                # TaskCancelledError — owners must not retry it.
                self._cancelled_tasks.discard(spec.task_id)
                payload = self.cancelled_payload(spec)
                self.record_task_event(
                    spec, "CANCELLED", start_ts=start, end_ts=time.time()
                )
            else:
                logger.debug("task %s raised", spec.name, exc_info=True)
                flight_recorder.record(
                    "task_fail", f"{task_tag}:{type(e).__name__}"
                )
                err = TaskError.from_exception(e, task_name=spec.name)
                payload = {
                    "task_id": spec.task_id,
                    "results": [],
                    "error": serialization.serialize(err).to_bytes(),
                }
                self.record_task_event(
                    spec, "FAILED", start_ts=start, end_ts=time.time(), error_type=type(e).__name__
                )
        finally:
            # A late cancel (SIGUSR2 handler raise, or the async-exc landing
            # after the body already exited) can fire INSIDE this finally and
            # would skip the remaining statements, leaking the _active_exec
            # entry and the context tokens. Each step is idempotent-guarded,
            # so retrying until all have run is safe; the pending cancel
            # exception is consumed by the first retry (the SIGUSR2 handler
            # won't re-raise once _main_task_id clears, and an async-exc is
            # delivered at most once).
            while True:
                try:
                    if on_main:
                        self._main_task_id = None
                    if token is not None:
                        _exec_ctx.reset(token)
                        token = None
                    if trace_token is not None:
                        tracing.reset_task_context(trace_token)
                        trace_token = None
                    with self._active_exec_lock:
                        self._active_exec.pop(exec_key, None)
                    break
                except BaseException:  # noqa: BLE001 — late cancel mid-cleanup
                    continue
        payload["duration_s"] = time.time() - start
        if spec.hop_ts:
            # Worker-side stamps travel back in the completion payload; the
            # transport layer adds its "reply" stamp as the payload leaves.
            spec.hop_ts["exec_end"] = time.monotonic()
            payload["hop"] = dict(spec.hop_ts)
        return payload

    def _run_actor_coroutine(self, coro):
        """Async actor methods run on a dedicated per-actor event loop."""
        if self._actor_async_loop is None:
            loop = asyncio.new_event_loop()
            t = threading.Thread(target=loop.run_forever, name="actor-async", daemon=True)
            t.start()
            self._actor_async_loop = loop
        # Propagate the task context onto the loop thread: each asyncio Task
        # runs in its own contextvars Context, so setting inside the wrapper
        # is task-local even when coroutines interleave on the shared loop.
        ctx = _exec_ctx.get()
        spec = ctx[1] if ctx is not None else None

        async def _with_ctx():
            if ctx is not None:
                _exec_ctx.set(ctx)
            if spec is not None and spec.trace_ctx:
                from ray_tpu.util import tracing

                tracing.set_task_context(spec.trace_ctx)
            return await coro

        return asyncio.run_coroutine_threadsafe(_with_ctx(), self._actor_async_loop).result()

    # ---- shutdown ----

    def shutdown(self, job_state: str | None = None):
        self._shutdown = True
        if self._lost_sweep_task is not None:
            self._lost_sweep_task.cancel()
            self._lost_sweep_task = None
        for c in list(self._sweep_clients.values()):
            c.close()
        self._sweep_clients.clear()
        if self._lease_mgr is not None:
            try:
                self._lease_mgr.close()
            except Exception:
                pass
        try:
            self.flush_task_events()
        except Exception:
            pass
        # Final metrics window must not vanish with the process: the periodic
        # flusher runs every metrics_flush_interval_s, and this GCS client is
        # about to close.
        try:
            from ray_tpu.util.metrics import flush_metrics

            flush_metrics(self)
        except Exception:
            pass
        flight_recorder.record("exit", self.mode)
        if self.mode == DRIVER:
            from ray_tpu._private.usage_stats import write_usage_stats

            write_usage_stats(self)
            if job_state is None:
                job_state = "SUCCEEDED"
            try:
                self.gcs.call(
                    "mark_job_finished",
                    {"job_id": self.job_id.hex(), "state": job_state},
                )
            except Exception:
                pass
        for c in list(self._actor_clients.values()):
            c.close()
        for c in list(self._owner_client_cache.values()):
            c.close()
        for c in list(self._devobj_clients.values()):
            c.close()
        self.server.stop()
        self.store.close()
        self.gcs.close()
        self.raylet.close()
        self._executor.shutdown(wait=False)


_MISSING = object()
