"""Plain-int counters for the node-to-node transfer plane.

Same discipline as ``rpc._WireStats``: every writer runs on the one IO loop
thread, so bare ``+=`` is race-free; the flush-time collector
(``self_metrics._collect_transfer_stats``) folds them into the
``ray_tpu_transfer_*`` instruments — an instrument lock per chunk would tax
the multi-MiB/s chunk stream exactly where it hurts.
"""

from __future__ import annotations


class _TransferStats:
    __slots__ = (
        "pushes",            # outbound pushes committed
        "pulls",             # pulls sealed locally
        "relays",            # cut-through relays completed (forward pre-seal)
        "bytes_out",         # chunk payload bytes sent (push + fetch responses)
        "bytes_in",          # chunk payload bytes received (pull + push sessions)
        "chunks_raw_out",    # chunks sent as raw frames
        "chunks_msgpack_out",  # chunks sent on the msgpack fallback
        "chunks_raw_in",     # chunks received as raw frames
        "chunks_msgpack_in",   # chunks received via msgpack
        "pull_sources",      # source replicas that served >=1 chunk of a pull
        "admission_stalls",  # pulls that queued on the byte budget
        "source_demotions",  # pull sources demoted after an error
    )

    def __init__(self):
        for name in self.__slots__:
            setattr(self, name, 0)


TRANSFER = _TransferStats()
