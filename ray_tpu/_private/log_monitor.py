"""Log pipeline: per-worker log files → GCS pubsub → driver stdout.

Analog of the reference's LogMonitor (python/ray/_private/log_monitor.py:102)
+ the driver-side print redirection (_private/worker.py print_logs): the
raylet tails every worker's stdout/stderr file and publishes new lines on the
``worker_logs`` channel; each driver subscribes and echoes lines belonging to
its job, prefixed ``({name} pid=..., node=...)`` like the reference.
"""

from __future__ import annotations

import asyncio
import glob
import logging
import os

logger = logging.getLogger(__name__)

MAX_LINES_PER_TICK = 200
MAX_LINE_LEN = 20_000


class LogMonitor:
    """Raylet-side tailer. Runs as an asyncio task on the raylet loop."""

    def __init__(self, raylet):
        self.raylet = raylet
        self.log_dir = os.path.join(raylet.session_dir, "logs")
        # path -> read offset
        self._offsets: dict[str, int] = {}

    @staticmethod
    def _read_chunk(path: str, offset: int, length: int):
        """Executor-side file read (None when the file vanished mid-tick)."""
        try:
            with open(path, "rb") as f:
                f.seek(offset)
                return f.read(length)
        except OSError:
            return None

    def _worker_for(self, path: str):
        """Map worker-<wid8>.out/.err to the raylet's worker handle."""
        base = os.path.basename(path)
        if not base.startswith("worker-"):
            return None
        wid8 = base[len("worker-") :].split(".")[0]
        for wid, w in self.raylet.workers.items():
            if wid.startswith(wid8):
                return w
        return None

    async def run(self):
        while True:
            try:
                await self._tick()
            except asyncio.CancelledError:
                return
            except Exception:
                logger.debug("log monitor tick failed", exc_info=True)
            await asyncio.sleep(0.3)

    async def _tick(self):
        for path in glob.glob(os.path.join(self.log_dir, "worker-*.out")) + glob.glob(
            os.path.join(self.log_dir, "worker-*.err")
        ):
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            # Attribution snapshot BEFORE reading: lines already in the file
            # were written under the job active up to now; a task dispatched
            # mid-tick must not claim them.
            worker = self._worker_for(path)
            offset = self._offsets.get(path, 0)
            if size <= offset:
                continue
            # Off-loop read: up to 1 MiB of file IO per path per tick would
            # otherwise stall every RPC on the raylet's loop (graftlint:
            # blocking/file-io-in-async).
            chunk = await asyncio.get_event_loop().run_in_executor(
                None, self._read_chunk, path, offset, min(size - offset, 1 << 20)
            )
            if chunk is None:
                continue
            # Only consume complete lines; partial tail re-read next tick.
            last_nl = chunk.rfind(b"\n")
            if last_nl < 0:
                if len(chunk) < MAX_LINE_LEN:
                    continue
                last_nl = len(chunk) - 1
            raw_lines = chunk[: last_nl + 1].splitlines(keepends=True)
            if len(raw_lines) > MAX_LINES_PER_TICK:
                # Publish a bounded batch and only advance the offset past
                # what was published — the rest is re-read next tick, never
                # silently dropped.
                raw_lines = raw_lines[:MAX_LINES_PER_TICK]
                consumed = sum(len(l) for l in raw_lines)
            else:
                consumed = last_nl + 1
            self._offsets[path] = offset + consumed
            lines = [l.decode(errors="replace").rstrip("\r\n")[:MAX_LINE_LEN] for l in raw_lines]
            if not lines:
                continue
            # Leased workers execute tasks the raylet never sees
            # individually, so attribution rides IN-BAND: the worker emits
            # "\x01attr:<job>:<task-name>" when its current task changes
            # (core_worker.execute_task) and the batch splits there.
            cur_name = getattr(worker, "last_task_name", None) if worker else None
            cur_job = getattr(worker, "last_job_id", None) if worker else None
            segments: list = []
            cur: list = []
            for line in lines:
                if line.startswith("\x01attr:"):
                    if cur:
                        segments.append((cur, cur_name, cur_job))
                        cur = []
                    parts = line[len("\x01attr:"):].split(":", 1)
                    if len(parts) == 2:
                        cur_job, cur_name = parts[0] or cur_job, parts[1]
                        if worker is not None:
                            worker.last_job_id = cur_job
                            worker.last_task_name = cur_name
                    continue
                cur.append(line)
            if cur:
                segments.append((cur, cur_name, cur_job))
            for seg_lines, name, job in segments:
                message = {
                    "lines": seg_lines,
                    "is_err": path.endswith(".err"),
                    "pid": worker.pid if worker else 0,
                    "node_id": self.raylet.node_id,
                    "job_id": job,
                    "name": name,
                }
                try:
                    await self.raylet.gcs.acall(
                        "publish", {"channel": "worker_logs", "message": message}
                    )
                except Exception:
                    pass


def print_worker_logs(message: dict, own_job_id: str):
    """Driver-side: echo a worker_logs message if it belongs to this job."""
    import sys

    job = message.get("job_id")
    if job is not None and job != own_job_id:
        return
    if job is None and not message.get("is_err"):
        # Unattributed stdout (e.g. prestarted worker chatter) would leak to
        # every driver; only unattributed STDERR (startup crashes) fans out.
        return
    name = message.get("name") or "worker"
    prefix = f"({name} pid={message.get('pid')}, node={str(message.get('node_id'))[:8]})"
    stream = sys.stderr if message.get("is_err") else sys.stdout
    for line in message.get("lines", []):
        print(f"{prefix} {line}", file=stream)
    try:
        stream.flush()
    except Exception:
        pass
