"""GlobalState — cluster introspection backed by the GCS.

TPU-native analog of the reference's ``python/ray/_private/state.py``
(GlobalStateAccessor-backed): node/actor/placement-group/job tables, the
task-event log, and the Chrome-trace timeline dump
(reference: state.py:416 ``chrome_tracing_dump``, API ``timeline()``).
"""

from __future__ import annotations

import json

from ray_tpu._private import worker_context
from ray_tpu._private.rpc import RpcClient


class GlobalState:
    """Reads cluster state over GCS RPCs. Usable from a connected driver
    (default) or standalone against an explicit GCS address."""

    def __init__(self, gcs_address=None):
        if gcs_address is not None:
            if isinstance(gcs_address, str):
                host, port = gcs_address.rsplit(":", 1)
                gcs_address = (host, int(port))
            self._gcs = RpcClient(tuple(gcs_address), label="state-gcs")
            self._owns_client = True
        else:
            self._gcs = worker_context.get_core_worker().gcs
            self._owns_client = False

    # ---- tables ----

    def nodes(self) -> list[dict]:
        resp = self._gcs.call("get_nodes")
        return list(resp["nodes"].values())

    def actors(self) -> list[dict]:
        return self._gcs.call("list_actors").get("actors", [])

    def device_objects(self) -> list[dict]:
        """Cluster-wide device-resident objects (experimental/device_object/):
        every holder registers a best-effort ``devobj/<oid>`` KV row at
        create and deletes it on free."""
        keys = self._gcs.call("kv_keys", {"prefix": "devobj/"}).get("keys", [])
        rows = []
        for key in keys:
            resp = self._gcs.call("kv_get", {"key": key})
            if not resp.get("found"):
                continue
            try:
                value = resp["value"]
                rows.append(json.loads(value if isinstance(value, str) else value.decode()))
            except Exception:
                continue
        return rows

    def placement_groups(self) -> list[dict]:
        return self._gcs.call("list_placement_groups").get("placement_groups", [])

    def jobs(self) -> list[dict]:
        return self._gcs.call("list_jobs").get("jobs", [])

    def task_events(self, limit: int = 10_000) -> list[dict]:
        return self._gcs.call("get_task_events", {"limit": limit}).get("events", [])

    def node_state(self, node: dict) -> dict:
        """Live per-raylet state (resources, workers, store usage)."""
        client = RpcClient(tuple(node["address"]), label="state-raylet")
        try:
            return client.call("get_state")
        finally:
            client.close()

    def cluster_resources(self) -> dict:
        total: dict[str, float] = {}
        for node in self.nodes():
            if node.get("state") != "ALIVE":
                continue
            for k, v in (node.get("resources_total") or {}).items():
                total[k] = total.get(k, 0) + v
        return total

    def available_resources(self) -> dict:
        avail: dict[str, float] = {}
        for node in self.nodes():
            if node.get("state") != "ALIVE":
                continue
            for k, v in (node.get("resources_available") or {}).items():
                avail[k] = avail.get(k, 0) + v
        return avail

    # ---- flight recorder ----

    def flight_recorder_dump(self) -> list[dict]:
        """Cluster-wide flight-recorder collection: every alive raylet's
        ``debug_dump`` returns all rings on its node (scanned from the mmap
        files, so SIGKILLed processes' final events are included), merged
        into one stream ordered by stamp."""
        from ray_tpu._private.flight_recorder import merge_events

        processes: list[dict] = []
        seen: set = set()
        for node in self.nodes():
            if node.get("state") != "ALIVE" or not node.get("address"):
                continue
            client = RpcClient(tuple(node["address"]), label="debug-raylet")
            try:
                resp = client.call("debug_dump", {}, timeout=10)
                for proc in resp.get("processes", []):
                    # Same-host clusters (cluster_utils.Cluster) share one
                    # session dir across raylets, so every raylet's scan
                    # returns every ring — dedupe by process identity or an
                    # N-raylet cluster reports each event N times.
                    key = (proc.get("pid"), proc.get("role"), proc.get("ident"))
                    if key in seen:
                        continue
                    seen.add(key)
                    proc["node_id"] = resp.get("node_id")
                    processes.append(proc)
            except Exception:
                continue
            finally:
                client.close()
        return merge_events(processes)

    # ---- timeline ----

    def chrome_tracing_dump(
        self,
        filename: str | None = None,
        flight_events: list[dict] | None = None,
        hop_records: list[dict] | None = None,
    ) -> list[dict]:
        """Convert the GCS task-event log into Chrome trace-event JSON
        (open in chrome://tracing or Perfetto). ``flight_events`` (from
        flight_recorder_dump) render as instant events per process/role;
        ``hop_records`` render as per-stage slices plus flow arrows next to
        the task rows (util.tracing.hop_trace_events)."""
        events = self.task_events()
        trace: list[dict] = []
        seen_procs: set[tuple] = set()
        for ev in events:
            pid = ev.get("node_id", "?")[:8]
            tid = ev.get("worker_id", "?")[:8]
            if (pid, tid) not in seen_procs:
                seen_procs.add((pid, tid))
                trace.append(
                    {
                        "name": "thread_name",
                        "ph": "M",
                        "pid": pid,
                        "tid": tid,
                        "args": {"name": f"worker:{tid}"},
                    }
                )
            state = ev.get("state")
            if state in ("FINISHED", "FAILED") and "start_ts" in ev:
                start = ev["start_ts"]
                end = ev.get("end_ts", ev["ts"])
                trace.append(
                    {
                        "name": ev.get("name", "task"),
                        "cat": "task",
                        "ph": "X",
                        "ts": start * 1e6,
                        "dur": max(end - start, 0) * 1e6,
                        "pid": pid,
                        "tid": tid,
                        "cname": "thread_state_runnable"
                        if state == "FINISHED"
                        else "terrible",
                        "args": {
                            "task_id": ev.get("task_id"),
                            "state": state,
                            "job_id": ev.get("job_id"),
                        },
                    }
                )
        if flight_events:
            for ev in flight_events:
                trace.append(
                    {
                        "name": ev.get("type", "event"),
                        "cat": "flight",
                        "ph": "i",
                        "s": "t",  # thread-scoped instant
                        "ts": ev["ts"] * 1e6,
                        "pid": f"flight:{ev.get('role', '?')}",
                        "tid": str(ev.get("pid", "?")),
                        "args": {"detail": ev.get("detail", ""), "seq": ev.get("seq")},
                    }
                )
        if hop_records:
            from ray_tpu.util.tracing import hop_trace_events

            trace.extend(hop_trace_events(hop_records))
        if filename:
            with open(filename, "w") as f:
                json.dump(trace, f)
        return trace

    def close(self):
        if self._owns_client:
            self._gcs.close()


def timeline(filename: str | None = None) -> list[dict]:
    """Dump a Chrome-trace timeline of executed tasks (reference:
    ``ray.timeline``, python/ray/_private/state.py:831). When hop records
    exist in the connected owner (RAY_TPU_HOP_TIMING=1, or the always-on
    1-in-N sampling), the per-hop dispatch budget renders as flow spans
    next to the task rows — classic, lease, actor, and ``path="compiled"``
    records alike."""
    cw = worker_context.get_core_worker()
    cw.flush_task_events()
    return GlobalState().chrome_tracing_dump(filename, hop_records=cw.hop_records())
