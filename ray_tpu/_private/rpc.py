"""Async RPC layer.

TPU-native analog of the reference's gRPC server/client wrappers
(src/ray/rpc/grpc_server.h:73, src/ray/rpc/client_call.h:181): length-prefixed
msgpack frames over asyncio TCP/Unix sockets, with a method-dispatch server,
retrying clients, and one background IO event loop per process (the analog of
the reference's instrumented_io_context, src/ray/common/asio/).

Wire format: 4-byte big-endian length, then a msgpack array
``[type, seq, method, payload]`` where type is REQUEST/RESPONSE/ERROR/PUSH.
Payloads are msgpack-native structures; rich Python objects are serialized by
the caller (see serialization.py) before they enter the RPC layer.

Raw frames (transfer hot path): a multi-MiB object chunk riding the msgpack
envelope costs an encode of the ``bytes`` payload plus a ``bytes(...)`` copy
on each side. A RAW frame instead sets the top bit of the length prefix and
carries a fixed binary header (kind, seq, object-id, start offset) followed
by the payload bytes written straight from an arena ``memoryview``; the
receive side hands the payload to a synchronous sink as a ``memoryview``
into the read buffer, so it lands at its arena destination with a single
copy and no intermediate Python ``bytes`` object. Raw support is negotiated
per transfer session (``push_begin``/``fetch_object_chunk`` payload keys);
peers that never advertise it keep the msgpack path, and a torn connection
mid-raw-frame tears the whole connection exactly like a torn msgpack frame
(the length prefix scopes both), so the stream can never desynchronize.
"""

from __future__ import annotations

import asyncio
import collections
import logging
import os
import random
import struct
import threading
import time
import traceback
from typing import Any, Awaitable, Callable

import msgpack

from ray_tpu._private import flight_recorder
from ray_tpu._private.concurrency import any_thread, blocking, loop_only

logger = logging.getLogger(__name__)

# Chaos injection seam (chaos.py). None in production: the entire cost of
# the disabled fault plane is this one is-None check per frame. chaos.py
# swaps the plan in/out; it imports this module, never the reverse.
_CHAOS = None


def addr_key(address) -> str:
    """Canonical endpoint string for chaos partition matching: unix socket
    path, or host:port."""
    if address is None:
        return ""
    if isinstance(address, str):
        return address
    return f"{address[0]}:{address[1]}"

REQUEST, RESPONSE, ERROR, PUSH = 0, 1, 2, 3

_MAX_FRAME = 1 << 31

# ---- raw frames ----
# Length prefix with the top bit set marks a raw frame; the low 31 bits are
# the byte count of header+id+payload. A pre-raw peer that is mistakenly sent
# one fails fast with "frame too large" and resets the connection — raw is
# only ever sent after the receiver advertised it, so this is a bug trap,
# not a compatibility channel.
RAW_FLAG = 0x80000000
# kind u8, flags u8 (reserved), oid_len u16, seq u32, start u64.
_RAW_HDR = struct.Struct("<BBHIQ")
RAW_CHUNK = 1  # client -> server: object chunk into an open push session
RAW_RESP = 2   # server -> client: chunk payload answering a pending request


class RawFrame:
    """A decoded raw frame. ``payload`` is a memoryview into the connection
    read buffer: valid ONLY until the consumer yields control back to the
    frame stream (it is released on generator resume), so raw sinks/handlers
    must consume it synchronously (one arena memcpy, no awaits)."""

    __slots__ = ("kind", "seq", "oid", "start", "payload")

    def __init__(self, kind, seq, oid, start, payload):
        self.kind = kind
        self.seq = seq
        self.oid = oid
        self.start = start
        self.payload = payload


class RawResult:
    """Returned by an rpc_ handler to answer with a raw frame instead of a
    msgpack RESPONSE. ``payload`` is written straight to the socket (an arena
    memoryview stays zero-copy); ``on_sent`` runs after the transport has
    taken the bytes — use it to release an object pin."""

    __slots__ = ("oid", "start", "payload", "on_sent")

    def __init__(self, oid: str, start: int, payload, on_sent=None):
        self.oid = oid
        self.start = start
        self.payload = payload
        self.on_sent = on_sent


def _pack_raw_header(kind: int, seq: int, oid_b: bytes, start: int, payload_len: int) -> bytes:
    n = _RAW_HDR.size + len(oid_b) + payload_len
    return (
        (RAW_FLAG | n).to_bytes(4, "big")
        + _RAW_HDR.pack(kind, 0, len(oid_b), seq, start)
        + oid_b
    )


class _WireStats:
    """Plain-int wire counters for the frame pump. Every reader/writer runs
    on the one IO loop thread, so bare ``+=`` is race-free there; the rare
    off-loop increments (connect bookkeeping) can at worst lose an event,
    never corrupt. Folded into ``ray_tpu_rpc_*`` instruments at metrics-flush
    cadence (self_metrics._collect_wire_stats) — an instrument lock per
    frame would tax the dispatch hot path."""

    __slots__ = (
        "frames_out", "bytes_out", "frames_in", "bytes_in",
        "connects", "resets", "hwm_stalls",
    )

    def __init__(self):
        self.frames_out = 0
        self.bytes_out = 0
        self.frames_in = 0
        self.bytes_in = 0
        self.connects = 0
        self.resets = 0
        self.hwm_stalls = 0


WIRE = _WireStats()


def schema(**fields):
    """Declare a wire schema for an ``rpc_`` handler (N4 analog of the
    reference's protobuf message types: the transport is schemaless msgpack,
    so required-field/type validation happens at dispatch).

    Field spec: name=type (required), name=(type, ...) for alternatives,
    name=None for required-any; prefix the name with ``_`` is not supported —
    mark OPTIONAL fields by wrapping the spec in a list: name=[type].
    Unknown payload keys are allowed (forward compatibility, like proto3).
    """

    def deco(fn):
        fn._rpc_schema = fields
        return fn

    return deco


def validate_payload(payload, fields) -> str | None:
    """Returns a problem description, or None if the payload conforms."""
    if not isinstance(payload, dict):
        return f"payload must be a map, got {type(payload).__name__}"
    for name, spec in fields.items():
        optional = isinstance(spec, list)
        if optional:
            spec = spec[0] if spec else None
        if name not in payload:
            if optional:
                continue
            return f"missing required field {name!r}"
        if spec is None:
            continue
        value = payload[name]
        if optional and value is None:
            continue
        if not isinstance(value, spec):
            want = getattr(spec, "__name__", spec)
            return f"field {name!r} must be {want}, got {type(value).__name__}"
    return None


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    pass


def _set_nodelay(writer: "asyncio.StreamWriter"):
    """Request/response frames are small; Nagle coalescing only adds
    latency (the reference's gRPC channels disable it too)."""
    import socket as _socket

    sock = writer.get_extra_info("socket")
    if sock is not None and sock.family in (_socket.AF_INET, _socket.AF_INET6):
        try:
            sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        except OSError:
            pass


def _pack(msg) -> bytes:
    body = msgpack.packb(msg, use_bin_type=True)
    WIRE.frames_out += 1
    WIRE.bytes_out += len(body) + 4
    return len(body).to_bytes(4, "big") + body


_READ_CHUNK = 256 * 1024
# Responses are written without awaiting drain() unless the socket buffer
# has actually backed up: drain is a scheduling point per frame, and the
# transport already buffers — only genuine backpressure should suspend.
_WRITE_HIGH_WATER = 1 << 20


async def _frame_stream(reader: asyncio.StreamReader):
    """Yield decoded frames, draining every COMPLETE frame per socket read.

    The hot dispatch path: readexactly(4)+readexactly(n) costs two loop
    wakeups per frame; one buffered read() serves however many frames
    arrived, which is what makes pipelined task/result streams cheap."""
    buf = bytearray()
    pos = 0
    while True:
        avail = len(buf) - pos
        if avail >= 4:
            length = int.from_bytes(buf[pos : pos + 4], "big")
            if length & RAW_FLAG:
                # Raw frame: fixed header + object id + payload, no msgpack.
                n = length & ~RAW_FLAG
                if n < _RAW_HDR.size or n > _MAX_FRAME:
                    raise RpcError(f"bad raw frame length: {n}")
                if avail >= 4 + n:
                    kind, _flags, oid_len, seq, rstart = _RAW_HDR.unpack_from(
                        buf, pos + 4
                    )
                    if _RAW_HDR.size + oid_len > n:
                        raise RpcError("raw frame header overruns frame")
                    id_at = pos + 4 + _RAW_HDR.size
                    oid = bytes(buf[id_at : id_at + oid_len]).decode()
                    pos += 4 + n
                    WIRE.frames_in += 1
                    WIRE.bytes_in += n + 4
                    # The payload memoryview aliases the read buffer: hand it
                    # out for the duration of ONE consumer step and release
                    # it on resume, so the buffer can compact/grow again.
                    mv = memoryview(buf)
                    payload = mv[id_at + oid_len : pos]
                    try:
                        yield RawFrame(kind, seq, oid, rstart, payload)
                    finally:
                        payload.release()
                        mv.release()
                    continue
            elif length > _MAX_FRAME:
                raise RpcError(f"frame too large: {length}")
            elif avail >= 4 + length:
                start = pos + 4
                frame = msgpack.unpackb(bytes(buf[start : start + length]), raw=False)
                pos = start + length
                WIRE.frames_in += 1
                WIRE.bytes_in += length + 4
                yield frame
                continue
        if pos:
            del buf[:pos]  # compact consumed bytes before growing
            pos = 0
        chunk = await reader.read(_READ_CHUNK)
        if not chunk:
            raise asyncio.IncompleteReadError(bytes(buf), None)
        buf += chunk


@loop_only
def _apply_send_action(act, writer, parts, label: str) -> bool:
    """Apply a chaos injection decision to one outbound frame (``parts`` =
    the frame's byte buffers in wire order). Returns False when the frame
    was dropped, True when bytes (possibly doctored) hit the transport.
    Raises ConnectionLost for partition (the link is severed; the live
    socket is torn so the peer's half dies too)."""
    kind = act.kind
    if kind == "drop":
        return False
    if kind == "kill":
        # Crash fault: this process dies NOW, mid-protocol, exactly like a
        # real SIGKILL/OOM — no atexit, no flushes, no goodbye frames. The
        # chaos_kill flight event was stamped by the plan (mmap ring
        # survives), so the injection log outlives the process.
        import signal as _signal

        os.kill(os.getpid(), _signal.SIGKILL)
        return False  # unreachable (SIGKILL is not deliverable-to-self-late)
    if kind == "partition":
        try:
            writer.close()
        except Exception:
            pass
        raise ConnectionLost(f"chaos: partition blocks send to {label}")
    if kind == "dup":
        for p in parts:
            writer.write(p)
        for p in parts:
            writer.write(bytes(p))  # the transport may already own the view
        return True
    if kind == "reset":
        data = b"".join(bytes(p) for p in parts)
        writer.write(data[: max(0, act.reset_at)])
        try:
            writer.close()
        except Exception:
            pass
        return True
    # delay: write the full frame later; delaying one frame past its
    # successors IS reordering. The connection may die in the window.
    data = b"".join(bytes(p) for p in parts)

    def _late_write(w=writer, d=data):
        try:
            if not w.is_closing():
                w.write(d)
        except Exception:
            pass

    asyncio.get_event_loop().call_later(act.delay_s, _late_write)
    return True


# Seeded jitter source for acall retry backoff: RAY_TPU_CHAOS_SEED makes
# the schedule reproducible under a chaos run; otherwise per-process random.
_BACKOFF_RNG = random.Random(
    int(os.environ.get("RAY_TPU_CHAOS_SEED", "0") or 0) ^ 0x5EEDBACC
    if os.environ.get("RAY_TPU_CHAOS_SEED")
    else None
)


def retry_backoff_s(attempt: int, base_s: float, max_s: float, rng=None) -> float:
    """Capped exponential backoff with jitter for acall retries: attempt 1
    waits ~base, doubling per attempt up to max, each scaled by a uniform
    [0.5, 1.0) jitter factor so a fleet of retriers against one recovering
    peer decorrelates instead of hammering in lockstep."""
    r = (rng or _BACKOFF_RNG).random()
    return min(max_s, base_s * (1 << max(0, attempt - 1))) * (0.5 + 0.5 * r)


def _drain_if_needed(writer: asyncio.StreamWriter):
    """Awaitable-or-None: drain only under real backpressure."""
    try:
        if writer.transport.get_write_buffer_size() > _WRITE_HIGH_WATER:
            WIRE.hwm_stalls += 1
            flight_recorder.record("rpc_hwm_stall")
            return writer.drain()
    except Exception:
        pass
    return None


class EventLoopThread:
    """One background asyncio loop per process; all sockets live here.

    Analog of the per-process instrumented_io_context event loop in the
    reference (src/ray/common/asio/instrumented_io_context.h:27), including
    per-handler call stats for debugging.
    """

    _instance: "EventLoopThread | None" = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._run, name="ray_tpu_io", daemon=True
        )
        self.handler_stats: dict[str, list] = collections.defaultdict(
            lambda: [0, 0.0]
        )  # name -> [count, total_s]
        self._thread.start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    @classmethod
    def get(cls) -> "EventLoopThread":
        with cls._instance_lock:
            if cls._instance is None or not cls._instance._thread.is_alive():
                cls._instance = cls()
            return cls._instance

    @classmethod
    def reset(cls):
        with cls._instance_lock:
            inst, cls._instance = cls._instance, None
        if inst is not None:
            inst.loop.call_soon_threadsafe(inst.loop.stop)

    @blocking
    def run(self, coro: Awaitable, timeout: float | None = None):
        """Run a coroutine on the IO loop from any other thread, blocking.
        @blocking: calling this FROM the loop thread deadlocks it (the loop
        would wait on a future only the loop can complete)."""
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    @any_thread
    def spawn(self, coro: Awaitable) -> "asyncio.Future":
        return asyncio.run_coroutine_threadsafe(coro, self.loop)


Handler = Callable[[dict], Awaitable[Any]]


class RpcServer:
    """Method-dispatch RPC server. Register async handlers by name."""

    def __init__(self, name: str = "server"):
        self.name = name
        self._handlers: dict[str, Handler] = {}
        self._schemas: dict[str, dict] = {}
        self._server: asyncio.Server | None = None
        self._conns: set[asyncio.StreamWriter] = set()
        self.address: tuple[str, int] | str | None = None
        # Chaos endpoint identity for response-side rule matching (the
        # raylet stamps its own address key here after start()).
        self.chaos_scope: str | None = None
        self._io = EventLoopThread.get()
        # Raw-frame sink: a SYNCHRONOUS callable (frame: RawFrame) -> dict,
        # invoked inline on the connection loop before the read buffer moves
        # (the payload memoryview dies when the frame stream resumes).
        self._raw_handler: Callable[[RawFrame], dict] | None = None

    def register(self, method: str, handler: Handler):
        self._handlers[method] = handler

    @any_thread
    def set_raw_handler(self, handler: Callable[[RawFrame], dict]):
        self._raw_handler = handler

    def register_all(self, obj, prefix: str = ""):
        """Register every ``rpc_<name>`` coroutine method of obj as <name>."""
        for attr in dir(obj):
            if attr.startswith("rpc_"):
                handler = getattr(obj, attr)
                self._handlers[prefix + attr[4:]] = handler
                schema = getattr(handler, "_rpc_schema", None)
                if schema is not None:
                    self._schemas[prefix + attr[4:]] = schema

    async def _serve_conn(self, reader, writer):
        _set_nodelay(writer)
        self._conns.add(writer)
        try:
            async for frame in _frame_stream(reader):
                if type(frame) is RawFrame:
                    # Handled INLINE (not ensure_future): the payload view is
                    # only valid until the stream resumes, and the arena
                    # write is a synchronous memcpy anyway.
                    handler = self._raw_handler
                    try:
                        if handler is None:
                            result = {"ok": False, "error": "no raw handler"}
                        else:
                            result = handler(frame)
                    except Exception as e:  # noqa: BLE001
                        result = {"ok": False, "error": repr(e)}
                    self._send_resp(
                        writer, "raw_chunk",
                        [_pack([RESPONSE, frame.seq, "raw_chunk", result])],
                    )
                    pending = _drain_if_needed(writer)
                    if pending is not None:
                        await pending
                    continue
                mtype, seq, method, payload = frame
                if mtype == REQUEST:
                    asyncio.ensure_future(
                        self._dispatch(writer, seq, method, payload)
                    )
                elif mtype == PUSH:
                    asyncio.ensure_future(self._dispatch(None, seq, method, payload))
        except (asyncio.IncompleteReadError, ConnectionResetError, BrokenPipeError, OSError):
            return
        finally:
            self._conns.discard(writer)
            try:
                writer.close()
            except Exception:
                pass

    @loop_only
    def _send_resp(self, writer, method: str, parts) -> bool:
        """Response write seam: every server->client frame funnels here so
        the chaos plane can doctor it. Disabled cost: one is-None check.
        Partition actions never apply to responses (partitions are enforced
        at clients/connects, which tears the shared socket anyway)."""
        ch = _CHAOS
        if ch is not None:
            peer = writer.get_extra_info("peername")
            act = ch.on_send(
                self.chaos_scope, self.name, addr_key(peer), method, side="resp"
            )
            if act is not None and act.kind != "partition":
                return _apply_send_action(act, writer, parts, self.name)
        for p in parts:
            writer.write(p)
        return True

    async def _dispatch(self, writer, seq, method, payload):
        start = time.monotonic()
        handler = self._handlers.get(method)
        try:
            if handler is None:
                raise RpcError(f"no handler for method {method!r} on {self.name}")
            schema = self._schemas.get(method)
            if schema is not None:
                problem = validate_payload(payload, schema)
                if problem:
                    raise RpcError(f"schema violation in {method!r}: {problem}")
            result = await handler(payload)
            if isinstance(result, RawResult):
                # Negotiated raw response: header + payload straight to the
                # socket, no msgpack encode / bytes copy of the chunk. The
                # transport owns the bytes once write() returns, so on_sent
                # (typically an object-pin release) is safe immediately after.
                try:
                    if writer is not None:
                        oid_b = result.oid.encode()
                        self._send_resp(
                            writer, method,
                            [
                                _pack_raw_header(
                                    RAW_RESP, seq, oid_b, result.start,
                                    len(result.payload),
                                ),
                                result.payload,
                            ],
                        )
                        WIRE.frames_out += 1
                        WIRE.bytes_out += (
                            4 + _RAW_HDR.size + len(oid_b) + len(result.payload)
                        )
                        pending = _drain_if_needed(writer)
                        if pending is not None:
                            await pending
                finally:
                    if result.on_sent is not None:
                        result.on_sent()
            elif writer is not None:
                self._send_resp(writer, method, [_pack([RESPONSE, seq, method, result])])
                pending = _drain_if_needed(writer)
                if pending is not None:
                    await pending
        except (ConnectionResetError, BrokenPipeError):
            pass  # peer went away mid-response (routine at shutdown)
        except Exception as e:
            if writer is not None:
                err = {"error": repr(e), "traceback": traceback.format_exc()}
                try:
                    writer.write(_pack([ERROR, seq, method, err]))
                    await writer.drain()
                except Exception:
                    pass
            else:
                logger.exception("push handler %s failed", method)
        finally:
            stats = self._io.handler_stats[f"{self.name}.{method}"]
            stats[0] += 1
            stats[1] += time.monotonic() - start

    async def _start_tcp(self, host: str, port: int):
        self._server = await asyncio.start_server(self._serve_conn, host, port)
        sock = self._server.sockets[0]
        self.address = sock.getsockname()[:2]

    async def _start_unix(self, path: str):
        self._server = await asyncio.start_unix_server(self._serve_conn, path)
        self.address = path

    def start(self, host: str = "127.0.0.1", port: int = 0):
        self._io.run(self._start_tcp(host, port))
        return self.address

    def start_unix(self, path: str):
        self._io.run(self._start_unix(path))
        return self.address

    def stop(self):
        async def _stop():
            if self._server is not None:
                self._server.close()
            for w in list(self._conns):
                try:
                    w.close()
                except Exception:
                    pass

        try:
            self._io.run(_stop(), timeout=5)
        except Exception:
            pass


class RpcClient:
    """Retrying RPC client; safe to call from any thread or from the IO loop."""

    def __init__(self, address, label: str = "", connect_timeout: float | None = None):
        from ray_tpu._private.config import get_config

        cfg = get_config()
        self.address = address
        self.label = label or str(address)
        # Chaos identity: the canonical target endpoint, plus an optional
        # local-endpoint scope (a raylet stamps its own address on clients
        # it owns so "this node's outbound traffic" is partitionable).
        self._addr_key = addr_key(address)
        self.chaos_scope: str | None = None
        self._io = EventLoopThread.get()
        self._connect_timeout = connect_timeout or cfg.rpc_connect_timeout_s
        self._retries = cfg.rpc_retries
        self._backoff_base_s = cfg.rpc_retry_backoff_base_ms / 1000.0
        self._backoff_max_s = cfg.rpc_retry_backoff_max_ms / 1000.0
        self._lock = asyncio.Lock()
        self._writer: asyncio.StreamWriter | None = None
        self._reader_task: asyncio.Task | None = None
        self._pending: dict[int, asyncio.Future] = {}
        # seq -> synchronous sink for a negotiated raw response: called with
        # the RawFrame while its payload view is valid (scatter straight into
        # the arena), its return value resolves the pending future.
        self._raw_sinks: dict[int, Callable[[RawFrame], Any]] = {}
        self._seq = 0
        self._push_handler: Callable[[str, dict], None] | None = None
        self._closed = False

    # ---- connection management (runs on IO loop) ----

    async def _ensure_connected(self):
        ch = _CHAOS
        if ch is not None and ch.check_connect(self.chaos_scope, self.label, self._addr_key):
            # Partitioned: fail the connect fast (the peer is unroutable NOW)
            # and tear any live socket so the peer's half dies with it.
            if self._writer is not None:
                try:
                    self._writer.close()
                except Exception:
                    pass
                self._writer = None
            raise ConnectionLost(f"chaos: partition blocks connect to {self.label}")
        if self._writer is not None and not self._writer.is_closing():
            return
        deadline = time.monotonic() + self._connect_timeout
        last_err = None
        while time.monotonic() < deadline:
            try:
                if isinstance(self.address, str):
                    reader, writer = await asyncio.open_unix_connection(self.address)
                else:
                    reader, writer = await asyncio.open_connection(*self.address)
                _set_nodelay(writer)
                self._writer = writer
                self._reader_task = asyncio.ensure_future(self._read_loop(reader))
                WIRE.connects += 1
                flight_recorder.record("rpc_connect", self.label)
                return
            except OSError as e:
                last_err = e
                await asyncio.sleep(0.05)
        raise ConnectionLost(f"cannot connect to {self.label}: {last_err}")

    async def _read_loop(self, reader):
        try:
            async for frame in _frame_stream(reader):
                if type(frame) is RawFrame:
                    sink = self._raw_sinks.pop(frame.seq, None)
                    fut = self._pending.pop(frame.seq, None)
                    try:
                        result = sink(frame) if sink is not None else None
                    except Exception as e:  # noqa: BLE001
                        if fut is not None and not fut.done():
                            fut.set_exception(
                                RpcError(f"{self.label}: raw sink failed: {e!r}")
                            )
                    else:
                        if fut is not None and not fut.done():
                            fut.set_result(
                                result
                                if result is not None
                                else {"ok": True, "len": len(frame.payload)}
                            )
                    continue
                mtype, seq, method, payload = frame
                if mtype in (RESPONSE, ERROR):
                    fut = self._pending.pop(seq, None)
                    self._raw_sinks.pop(seq, None)  # peer answered in msgpack
                    if fut is not None and not fut.done():
                        if mtype == RESPONSE:
                            fut.set_result(payload)
                        else:
                            fut.set_exception(
                                RpcError(
                                    f"{self.label}.{method}: {payload['error']}\n"
                                    + payload.get("traceback", "")
                                )
                            )
                elif mtype == PUSH and self._push_handler is not None:
                    try:
                        self._push_handler(method, payload)
                    except Exception:
                        logger.exception("push handler failed")
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            pass
        finally:
            self._writer = None
            if not self._closed:
                WIRE.resets += 1
                flight_recorder.record("rpc_reset", self.label)
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionLost(f"connection to {self.label} lost"))
            self._pending.clear()
            self._raw_sinks.clear()

    @loop_only
    def _send_frames(self, method: str, parts) -> bool:
        """Client send seam: every outbound frame funnels here. Returns
        False when the chaos plane dropped the frame (the caller's future
        then heals by timeout/retry, exactly like real loss); raises
        ConnectionLost on an injected partition. Disabled cost: one
        is-None check per frame."""
        ch = _CHAOS
        if ch is not None:
            act = ch.on_send(self.chaos_scope, self.label, self._addr_key, method)
            if act is not None:
                return _apply_send_action(act, self._writer, parts, self.label)
        for p in parts:
            self._writer.write(p)
        return True

    async def astart_call(
        self, method: str, payload: dict | None = None, raw_sink=None
    ) -> "asyncio.Future":
        """Send a request; return the response future without awaiting it.

        Lets callers pipeline ordered calls: the send happens under the client
        lock (FIFO), so two astart_call()s issued in order hit the wire in
        order (the analog of the reference's SequentialActorSubmitQueue).

        ``raw_sink``: synchronous callable invoked with the RawFrame if the
        peer answers this request with a raw frame (negotiated transfer
        path); its return value resolves the future. A msgpack answer simply
        resolves the future as usual (the sink is dropped) — that IS the
        mixed-version fallback.
        """
        async with self._lock:
            await self._ensure_connected()
            self._seq += 1
            seq = self._seq
            fut = asyncio.get_event_loop().create_future()
            fut._rtpu_seq = seq  # lets acall unregister on per-attempt timeout
            self._pending[seq] = fut
            if raw_sink is not None:
                self._raw_sinks[seq] = raw_sink
            try:
                self._send_frames(method, [_pack([REQUEST, seq, method, payload or {}])])
            except ConnectionLost:
                # Injected partition: unregister the stillborn attempt so a
                # late frame can never resolve it, then surface the loss.
                self._pending.pop(seq, None)
                self._raw_sinks.pop(seq, None)
                raise
            pending = _drain_if_needed(self._writer)
            if pending is not None:
                await pending
        return fut

    async def astart_raw(
        self, kind: int, oid: str, start: int, payload
    ) -> "asyncio.Future":
        """Send a raw frame (header + payload bytes, no msgpack); return the
        future for the receiver's ack. ``payload`` is any buffer — an arena
        memoryview goes to the socket without an intermediate ``bytes``
        (the transport copies only what it cannot send immediately). Only
        valid after the peer advertised raw support for this session."""
        async with self._lock:
            await self._ensure_connected()
            self._seq += 1
            seq = self._seq
            fut = asyncio.get_event_loop().create_future()
            fut._rtpu_seq = seq
            self._pending[seq] = fut
            oid_b = oid.encode()
            try:
                self._send_frames(
                    "raw_chunk",
                    [_pack_raw_header(kind, seq, oid_b, start, len(payload)), payload],
                )
            except ConnectionLost:
                self._pending.pop(seq, None)
                raise
            WIRE.frames_out += 1
            WIRE.bytes_out += 4 + _RAW_HDR.size + len(oid_b) + len(payload)
            pending = _drain_if_needed(self._writer)
            if pending is not None:
                await pending
        return fut

    @loop_only
    def send_nowait(self, method: str, payload: dict | None = None):
        """LOOP-THREAD-ONLY fast path: write the request frame synchronously
        when the connection is up and no other sender holds the client lock;
        returns the response future, or None (caller falls back to acall).

        Saves the task-scheduling loop iteration astart_call costs per send —
        measurable on the sync dispatch ping-pong. Write ordering is
        preserved: every writer (here and astart_call) runs on the one IO
        loop, and the lock.locked() guard keeps us from interleaving with a
        sender that is mid-connect under the lock."""
        if (
            self._closed
            or self._writer is None
            or self._writer.is_closing()
            or self._lock.locked()
        ):
            return None
        try:
            if self._writer.transport.get_write_buffer_size() > _WRITE_HIGH_WATER:
                # Genuine backpressure (stalled peer): fall back to the
                # acall path, which awaits drain — an unchecked write here
                # would grow the socket buffer without bound.
                WIRE.hwm_stalls += 1
                flight_recorder.record("rpc_hwm_stall", self.label)
                return None
        except Exception:
            pass
        self._seq += 1
        seq = self._seq
        fut = asyncio.get_event_loop().create_future()
        fut._rtpu_seq = seq  # lets ack-timeout callers unregister the entry
        self._pending[seq] = fut
        try:
            self._send_frames(method, [_pack([REQUEST, seq, method, payload or {}])])
        except ConnectionLost:
            # Injected partition: behave like the cold-connection case —
            # the caller falls back to acall, which raises/retries cleanly.
            self._pending.pop(seq, None)
            return None
        return fut

    async def acall(
        self,
        method: str,
        payload: dict | None = None,
        timeout: float | None = None,
        retries: int | None = None,
        raw_sink=None,
    ):
        """Async call from the IO loop.

        ``timeout`` is PER ATTEMPT and TimeoutError is retried, so the worst
        case block is ``(retries+1) * timeout`` plus backoff. Callers that
        need a total bound pass ``retries=0`` (single attempt, safe only when
        dropping the message is acceptable) or wrap in an outer wait_for.
        """
        payload = payload or {}
        max_retries = self._retries if retries is None else retries
        attempt = 0
        while True:
            fut = None
            try:
                fut = await self.astart_call(method, payload, raw_sink=raw_sink)
                if timeout is not None:
                    return await asyncio.wait_for(fut, timeout)
                return await fut
            except (ConnectionLost, asyncio.TimeoutError):
                # Unregister the abandoned attempt. CRITICAL for raw sinks: a
                # LATE raw response must never invoke a sink whose caller has
                # moved on — the sink writes memory (arena scatter), and its
                # destination may have been freed/reused by then. With the
                # entry popped, the late frame resolves nothing and is
                # dropped on the floor.
                if fut is not None:
                    seq = getattr(fut, "_rtpu_seq", None)
                    if seq is not None:
                        self._pending.pop(seq, None)
                        self._raw_sinks.pop(seq, None)
                attempt += 1
                if self._closed or attempt > max_retries:
                    raise
                # Capped exponential backoff with seeded jitter: a
                # partitioned/recovering peer is probed at a decaying rate
                # instead of hammered at the fixed-pause full rate
                # (retries=0 callers never reach this sleep).
                await asyncio.sleep(
                    retry_backoff_s(attempt, self._backoff_base_s, self._backoff_max_s)
                )

    async def apush(self, method: str, payload: dict | None = None):
        async with self._lock:
            await self._ensure_connected()
            self._seq += 1
            self._send_frames(method, [_pack([PUSH, self._seq, method, payload or {}])])
            pending = _drain_if_needed(self._writer)
            if pending is not None:
                await pending

    @staticmethod
    def pack_push_frame(method: str, payload: dict) -> bytes:
        """Encode a one-way PUSH frame for apush_packed. seq is fixed at 0:
        PUSH dispatch never consults it (no response to pair), so the same
        bytes are valid on every connection."""
        return _pack([PUSH, 0, method, payload])

    async def apush_packed(self, method: str, frame: bytes):
        """One-way push of a PRE-PACKED frame (see pack_push_frame). The
        group-broadcast fan-out encodes each multi-MiB chunk frame ONCE and
        writes the same bytes down every member connection — K-1 msgpack
        encodes saved per chunk is most of the fan-out's CPU at scale.
        ``method`` is passed for the chaos/observability seam only; the
        wire bytes are ``frame`` verbatim."""
        async with self._lock:
            await self._ensure_connected()
            self._seq += 1
            self._send_frames(method, [frame])
            pending = _drain_if_needed(self._writer)
            if pending is not None:
                await pending

    # ---- blocking API (from user threads) ----

    @blocking
    def call(
        self,
        method: str,
        payload: dict | None = None,
        timeout: float | None = None,
        retries: int | None = None,
    ):
        return self._io.run(self.acall(method, payload, timeout=timeout, retries=retries))

    @blocking
    def push(self, method: str, payload: dict | None = None):
        return self._io.run(self.apush(method, payload))

    @any_thread
    def set_push_handler(self, handler: Callable[[str, dict], None]):
        self._push_handler = handler

    @any_thread
    def close(self):
        self._closed = True

        async def _close():
            if self._writer is not None:
                try:
                    self._writer.close()
                except Exception:
                    pass
            if self._reader_task is not None:
                self._reader_task.cancel()

        # Never block the IO loop on itself: from the loop thread just
        # schedule the close; from any other thread wait briefly.
        if threading.current_thread() is self._io._thread:
            asyncio.ensure_future(_close())
            return
        try:
            self._io.run(_close(), timeout=2)
        except Exception:
            pass
