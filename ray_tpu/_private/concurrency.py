"""Thread-affinity contracts for the runtime's concurrency model.

The process has a small, fixed set of thread roles (see CONCURRENCY.md):

- the **IO loop thread** (``rpc.EventLoopThread``): every socket, every RPC
  handler, every asyncio primitive lives here;
- **user threads**: the driver's threads calling the public sync API
  (``get``/``put``/``wait``/``.remote``);
- the **task-exec thread** (worker processes: the MAIN thread, see
  ``worker_main._MainThreadExecutor``) running user task bodies;
- assorted daemon helpers (log resubscribe, task-event flush, raylet watch).

PR 2's warm-lease fast path made the hottest functions deliberately
single-threaded-by-contract (``RpcClient.send_nowait`` writes a frame with no
lock at all). These markers turn those prose contracts into something a tool
can check:

- ``@loop_only``  — may ONLY run on a thread with a running asyncio event
  loop (i.e. as loop callbacks / from coroutines). Calling it from any other
  thread without a ``call_soon_threadsafe``/``run_coroutine_threadsafe`` hop
  is a bug even when it happens to work today.
- ``@any_thread`` — designed to be safe from every thread role; the
  documented cross-thread entry points (they hop internally when needed).
- ``@blocking``   — blocks the calling thread (lock/event waits, blocking
  RPC round trips). Must NEVER run on the IO loop thread: every socket in
  the process stalls, and anything that waits on loop progress deadlocks.

``ray_tpu.tools.graftlint`` checks these statically (call-graph pass over the
package); with ``RAY_TPU_DEBUG_AFFINITY=1`` set **before import** the markers
also install a cheap runtime assert so the dynamic behavior backs up the
static analysis in tests. Without the env var they return the function
unchanged — zero overhead on the hot path.
"""

from __future__ import annotations

import asyncio
import functools
import os

DEBUG_AFFINITY = os.environ.get("RAY_TPU_DEBUG_AFFINITY") == "1"


def _on_loop_thread() -> bool:
    """True iff the current thread has a RUNNING asyncio loop — i.e. we are
    executing a loop callback or a coroutine step (``get_running_loop`` is
    set for the whole ``run_forever``, including sync callbacks)."""
    try:
        asyncio.get_running_loop()
        return True
    except RuntimeError:
        return False


def loop_only(fn):
    """Contract: ``fn`` runs only on an event-loop thread."""
    if not DEBUG_AFFINITY:
        fn.__graftlint_affinity__ = "loop_only"
        return fn

    @functools.wraps(fn)
    def _guarded(*args, **kwargs):
        assert _on_loop_thread(), (
            f"{fn.__qualname__} is @loop_only but was called from a thread "
            "with no running event loop; hop via call_soon_threadsafe / "
            "run_coroutine_threadsafe (RAY_TPU_DEBUG_AFFINITY=1)"
        )
        return fn(*args, **kwargs)

    _guarded.__graftlint_affinity__ = "loop_only"
    return _guarded


def any_thread(fn):
    """Contract: ``fn`` is a documented cross-thread entry point."""
    fn.__graftlint_affinity__ = "any_thread"
    return fn


def blocking(fn):
    """Contract: ``fn`` blocks the calling thread and must never run on an
    event-loop thread."""
    if not DEBUG_AFFINITY:
        fn.__graftlint_affinity__ = "blocking"
        return fn

    @functools.wraps(fn)
    def _guarded(*args, **kwargs):
        assert not _on_loop_thread(), (
            f"{fn.__qualname__} is @blocking (stalls the calling thread) but "
            "was called on an event-loop thread; move it off-loop with "
            "run_in_executor (RAY_TPU_DEBUG_AFFINITY=1)"
        )
        return fn(*args, **kwargs)

    _guarded.__graftlint_affinity__ = "blocking"
    return _guarded
