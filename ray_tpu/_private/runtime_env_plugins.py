"""Runtime-env plugin protocol + URI cache.

Analog of the reference's RuntimeEnvPlugin seam
(python/ray/_private/runtime_env/plugin.py) and its per-URI resource cache
(runtime_env/uri_cache.py): a plugin owns one runtime_env FIELD, validates
it at submission, materializes expensive per-URI resources ONCE per node
into a content-addressed cache directory, and applies the result (env
vars, sys.path, cwd) at every worker start.

The in-image build ships no pip/conda/container provisioning (no network),
but the SEAM is what the reference exposes: site plugins register via the
``RAY_TPU_RUNTIME_ENV_PLUGINS`` env var (JSON list of ``{"class":
"module.Class"}``, read in every process) or programmatically via
``register_plugin`` — the programmatic path also ships the class path
inside the runtime env itself so workers load it without pre-set env vars.
"""

from __future__ import annotations

import hashlib
import importlib
import json
import logging
import os
import threading

logger = logging.getLogger(__name__)

_PLUGIN_ENV_VAR = "RAY_TPU_RUNTIME_ENV_PLUGINS"
_PLUGIN_CLASSES_FIELD = "_plugin_classes"  # injected into runtime_env dicts

# RLock: the env-var loader registers plugins while holding the lock, so
# registration must be re-entrant (and fully complete before any other
# thread can observe the loaded flag).
_lock = threading.RLock()
_plugins: dict[str, "RuntimeEnvPlugin"] = {}
_env_var_loaded = False


class RuntimeEnvPlugin:
    """Subclass and register. ``name`` is the runtime_env field the plugin
    owns (e.g. "conda", "my_env_setup")."""

    name: str = ""
    priority: int = 10  # lower runs first at worker start

    def validate(self, value, runtime_env: dict) -> None:
        """Raise at SUBMISSION time for malformed config."""

    def get_uris(self, value, runtime_env: dict) -> list:
        """URIs whose materialization is cacheable per node. Default: one
        URI derived from the field value (every distinct value caches
        separately)."""
        blob = json.dumps(value, sort_keys=True, default=str)
        return [f"{self.name}://{hashlib.sha1(blob.encode()).hexdigest()[:16]}"]

    def create(self, uri: str, value, runtime_env: dict, target_dir: str) -> None:
        """Materialize `uri` into target_dir. Runs ONCE per (node, uri) —
        later workers reuse the cached directory."""

    def apply(self, value, runtime_env: dict, cached_dirs: dict) -> None:
        """Per-worker-start hook: mutate os.environ / sys.path / cwd."""


def register_plugin(plugin: RuntimeEnvPlugin) -> None:
    if not plugin.name:
        raise ValueError("plugin.name must be a non-empty runtime_env field name")
    with _lock:
        _plugins[plugin.name] = plugin


def unregister_plugin(name: str) -> None:
    with _lock:
        _plugins.pop(name, None)


def _load_from_env_var() -> None:
    global _env_var_loaded
    with _lock:
        if _env_var_loaded:
            return
        # Load COMPLETELY under the lock: a concurrent plugin_fields() must
        # never observe loaded=True with registrations still in flight.
        _env_var_loaded = True
        raw = os.environ.get(_PLUGIN_ENV_VAR)
        if not raw:
            return
        try:
            entries = json.loads(raw)
        except json.JSONDecodeError:
            logger.error("%s is not valid JSON; ignoring", _PLUGIN_ENV_VAR)
            return
        for entry in entries:
            try:
                _register_class_path(entry["class"])
            except Exception:
                logger.exception("failed to load runtime-env plugin %r", entry)


def _register_class_path(class_path: str) -> None:
    module_name, _, cls_name = class_path.rpartition(".")
    cls = getattr(importlib.import_module(module_name), cls_name)
    register_plugin(cls())


def _load_from_runtime_env(runtime_env: dict, strict: bool = False) -> None:
    """Workers: load plugin classes the submitter shipped in the env.

    strict=True raises on import failure — a task must fail LOUDLY rather
    than run without the environment its plugin was supposed to set up."""
    failures = []
    for class_path in runtime_env.get(_PLUGIN_CLASSES_FIELD) or []:
        with _lock:
            known = {
                f"{type(p).__module__}.{type(p).__qualname__}" for p in _plugins.values()
            }
        if class_path in known:
            continue
        try:
            _register_class_path(class_path)
        except Exception as e:
            logger.exception("failed to load shipped runtime-env plugin %s", class_path)
            failures.append(f"{class_path}: {e!r}")
    if failures and strict:
        raise RuntimeError(
            "runtime-env plugin classes shipped with this task failed to "
            "import on the worker (are their modules on py_modules / the "
            "node image?): " + "; ".join(failures)
        )


def ensure_loaded(runtime_env: dict | None = None, strict: bool = False) -> None:
    """Load env-var plugins plus any classes shipped inside runtime_env."""
    _load_from_env_var()
    if runtime_env:
        _load_from_runtime_env(runtime_env, strict=strict)


def plugin_fields() -> set:
    _load_from_env_var()
    with _lock:
        return set(_plugins)


def attach_plugin_classes(runtime_env: dict) -> dict:
    """Submitter side: record the class paths of registered plugins whose
    fields the env uses, so workers can import them."""
    _load_from_env_var()
    with _lock:
        used = [
            f"{type(p).__module__}.{type(p).__qualname__}"
            for name, p in _plugins.items()
            if name in runtime_env
        ]
    if used:
        runtime_env = dict(runtime_env)
        runtime_env[_PLUGIN_CLASSES_FIELD] = sorted(used)
    return runtime_env


def validate_with_plugins(runtime_env: dict) -> None:
    _load_from_env_var()
    with _lock:
        plugins = dict(_plugins)
    for name, plugin in plugins.items():
        if name in runtime_env:
            plugin.validate(runtime_env[name], runtime_env)


def apply_plugins(runtime_env: dict, session_dir: str) -> None:
    """Worker-start hook (worker_main._apply_runtime_env): materialize
    cached URIs and apply every plugin owning a present field."""
    _load_from_env_var()
    _load_from_runtime_env(runtime_env)
    with _lock:
        plugins = sorted(_plugins.values(), key=lambda p: p.priority)
    cache_root = os.path.join(session_dir, "runtime_env_cache")
    for plugin in plugins:
        if plugin.name not in runtime_env:
            continue
        value = runtime_env[plugin.name]
        cached: dict = {}
        for uri in plugin.get_uris(value, runtime_env):
            digest = hashlib.sha1(uri.encode()).hexdigest()[:20]
            target = os.path.join(cache_root, plugin.name, digest)
            marker = os.path.join(target, ".ready")
            if not os.path.exists(marker):
                # First worker on this node materializes; concurrent workers
                # race benignly (tmp dir + atomic rename). A failed create
                # must not leak its partial tmp dir — crash-looping workers
                # would accumulate one per attempt.
                import shutil

                tmp = target + f".tmp.{os.getpid()}"
                os.makedirs(tmp, exist_ok=True)
                try:
                    plugin.create(uri, value, runtime_env, tmp)
                    open(os.path.join(tmp, ".ready"), "w").close()
                    try:
                        os.rename(tmp, target)
                    except OSError:
                        shutil.rmtree(tmp, ignore_errors=True)  # lost the race
                except BaseException:
                    shutil.rmtree(tmp, ignore_errors=True)
                    raise
            cached[uri] = target
        plugin.apply(value, runtime_env, cached)
