"""Usage stats collection.

Analog of the reference's opt-out telemetry (_private/usage/usage_lib.py:93):
cluster/runtime metadata is collected at shutdown. This deployment has no
egress, so the report is only written to ``<session_dir>/usage_stats.json``
(the reference uploads to a collector URL when enabled). Opt out with
``RAY_TPU_USAGE_STATS_ENABLED=0``.
"""

from __future__ import annotations

import json
import os
import platform
import sys
import time


def usage_stats_enabled() -> bool:
    return os.environ.get("RAY_TPU_USAGE_STATS_ENABLED", "1") != "0"


def _library_usages() -> list[str]:
    used = []
    for lib in ("train", "tune", "data", "serve", "rllib", "workflow", "dag"):
        if f"ray_tpu.{lib}" in sys.modules:
            used.append(lib)
    return used


def collect_usage_stats(core_worker) -> dict:
    import ray_tpu

    report = {
        "schema_version": "0.1",
        "ray_tpu_version": ray_tpu.__version__,
        "python_version": platform.python_version(),
        "os": platform.system().lower(),
        "collected_at": time.time(),
        "libraries_used": _library_usages(),
    }
    try:
        nodes = core_worker.gcs.call("get_nodes")["nodes"]
        alive = [n for n in nodes.values() if n["state"] == "ALIVE"]
        report["num_nodes"] = len(alive)
        total: dict = {}
        for n in alive:
            for k, v in n.get("resources_total", {}).items():
                total[k] = total.get(k, 0) + v
        report["total_num_cpus"] = total.get("CPU", 0)
        report["total_num_tpus"] = total.get("TPU", 0)
    except Exception:
        pass
    return report


def write_usage_stats(core_worker):
    """Called from driver shutdown; never raises."""
    if not usage_stats_enabled():
        return
    try:
        report = collect_usage_stats(core_worker)
        path = os.path.join(core_worker.session_dir, "usage_stats.json")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            json.dump(report, f, indent=2)
    except Exception:
        pass
