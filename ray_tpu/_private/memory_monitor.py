"""Node memory monitor → OOM worker killing.

Analog of the reference's memory monitor (_private/memory_monitor.py:94) and
the raylet's worker-killing policies
(worker_killing_policy_group_by_owner.h:85, retriable-FIFO policy): when node
memory passes the threshold, kill the most recently started retriable task's
worker first (its lost progress is the cheapest), falling back to the newest
busy worker. The kill surfaces as a worker death with an OOM cause, so the
owner raises OutOfMemoryError or retries per the task's policy.
"""

from __future__ import annotations

import logging
import time

logger = logging.getLogger(__name__)


def node_memory_fraction() -> float:
    """Used/total from /proc/meminfo (MemAvailable-based, like the reference's
    psutil fallback path)."""
    try:
        info = {}
        with open("/proc/meminfo") as f:
            for line in f:
                key, _, rest = line.partition(":")
                info[key] = int(rest.strip().split()[0])  # kB
        total = info.get("MemTotal", 0)
        avail = info.get("MemAvailable", info.get("MemFree", 0))
        if total <= 0:
            return 0.0
        return 1.0 - (avail / total)
    except Exception:
        return 0.0


class MemoryMonitor:
    def __init__(self, raylet):
        self.raylet = raylet
        self.cfg = raylet.cfg
        self._last_kill_ts = 0.0

    def tick(self):
        """Called from the raylet reap loop; returns the killed worker or None."""
        if not self.cfg.memory_monitor_enabled:
            return None
        frac = node_memory_fraction()
        if frac < self.cfg.memory_usage_threshold:
            return None
        # Cooldown: give the previous kill a chance to free memory.
        if time.monotonic() - self._last_kill_ts < 2.0:
            return None
        victim = self._pick_victim()
        if victim is None:
            return None
        self._last_kill_ts = time.monotonic()
        logger.warning(
            "node memory %.0f%% >= %.0f%%: killing worker %s (task %s) to relieve pressure",
            frac * 100,
            self.cfg.memory_usage_threshold * 100,
            victim.worker_id[:8],
            victim.current_task.name if victim.current_task else "?",
        )
        victim.oom_killed = True
        if victim.proc is not None:
            victim.proc.kill()
        return victim

    def _pick_victim(self):
        """Retriable tasks first, newest first (cheapest lost progress);
        then any busy worker, newest first. Actors are last resorts the
        reference also avoids — we skip them entirely."""
        busy = [
            w
            for w in self.raylet.workers.values()
            if w.state == "busy" and w.current_task is not None and w.proc is not None
        ]
        if not busy:
            return None
        retriable = [w for w in busy if w.current_task.max_retries > 0]
        pool = retriable or busy
        return max(pool, key=lambda w: w.dispatch_ts)
