"""Worker process entry point.

TPU-native analog of the reference's default_worker.py + the Cython task
execution handler (python/ray/_private/workers/default_worker.py,
_raylet.pyx:1791 task_execution_handler): spawned by the raylet's worker pool,
registers back, then serves

- ``push_task`` from the raylet (normal + actor-creation tasks)
- ``actor_call`` directly from callers (the direct actor transport —
  reference: direct_actor_task_submitter.h:67 server side,
  actor_scheduling_queue.h:40 ordering)
- ``kill_self`` for ray_tpu.kill / actor teardown.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import sys
import threading
from concurrent.futures import ThreadPoolExecutor

logger = logging.getLogger(__name__)


class _MainThreadExecutor:
    """Executor-protocol shim that runs submitted callables on the worker's
    MAIN thread (worker_main.main() drains the queue in run_forever).

    Tasks must execute on the main thread so that non-force
    ray_tpu.cancel() can interrupt C-blocked calls: CPython delivers signal
    handlers only to the main thread, and a handler that raises aborts the
    in-flight blocking call (PEP 475). The reference runs tasks on the
    worker main thread and cancels via KeyboardInterrupt for exactly this
    reason (_raylet.pyx task_execution_handler + CancelTask).

    Duck-types concurrent.futures.Executor far enough for
    loop.run_in_executor (submit) and CoreWorker teardown (shutdown)."""

    def __init__(self):
        import queue

        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._stopped = False

    def submit(self, fn, *args, **kwargs):
        import concurrent.futures

        fut = concurrent.futures.Future()
        self._q.put((fut, fn, args, kwargs))
        return fut

    def run_forever(self):
        while not self._stopped:
            item = self._q.get()
            if item is None:
                break
            fut, fn, args, kwargs = item
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                result = fn(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001 — ship to the waiter
                fut.set_exception(e)
            else:
                fut.set_result(result)

    def shutdown(self, wait=True, cancel_futures=False):
        self._stopped = True
        self._q.put(None)


class WorkerExecutor:
    def __init__(self, core_worker, raylet_client):
        self.cw = core_worker
        self.raylet = raylet_client
        self._loop = core_worker._io.loop
        self._actor_queue: asyncio.Queue | None = None
        self._consumer_task = None
        self._concurrency_pool: ThreadPoolExecutor | None = None
        server = core_worker.server
        server.register("push_task", self.rpc_push_task)
        server.register("actor_call", self.rpc_actor_call)
        server.register("kill_self", self.rpc_kill_self)
        server.register("lease_exec", self.rpc_lease_exec)
        server.register("lease_ping", self.rpc_lease_ping)
        server.register("cancel_exec", self.rpc_cancel_exec)
        # Leased-task pipeline (reference: direct task transport worker side,
        # core_worker.cc task receiver): owners ship batches of specs; we
        # execute FIFO and push completion payloads back, coalescing results
        # that finish while a previous report RPC is still in flight.
        self._lease_buf: list = []
        self._lease_event: asyncio.Event | None = None
        self._lease_task = None
        self._done_buf: list = []
        self._done_flushing = False

    def _safe_execute(self, spec):
        """execute_task catches everything inside its own try; anything that
        escapes is either a cancellation async-exc that landed a few
        bytecodes late (after the task body returned — the tombstone for
        spec.task_id is still set because the FINISHED path never consumes
        it) or a genuine internal error. Only the former becomes a
        cancelled payload; misreporting an internal error as CANCELLED
        would suppress the owner's retries and hide the real failure."""
        from ray_tpu._private import serialization
        from ray_tpu.exceptions import TaskCancelledError, TaskError

        try:
            return self.cw.execute_task(spec)
        except BaseException as e:  # noqa: BLE001 — must not kill the loop
            if (
                isinstance(e, TaskCancelledError)
                and spec.task_id in self.cw._cancelled_tasks
            ):
                self.cw._cancelled_tasks.discard(spec.task_id)
                return self.cw.cancelled_payload(spec)
            logger.exception("task %s escaped execute_task", spec.task_id[:8])
            err = TaskError.from_exception(e, task_name=spec.name)
            return {
                "task_id": spec.task_id,
                "results": [],
                "error": serialization.serialize(err).to_bytes(),
                "duration_s": 0.0,
            }

    # ---- normal / actor-creation tasks ----

    async def rpc_push_task(self, req):
        from ray_tpu._private.task_spec import TaskSpec

        spec = TaskSpec.from_wire(req["spec"])
        asyncio.ensure_future(self._execute_pushed(spec))
        return {"ok": True}

    async def _execute_pushed(self, spec):
        loop = asyncio.get_event_loop()
        payload = await loop.run_in_executor(self.cw._executor, self._safe_execute, spec)
        if spec.is_actor_creation():
            await self._finish_actor_creation(spec, payload)
        else:
            # Report to owner, then free the lease.
            await self._report_to_owner(spec, payload)
            try:
                await self.raylet.acall("task_finished", {"worker_id": self.cw.worker_id})
            except Exception:
                pass

    async def _report_to_owner(self, spec, payload):
        if spec.owner_addr is None:
            return
        try:
            owner = self.cw._owner_client(tuple(spec.owner_addr))
            await owner.acall("task_done", payload)
        except Exception:
            logger.warning("could not report task %s to owner", spec.task_id[:8])

    async def _finish_actor_creation(self, spec, payload):
        if payload.get("error") is None:
            if spec.max_concurrency > 1:
                self._concurrency_pool = ThreadPoolExecutor(
                    max_workers=spec.max_concurrency, thread_name_prefix="actor-cg"
                )
            else:
                self._actor_queue = asyncio.Queue()
                self._consumer_task = asyncio.ensure_future(self._actor_consumer())
            resp = await self.cw.gcs.acall(
                "actor_alive",
                {
                    "actor_id": spec.actor_id,
                    "address": list(self.cw.address),
                    "node_id": self.cw.node_id,
                    "worker_id": self.cw.worker_id,
                },
            )
            if resp.get("duplicate"):
                # Another worker already owns this actor (e.g. GCS-restart
                # recovery raced an in-flight creation); the incumbent wins.
                logger.warning("duplicate actor %s; exiting", spec.actor_id[:8])
                os._exit(0)
            await self.raylet.acall("actor_ready", {"worker_id": self.cw.worker_id})
        else:
            logger.error("actor %s __init__ failed", spec.actor_id[:8])
            try:
                await self.cw.gcs.acall(
                    "report_worker_death",
                    {"actor_ids": [spec.actor_id], "reason": "actor __init__ raised"},
                )
            finally:
                os._exit(1)

    # ---- leased normal tasks (reference: direct_task_transport worker side) ----

    async def rpc_lease_ping(self, req):
        return {"ok": True}

    async def rpc_lease_exec(self, req):
        from ray_tpu._private.task_spec import TaskSpec

        if self._lease_event is None:
            self._lease_event = asyncio.Event()
        for wire in req["specs"]:
            self._lease_buf.append(TaskSpec.from_wire(wire))
        self._lease_event.set()
        if self._lease_task is None or self._lease_task.done():
            self._lease_task = asyncio.ensure_future(self._lease_consumer())
        # Ack = accepted-into-queue, not executed: the owner's flow control
        # is per-task (tasks_done), so the ack must not wait on execution.
        return {"accepted": len(req["specs"])}

    async def _lease_consumer(self):
        loop = asyncio.get_event_loop()
        while True:
            while not self._lease_buf:
                self._lease_event.clear()
                await self._lease_event.wait()
            spec = self._lease_buf.pop(0)
            payload = await loop.run_in_executor(self.cw._executor, self._safe_execute, spec)
            self._done_buf.append((tuple(spec.owner_addr), payload))
            if not self._done_flushing:
                self._done_flushing = True
                asyncio.ensure_future(self._flush_done())

    async def _flush_done(self):
        """Deliver completion payloads, re-queuing on failure: dropping a
        batch would leave the owner's get() hanging forever — its lease
        probe only pings THIS worker, which is alive. Bounded retries: a
        permanently unreachable owner is dead, and dead owners' results
        are garbage."""
        try:
            attempts = 0
            while self._done_buf:
                batch, self._done_buf = self._done_buf, []
                by_owner: dict = {}
                for owner_addr, payload in batch:
                    by_owner.setdefault(owner_addr, []).append(payload)
                failed: list = []
                for owner_addr, payloads in by_owner.items():
                    try:
                        owner = self.cw._owner_client(owner_addr)
                        await owner.acall("tasks_done", {"batch": payloads})
                    except Exception:
                        logger.warning(
                            "lease result delivery to %s failed (%d results)",
                            owner_addr, len(payloads),
                        )
                        failed.extend((owner_addr, p) for p in payloads)
                if failed:
                    attempts += 1
                    if attempts >= 12:  # ~60s of owner unreachability
                        # Dropping silently would hang a still-alive owner
                        # forever (its probe pings US, and we're healthy).
                        # Dying converts the situation into worker-death:
                        # the raylet revokes the lease and the owner's
                        # failover re-runs the tasks (or, if the owner is
                        # truly dead, nothing is lost).
                        logger.error(
                            "exiting: %d lease results undeliverable to owner",
                            len(failed),
                        )
                        os._exit(1)
                    self._done_buf = failed + self._done_buf
                    await asyncio.sleep(min(5.0, 0.5 * attempts))
        finally:
            self._done_flushing = False

    # ---- direct actor calls ----

    async def rpc_actor_call(self, req):
        from ray_tpu._private.task_spec import TaskSpec

        spec = TaskSpec.from_wire(req["spec"])
        loop = asyncio.get_event_loop()
        if self._concurrency_pool is not None:
            # Threaded actor: concurrent execution, no ordering guarantee
            # (reference: concurrency groups / max_concurrency > 1).
            return await loop.run_in_executor(
                self._concurrency_pool, self._safe_execute, spec
            )
        if self._actor_queue is None:
            # Call raced actor initialisation; serialize behind creation.
            return await loop.run_in_executor(self.cw._executor, self._safe_execute, spec)
        fut = loop.create_future()
        self._actor_queue.put_nowait((spec, fut))  # pre-await: preserves order
        return await fut

    async def _actor_consumer(self):
        """Ordered execution queue (reference: actor_scheduling_queue.h:40)."""
        loop = asyncio.get_event_loop()
        while True:
            spec, fut = await self._actor_queue.get()
            try:
                payload = await loop.run_in_executor(
                    self.cw._executor, self._safe_execute, spec
                )
                if not fut.done():
                    fut.set_result(payload)
            except Exception as e:
                if not fut.done():
                    fut.set_exception(e)

    # ---- cancellation (reference: core_worker.cc HandleCancelTask) ----

    async def rpc_cancel_exec(self, req):
        """Recall a task delivered to this worker: dequeue if still queued
        (lease buffer / actor queue), interrupt if running, tombstone if it
        has not arrived yet; recursively cancel children this worker owns."""
        task_id = req["task_id"]
        force = bool(req.get("force"))
        recursive = req.get("recursive", True)
        handled = False
        # Queued leased task, not yet started.
        for i, s in enumerate(self._lease_buf):
            if s.task_id == task_id:
                spec = self._lease_buf.pop(i)
                self._done_buf.append((tuple(spec.owner_addr), self.cw.cancelled_payload(spec)))
                if not self._done_flushing:
                    self._done_flushing = True
                    asyncio.ensure_future(self._flush_done())
                handled = True
                break
        # Queued actor call, not yet dispatched (reference: pre-dispatch
        # actor-task cancellation).
        if not handled and self._actor_queue is not None:
            kept, target = [], None
            while not self._actor_queue.empty():
                item = self._actor_queue.get_nowait()
                if item[0].task_id == task_id:
                    target = item
                else:
                    kept.append(item)
            for item in kept:
                self._actor_queue.put_nowait(item)
            if target is not None:
                spec, fut = target
                if not fut.done():
                    fut.set_result(self.cw.cancelled_payload(spec))
                handled = True
        # Running right now.
        if not handled:
            handled = self.cw.interrupt_running_task(task_id, force=force)
        if not handled:
            # Not here (yet): tombstone so a late arrival is dropped at
            # execution entry and reported as cancelled.
            self.cw.mark_cancelled(task_id)
        if recursive:
            self.cw.cancel_children_of(task_id, force, recursive)
        return {"found": handled}

    async def rpc_kill_self(self, req):
        def _die():
            os._exit(0)

        asyncio.get_event_loop().call_later(0.05, _die)
        return {"ok": True}


def _apply_runtime_env(raw: str | None):
    """Apply this worker's runtime env before anything else imports.

    Reference: _private/runtime_env/ plugins — env_vars, working_dir and
    py_modules are fully supported; pip/conda/container provisioning needs
    package installation (network) and is rejected up-front so tasks fail
    with a clear error instead of silently running in the wrong env.
    """
    if not raw:
        return
    from ray_tpu._private import runtime_env_plugins
    from ray_tpu.runtime_env import UNSUPPORTED_FIELDS

    renv = json.loads(raw)
    # Built-in fields FIRST: shipped plugin classes usually live in
    # py_modules, so sys.path must be extended before plugin import.
    for key, value in (renv.get("env_vars") or {}).items():
        os.environ[str(key)] = str(value)
    working_dir = renv.get("working_dir")
    if working_dir:
        os.chdir(working_dir)
        sys.path.insert(0, working_dir)
    for mod_path in renv.get("py_modules") or []:
        sys.path.insert(0, mod_path)
    runtime_env_plugins.ensure_loaded(renv, strict=True)
    unsupported = (set(renv) & UNSUPPORTED_FIELDS) - runtime_env_plugins.plugin_fields()
    if unsupported:
        raise RuntimeError(
            f"runtime_env fields {sorted(unsupported)} require package "
            "installation, which this environment does not support; "
            "pre-install dependencies on the node image instead"
        )
    try:
        runtime_env_plugins.apply_plugins(
            renv, os.environ.get("RAY_TPU_SESSION_DIR", "/tmp/ray_tpu")
        )
    except Exception:
        logger.exception("runtime-env plugin application failed")
        raise


def main():
    import time as _time

    _boot_t0 = _time.monotonic()
    _trace = os.environ.get("RAY_TPU_BOOT_TRACE")

    def _mark(label):
        if _trace:
            print(f"[boot-trace {os.getpid()}] {label} +{(_time.monotonic() - _boot_t0) * 1e3:.1f}ms",
                  file=sys.stderr, flush=True)

    logging.basicConfig(
        level=logging.INFO,
        format=f"[worker %(process)d] %(levelname)s %(name)s: %(message)s",
    )
    # `ray_tpu stack` sends SIGUSR1; the dump lands in this worker's .err log
    # (the reference shells out to py-spy from the dashboard agent — not in
    # this image, so workers self-report via faulthandler).
    try:
        import faulthandler
        import signal

        faulthandler.register(signal.SIGUSR1, file=sys.stderr, all_threads=True)
    except Exception:
        pass
    _apply_runtime_env(os.environ.get("RAY_TPU_RUNTIME_ENV"))
    worker_id = os.environ["RAY_TPU_WORKER_ID"]
    node_id = os.environ["RAY_TPU_NODE_ID"]
    raylet_addr = json.loads(os.environ["RAY_TPU_RAYLET_ADDR"])
    gcs_addr = json.loads(os.environ["RAY_TPU_GCS_ADDR"])
    arena_name = os.environ["RAY_TPU_ARENA_NAME"]
    session_dir = os.environ["RAY_TPU_SESSION_DIR"]

    # Test runs pin jax to CPU: a sitecustomize may force jax_platforms to a
    # TPU plugin via jax.config.update, which only another config.update can
    # override (see tests/conftest.py). If no sitecustomize imported jax into
    # this process, the env var governs the (lazy) first import instead —
    # eagerly importing jax here cost ~2s on EVERY worker spawn, dominating
    # the actor-creation envelope.
    from ray_tpu._private.jax_platform import apply_forced_jax_platforms

    apply_forced_jax_platforms()

    from ray_tpu._private import worker_context
    from ray_tpu._private.core_worker import WORKER, CoreWorker
    from ray_tpu._private.ids import JobID

    _mark("imports")
    worker_env = os.environ.get("RAY_TPU_RUNTIME_ENV")
    cw = CoreWorker(
        mode=WORKER,
        gcs_address=gcs_addr,
        raylet_address=raylet_addr,
        arena_name=arena_name,
        node_id=node_id,
        session_dir=session_dir,
        job_id=JobID.from_int(0),
        worker_id=worker_id,
        # Nested tasks inherit this worker's runtime env by default
        # (reference semantics: children inherit the parent's env).
        job_runtime_env=json.loads(worker_env) if worker_env else None,
    )
    worker_context.set_core_worker(cw)
    _mark("core_worker")
    # Tasks run on THIS (main) thread: swap the default pool executor for
    # the main-thread drain loop and install the cancel signal handler —
    # both before register_worker, after which tasks may arrive.
    from ray_tpu.exceptions import TaskCancelledError

    cw._executor.shutdown(wait=False)
    cw._executor = _MainThreadExecutor()
    cw._main_thread_ident = threading.get_ident()

    def _cancel_handler(signum, frame):
        # Raise ONLY if the cancel target is still the task running on this
        # thread — a signal that lands after the task finished (or while
        # idle in the queue) is a no-op and the interrupted blocking call
        # is retried per PEP 475.
        target = cw._main_cancel_target
        if target is not None and target == cw._main_task_id:
            cw._main_cancel_target = None
            raise TaskCancelledError("task was cancelled by ray_tpu.cancel()")

    import signal

    signal.signal(signal.SIGUSR2, _cancel_handler)
    executor = WorkerExecutor(cw, cw.raylet)
    reply = cw.raylet.call(
        "register_worker",
        {"worker_id": worker_id, "address": list(cw.address), "pid": os.getpid()},
    )
    if not (reply or {}).get("ok", True):
        # The raylet retired this worker id (e.g. a zygote spawn it gave up
        # on and replaced) — we're an orphan; exit instead of double-serving.
        sys.exit(0)
    _mark("registered")
    # Workers exit if their parent raylet dies (reference: core_worker.cc:926
    # ExitIfParentRayletDies).
    def _watch_raylet():
        import time

        while True:
            time.sleep(2.0)
            try:
                cw.raylet.call("store_contains", {"object_id": "00" * 28}, timeout=5)
            except Exception:
                logger.warning("parent raylet unreachable; worker exiting")
                os._exit(1)

    threading.Thread(target=_watch_raylet, daemon=True).start()
    cw._executor.run_forever()


if __name__ == "__main__":
    main()
