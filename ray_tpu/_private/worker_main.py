"""Worker process entry point.

TPU-native analog of the reference's default_worker.py + the Cython task
execution handler (python/ray/_private/workers/default_worker.py,
_raylet.pyx:1791 task_execution_handler): spawned by the raylet's worker pool,
registers back, then serves

- ``push_task`` from the raylet (normal + actor-creation tasks)
- ``actor_call`` directly from callers (the direct actor transport —
  reference: direct_actor_task_submitter.h:67 server side,
  actor_scheduling_queue.h:40 ordering)
- ``kill_self`` for ray_tpu.kill / actor teardown.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor

from ray_tpu._private.concurrency import any_thread, loop_only

logger = logging.getLogger(__name__)


def _set_result_if_pending(fut, payload):
    if not fut.done():
        fut.set_result(payload)


class _MainThreadExecutor:
    """Executor-protocol shim that runs submitted callables on the worker's
    MAIN thread (worker_main.main() drains the queue in run_forever).

    Tasks must execute on the main thread so that non-force
    ray_tpu.cancel() can interrupt C-blocked calls: CPython delivers signal
    handlers only to the main thread, and a handler that raises aborts the
    in-flight blocking call (PEP 475). The reference runs tasks on the
    worker main thread and cancels via KeyboardInterrupt for exactly this
    reason (_raylet.pyx task_execution_handler + CancelTask).

    Duck-types concurrent.futures.Executor far enough for
    loop.run_in_executor (submit) and CoreWorker teardown (shutdown)."""

    def __init__(self):
        import queue

        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._stopped = False

    @any_thread
    def submit(self, fn, *args, **kwargs):
        import concurrent.futures

        fut = concurrent.futures.Future()
        self._q.put((fut, fn, args, kwargs))
        return fut

    @any_thread
    def submit_callback(self, fn, args, callback):
        """Zero-Future fast path: run fn(*args) on the exec thread, deliver
        the result to callback(result) ON THAT THREAD (callers hop back to
        their loop themselves). Saves the cf.Future + wrap_future + done-
        callback machinery per task — measurable on the lease hot loop."""
        self._q.put((None, fn, args, callback))

    def run_forever(self):
        while not self._stopped:
            item = self._q.get()
            if item is None:
                break
            fut, fn, args, kwargs = item
            if fut is None:  # submit_callback fast path
                callback = kwargs
                try:
                    result = fn(*args)
                except BaseException:  # noqa: BLE001 — fn is _safe_execute-
                    # class (never raises); a raise here is a framework bug,
                    # but the callback must still fire or a task is lost.
                    logger.exception("submit_callback fn raised")
                    result = None
                try:
                    callback(result)
                except BaseException:  # noqa: BLE001
                    logger.exception("submit_callback delivery failed")
                continue
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                result = fn(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001 — ship to the waiter
                fut.set_exception(e)
            else:
                fut.set_result(result)

    def shutdown(self, wait=True, cancel_futures=False):
        self._stopped = True
        self._q.put(None)


class WorkerExecutor:
    def __init__(self, core_worker, raylet_client):
        self.cw = core_worker
        self.raylet = raylet_client
        self._loop = core_worker._io.loop
        self._concurrency_pool: ThreadPoolExecutor | None = None
        server = core_worker.server
        server.register("push_task", self.rpc_push_task)
        server.register("actor_call", self.rpc_actor_call)
        server.register("actor_has_task", self.rpc_actor_has_task)
        server.register("kill_self", self.rpc_kill_self)
        server.register("lease_exec", self.rpc_lease_exec)
        server.register("lease_ping", self.rpc_lease_ping)
        server.register("cancel_exec", self.rpc_cancel_exec)
        # Channel-loop mode (compiled execution graphs, dag/compiled.py):
        # install starts a resident loop on a dedicated thread that serves
        # channel iterations with no per-call task spec / ObjectRef / raylet
        # RPC; classic calls keep flowing through the main exec queue.
        server.register("channel_loop_install", self.rpc_channel_loop_install)
        server.register("channel_loop_stop", self.rpc_channel_loop_stop)
        server.register("channel_loop_stats", self.rpc_channel_loop_stats)
        self._channel_loops: dict = {}
        # Leased-task pipeline (reference: direct task transport worker side,
        # core_worker.cc task receiver): owners ship batches of specs; we
        # execute FIFO (the main-thread exec queue) and push completion
        # payloads back, coalescing results that finish while a previous
        # report RPC is still in flight.
        self._done_buf: list = []
        self._done_flushing = False
        # Queued-but-unstarted specs (task_id -> ("lease", owner_addr, spec)
        # or ("actor", fut, spec)): lets pre-dispatch cancellation resolve
        # the caller IMMEDIATELY instead of waiting behind the running task.
        # Entries pop at execution start (exec thread; GIL-atomic dict ops).
        self._fast_queued: dict = {}
        # Actor-call at-least-once state: received task ids (duplicate
        # frames must NOT re-execute the method) and a bounded cache of
        # recent results (re-answers a duplicate/probe after the original
        # response frame was lost). See rpc_actor_call/rpc_actor_has_task.
        from collections import deque

        from ray_tpu._private.ids import BoundedIdSet

        self._actor_call_seen = BoundedIdSet(cap=4096)
        self._actor_results: dict = {}
        self._actor_results_order: deque = deque()

    def _safe_execute(self, spec):
        """execute_task catches everything inside its own try; anything that
        escapes is either a cancellation async-exc that landed a few
        bytecodes late (after the task body returned — the tombstone for
        spec.task_id is still set because the FINISHED path never consumes
        it) or a genuine internal error. Only the former becomes a
        cancelled payload; misreporting an internal error as CANCELLED
        would suppress the owner's retries and hide the real failure."""
        from ray_tpu._private import serialization
        from ray_tpu.exceptions import TaskCancelledError, TaskError

        try:
            return self.cw.execute_task(spec)
        except BaseException as e:  # noqa: BLE001 — must not kill the loop
            if (
                isinstance(e, TaskCancelledError)
                and spec.task_id in self.cw._cancelled_tasks
            ):
                self.cw._cancelled_tasks.discard(spec.task_id)
                return self.cw.cancelled_payload(spec)
            logger.exception("task %s escaped execute_task", spec.task_id[:8])
            err = TaskError.from_exception(e, task_name=spec.name)
            return {
                "task_id": spec.task_id,
                "results": [],
                "error": serialization.serialize(err).to_bytes(),
                "duration_s": 0.0,
            }

    # ---- normal / actor-creation tasks ----

    async def rpc_push_task(self, req):
        from ray_tpu._private.task_spec import TaskSpec

        spec = TaskSpec.from_wire(req["spec"])
        if spec.hop_ts:
            spec.hop_ts["worker_recv"] = time.monotonic()
        asyncio.ensure_future(self._execute_pushed(spec))
        return {"ok": True}

    async def _execute_pushed(self, spec):
        loop = asyncio.get_event_loop()
        payload = await loop.run_in_executor(self.cw._executor, self._safe_execute, spec)
        if spec.is_actor_creation():
            await self._finish_actor_creation(spec, payload)
        else:
            if payload.get("hop") is not None:
                payload["hop"]["reply"] = time.monotonic()
            payload["cid"] = os.urandom(8).hex()  # owner-side duplicate filter
            # Piggybacked completion: once the task_done frame is ON THE
            # WIRE, task_finished runs concurrently with the owner's ack
            # (was two serial RTTs per classic-path task). Ordering is
            # load-bearing: freeing the worker FIRST would let a crash in
            # the window clear worker.current_task at the raylet, so a
            # death before the owner got the result would send no
            # task_failed and the owner would wait for the slow lost-task
            # sweep. task_finished stays an acknowledged, retried acall — a
            # one-way push frame lost to a resetting connection would
            # strand the worker 'busy' forever.
            sent = None
            if spec.owner_addr is not None:
                try:
                    owner = self.cw._owner_client(tuple(spec.owner_addr))
                    sent = owner.send_nowait("task_done", payload)
                except Exception:
                    sent = None
            if sent is None:
                # Cold or backpressured owner connection: keep the fully
                # crash-safe serial order (owner ack, then free the worker).
                await self._report_to_owner(spec, payload)
                try:
                    await self.raylet.acall(
                        "task_finished", {"worker_id": self.cw.worker_id}
                    )
                except Exception:
                    pass
            else:
                fin = asyncio.ensure_future(
                    self.raylet.acall("task_finished", {"worker_id": self.cw.worker_id})
                )
                fin.add_done_callback(lambda t: t.cancelled() or t.exception())
                try:
                    # Bounded ack wait: a task_done frame lost WITHOUT a
                    # connection reset (receiver dropped it, chaos drop)
                    # used to park this await forever and the owner's get()
                    # with it until the lost-task sweep. On timeout the
                    # stale pending entry is unregistered and the payload
                    # re-delivers through the acked retrying path (the
                    # owner drops the duplicate by cid).
                    await asyncio.wait_for(
                        sent, self.cw.cfg.task_done_ack_timeout_s
                    )
                except Exception:
                    # Connection failed or the ack never came: re-deliver
                    # through the retrying path (owner dedupes by cid).
                    seq = getattr(sent, "_rtpu_seq", None)
                    if seq is not None and spec.owner_addr is not None:
                        try:
                            self.cw._owner_client(
                                tuple(spec.owner_addr)
                            )._pending.pop(seq, None)
                        except Exception:
                            pass
                    await self._report_to_owner(spec, payload)

    async def _report_to_owner(self, spec, payload):
        if spec.owner_addr is None:
            return
        try:
            owner = self.cw._owner_client(tuple(spec.owner_addr))
            # Per-attempt ack bound so a silently lost frame retries (acall
            # re-sends on TimeoutError; the owner dedupes by cid) instead
            # of parking this coroutine on an unresolvable future.
            await owner.acall(
                "task_done", payload, timeout=self.cw.cfg.task_done_ack_timeout_s
            )
        except Exception:
            logger.warning("could not report task %s to owner", spec.task_id[:8])

    async def _finish_actor_creation(self, spec, payload):
        if payload.get("error") is None:
            if spec.max_concurrency > 1:
                self._concurrency_pool = ThreadPoolExecutor(
                    max_workers=spec.max_concurrency, thread_name_prefix="actor-cg"
                )
            # max_concurrency == 1 needs no queue of its own: ordered calls
            # ride the main-thread exec queue (rpc_actor_call fast path).
            resp = await self.cw.gcs.acall(
                "actor_alive",
                {
                    "actor_id": spec.actor_id,
                    "address": list(self.cw.address),
                    "node_id": self.cw.node_id,
                    "worker_id": self.cw.worker_id,
                },
            )
            if resp.get("duplicate"):
                # Another worker already owns this actor (e.g. GCS-restart
                # recovery raced an in-flight creation); the incumbent wins.
                logger.warning("duplicate actor %s; exiting", spec.actor_id[:8])
                os._exit(0)
            await self.raylet.acall("actor_ready", {"worker_id": self.cw.worker_id})
        else:
            logger.error("actor %s __init__ failed", spec.actor_id[:8])
            try:
                await self.cw.gcs.acall(
                    "report_worker_death",
                    {"actor_ids": [spec.actor_id], "reason": "actor __init__ raised"},
                )
            finally:
                os._exit(1)

    # ---- leased normal tasks (reference: direct_task_transport worker side) ----

    async def rpc_lease_ping(self, req):
        return {"ok": True}

    async def rpc_lease_exec(self, req):
        from ray_tpu._private.task_spec import TaskSpec

        specs = [TaskSpec.from_wire(wire) for wire in req["specs"]]
        now = time.monotonic()
        for spec in specs:
            if spec.hop_ts:
                spec.hop_ts["worker_recv"] = now
        ex = self.cw._executor
        if hasattr(ex, "submit_callback"):
            # Hot loop: specs go straight onto the main-thread exec queue
            # (FIFO preserved — one queue, one thread) and completions hop
            # back with a single call_soon_threadsafe each. No consumer
            # coroutine, no cf.Future per task.
            import functools

            for spec in specs:
                self._fast_queued[spec.task_id] = ("lease", tuple(spec.owner_addr), spec)
                ex.submit_callback(
                    self._fast_execute,
                    (spec,),
                    functools.partial(
                        self._lease_result_from_thread, tuple(spec.owner_addr), spec
                    ),
                )
        else:
            # Fallback executors (no submit_callback) are single-worker
            # ThreadPoolExecutors — submission order IS execution order.
            loop = asyncio.get_event_loop()
            for spec in specs:
                asyncio.ensure_future(self._lease_exec_fallback(loop, spec))
        # Ack = accepted-into-queue, not executed: the owner's flow control
        # is per-task (tasks_done), so the ack must not wait on execution.
        return {"accepted": len(specs)}

    async def _lease_exec_fallback(self, loop, spec):
        payload = await loop.run_in_executor(self.cw._executor, self._safe_execute, spec)
        self._lease_done(tuple(spec.owner_addr), payload)

    def _fast_execute(self, spec):
        """Exec-thread entry: unregister from the queued set, then run.
        A cancel that raced us already delivered a cancelled payload and
        tombstoned the id — execute_task's entry check drops the body and
        the duplicate completion is ignored by the owner (pending popped)."""
        self._fast_queued.pop(spec.task_id, None)
        return self._safe_execute(spec)

    def _bug_payload(self, spec):
        """A completion for a spec whose execution path itself broke:
        dropping it instead would hang the owner forever (its lease probe
        pings THIS worker, which is alive)."""
        from ray_tpu._private import serialization
        from ray_tpu.exceptions import TaskError

        err = TaskError.from_exception(
            RuntimeError("worker framework error during task execution"),
            task_name=spec.name,
        )
        return {
            "task_id": spec.task_id,
            "results": [],
            "error": serialization.serialize(err).to_bytes(),
            "duration_s": 0.0,
        }

    @any_thread
    def _lease_result_from_thread(self, owner_addr, spec, payload):
        """Runs on the exec thread; marshal the completion to the loop."""
        if payload is None:  # submit_callback swallowed a framework bug
            payload = self._bug_payload(spec)
        self._loop.call_soon_threadsafe(self._lease_done, owner_addr, payload)

    @loop_only
    def _lease_done(self, owner_addr, payload):
        if payload.get("hop") is not None:
            payload["hop"]["reply"] = time.monotonic()
        # Delivery here is at-least-once (both the direct-send fallback and
        # _flush_done re-send payloads whose connection failed after the
        # frame may already have arrived); the cid lets the owner drop the
        # duplicates instead of double-consuming retry budget.
        payload.setdefault("cid", os.urandom(8).hex())
        # Clear pipe + warm connection: write the tasks_done frame NOW
        # (zero scheduling between completion and the wire). Failures fall
        # back into the buffered retry path below, which is also taken
        # whenever a flush is already in flight (keeps rough FIFO).
        if not self._done_buf and not self._done_flushing:
            fut = None
            try:
                owner = self.cw._owner_client(owner_addr)
                fut = owner.send_nowait("tasks_done", {"batch": [payload]})
            except Exception:
                fut = None
            if fut is not None:
                def _delivered(f, oa=owner_addr, p=payload):
                    if f.cancelled() or f.exception() is not None:
                        self._lease_done_buffered(oa, p)

                fut.add_done_callback(_delivered)

                # Ack watchdog: a tasks_done frame lost WITHOUT a reset
                # (silent receiver drop, chaos drop) resolves this future
                # never — the owner's get() used to hang forever because
                # its lease probe pings THIS worker, which is alive.
                # Cancelling routes into _delivered -> the acked retrying
                # path (owner dedupes by cid).
                def _ack_timeout(f=fut, oa=owner_addr):
                    if f.done():
                        return
                    seq = getattr(f, "_rtpu_seq", None)
                    if seq is not None:
                        try:
                            self.cw._owner_client(oa)._pending.pop(seq, None)
                        except Exception:
                            pass
                    f.cancel()

                self._loop.call_later(
                    self.cw.cfg.task_done_ack_timeout_s, _ack_timeout
                )
                return
        self._lease_done_buffered(owner_addr, payload)

    @loop_only
    def _lease_done_buffered(self, owner_addr, payload):
        self._done_buf.append((owner_addr, payload))
        if not self._done_flushing:
            self._done_flushing = True
            asyncio.ensure_future(self._flush_done())

    async def _flush_done(self):
        """Deliver completion payloads, re-queuing on failure: dropping a
        batch would leave the owner's get() hanging forever — its lease
        probe only pings THIS worker, which is alive. Bounded retries: a
        permanently unreachable owner is dead, and dead owners' results
        are garbage."""
        try:
            attempts = 0
            while self._done_buf:
                batch, self._done_buf = self._done_buf, []
                by_owner: dict = {}
                for owner_addr, payload in batch:
                    by_owner.setdefault(owner_addr, []).append(payload)
                failed: list = []
                for owner_addr, payloads in by_owner.items():
                    try:
                        owner = self.cw._owner_client(owner_addr)
                        batch = {"batch": payloads}
                        ack = self.cw.cfg.task_done_ack_timeout_s
                        fut = owner.send_nowait("tasks_done", batch)
                        if fut is not None:
                            # Bounded ack wait (silent-loss heal; the
                            # timeout path re-queues, owner dedupes by cid).
                            try:
                                await asyncio.wait_for(fut, ack)
                            except asyncio.TimeoutError:
                                seq = getattr(fut, "_rtpu_seq", None)
                                if seq is not None:
                                    owner._pending.pop(seq, None)
                                raise
                        else:
                            await owner.acall("tasks_done", batch, timeout=ack)
                    except Exception:
                        logger.warning(
                            "lease result delivery to %s failed (%d results)",
                            owner_addr, len(payloads),
                        )
                        failed.extend((owner_addr, p) for p in payloads)
                if failed:
                    attempts += 1
                    if attempts >= 12:  # ~60s of owner unreachability
                        # Dropping silently would hang a still-alive owner
                        # forever (its probe pings US, and we're healthy).
                        # Dying converts the situation into worker-death:
                        # the raylet revokes the lease and the owner's
                        # failover re-runs the tasks (or, if the owner is
                        # truly dead, nothing is lost).
                        logger.error(
                            "exiting: %d lease results undeliverable to owner",
                            len(failed),
                        )
                        os._exit(1)
                    self._done_buf = failed + self._done_buf
                    await asyncio.sleep(min(5.0, 0.5 * attempts))
        finally:
            self._done_flushing = False

    # ---- direct actor calls ----

    async def rpc_actor_call(self, req):
        from ray_tpu._private.task_spec import TaskSpec

        spec = TaskSpec.from_wire(req["spec"])
        # At-least-once dedupe: the owner resends an actor_call whose frame
        # it believes lost (probe-and-resend in _drive_actor_call), and the
        # wire itself can duplicate under chaos. Without this tombstone a
        # duplicated frame EXECUTED THE METHOD TWICE — user-visible state
        # mutated twice. The duplicate is answered from the result cache
        # when the first execution already finished, else with a dup marker
        # (the live execution's response rides the original request).
        tid = spec.task_id
        if tid in self._actor_call_seen:
            cached = self._actor_results.get(tid)
            if cached is not None:
                return cached
            return {"dup": True, "task_id": tid}
        self._actor_call_seen.add(tid)
        if spec.hop_ts:
            spec.hop_ts["worker_recv"] = time.monotonic()
        loop = asyncio.get_event_loop()
        if self._concurrency_pool is not None:
            # Threaded actor: concurrent execution, no ordering guarantee
            # (reference: concurrency groups / max_concurrency > 1).
            return self._finish_actor_call(tid, await loop.run_in_executor(
                self._concurrency_pool, self._safe_execute, spec
            ))
        ex = self.cw._executor
        if hasattr(ex, "submit_callback"):
            # Hot loop: straight onto the main-thread exec queue (FIFO =
            # actor order; creation rides the same queue, so calls racing
            # init serialize behind it automatically). One threadsafe hop
            # back, no cf.Future. Pre-dispatch cancellation resolves the
            # future immediately via _fast_queued (see rpc_cancel_exec).
            fut = loop.create_future()
            self._fast_queued[spec.task_id] = ("actor", fut, spec)

            def deliver(payload, _fut=fut, _loop=loop, _spec=spec):
                if payload is None:  # framework bug: never leave fut hanging
                    payload = self._bug_payload(_spec)
                _loop.call_soon_threadsafe(_set_result_if_pending, _fut, payload)

            ex.submit_callback(self._fast_execute, (spec,), deliver)
            return self._finish_actor_call(tid, await fut)
        # Fallback executors are single-worker ThreadPoolExecutors:
        # submission order is execution order.
        return self._finish_actor_call(
            tid,
            await loop.run_in_executor(self.cw._executor, self._safe_execute, spec),
        )

    async def rpc_actor_has_task(self, req):
        """Owner-side loss probe (see _drive_actor_call): has this worker
        ever RECEIVED the call, and if finished, what was its result? The
        probe rides the same FIFO connection as the call itself, so 'never
        received' is proof the frame was lost, not merely late."""
        tid = req["task_id"]
        cached = self._actor_results.get(tid)
        return {
            "has": tid in self._actor_call_seen,
            "result": cached,
        }

    def _finish_actor_call(self, tid: str, payload):
        """Hop stamp + result cache (answers duplicate/probe re-delivery
        after a lost response frame; bounded FIFO)."""
        if payload.get("hop") is not None:
            payload["hop"]["reply"] = time.monotonic()
        self._actor_results[tid] = payload
        self._actor_results_order.append(tid)
        while len(self._actor_results_order) > 512:
            self._actor_results.pop(self._actor_results_order.popleft(), None)
        return payload

    # ---- channel-loop mode (compiled graphs; experimental/channel/) ----

    async def rpc_channel_loop_install(self, req):
        """Bind this actor into a compiled DAG: build the channel endpoints
        and start the resident loop on its own dedicated thread. A separate
        thread (the reference runs accelerated-DAG loops on a background
        execution thread the same way) keeps the actor AVAILABLE: classic
        method calls still run on the main exec queue instead of queuing
        behind the loop forever. Mixing classic calls with compiled stages
        therefore executes them concurrently — same hazard class as
        max_concurrency > 1, and the user opted in by mixing the paths."""
        from ray_tpu.experimental.channel.resident_loop import ChannelLoop

        if self._channel_loops:
            return {
                "error": "actor already participates in a compiled graph; "
                "teardown() the existing CompiledDAG first"
            }
        if self.cw._actor_instance is None:
            return {"error": "channel loops require an actor worker"}
        try:
            loop = ChannelLoop(self.cw, req["loop_id"], req["stages"])
        except Exception as e:  # bad descriptor / unknown method
            return {"error": f"channel loop install failed: {e!r}"}
        self._channel_loops[req["loop_id"]] = loop
        threading.Thread(
            target=loop.run, name="channel-loop", daemon=True
        ).start()
        return {"ok": True}

    async def rpc_channel_loop_stop(self, req):
        """Teardown: stop the resident loop, wait for its thread to exit,
        and drop its reader gates. ok=False (loop still running — e.g. a
        stage method stuck in user code) keeps the loop REGISTERED so a new
        compile cannot double-bind the actor, and tells the driver not to
        free arena blocks the loop may still write."""
        loop = self._channel_loops.pop(req["loop_id"], None)
        if loop is None:
            return {"ok": True, "stopped": False}
        loop.stop()
        try:
            await asyncio.wait_for(loop.exited.wait(), 15)
        except asyncio.TimeoutError:
            self._channel_loops[req["loop_id"]] = loop
            return {"ok": False, "error": "channel loop did not exit within 15s"}
        self.cw.channels.drop(loop.channel_ids)
        # Eager-pushed payloads nobody will ever take (producer raced the
        # stop) must not sit in the inbox until the age sweep.
        for cid in loop.channel_ids:
            self.cw.p2p_inbox.purge_prefix(f"chdev/{cid}/")
        return {"ok": True, "stopped": True}

    async def rpc_channel_loop_stats(self, req):
        """Per-stage stall/busy/resolve split of a resident loop — the
        driver-side bubble-fraction measurement reads it (parallel/
        mpmd_pipeline.py, microbench --pipeline)."""
        loop = self._channel_loops.get(req["loop_id"])
        if loop is None:
            return {"found": False, "stages": []}
        if req.get("reset"):
            import time as _time

            for s in loop.stages:
                s.stall_ns = s.busy_ns = s.resolve_ns = s.iters = 0
                # Stamp the reset so an interval already in flight (a loop
                # blocked in read()) charges only its post-reset portion.
                s.reset_ns = _time.perf_counter_ns()
        return {"found": True, "stages": [s.stats_dict() for s in loop.stages]}

    # ---- cancellation (reference: core_worker.cc HandleCancelTask) ----

    async def rpc_cancel_exec(self, req):
        """Recall a task delivered to this worker: resolve immediately if
        still queued (exec-queue registry), interrupt if running, tombstone
        if it has not arrived yet; recursively cancel children this worker
        owns."""
        task_id = req["task_id"]
        force = bool(req.get("force"))
        recursive = req.get("recursive", True)
        handled = False
        # Queued-but-unstarted (any kind): tombstone FIRST so a racing
        # dequeue drops the body at execute_task entry, then answer the
        # caller NOW — a cancelled call must not wait behind the currently
        # running task. The spec still flows through the exec queue; its
        # duplicate cancelled completion is ignored by the owner (pending
        # already popped) / the already-resolved future.
        entry = None
        if task_id in self._fast_queued:
            # Tombstone BEFORE popping: if the exec thread dequeues the spec
            # in this window, the entry check still drops the body.
            self.cw.mark_cancelled(task_id)
            entry = self._fast_queued.pop(task_id, None)
        if entry is not None:
            if entry[0] == "lease":
                _, owner_addr, spec = entry
                self._lease_done(owner_addr, self.cw.cancelled_payload(spec))
            else:  # actor
                _, fut, spec = entry
                _set_result_if_pending(fut, self.cw.cancelled_payload(spec))
            handled = True
        # Running right now.
        if not handled:
            handled = self.cw.interrupt_running_task(task_id, force=force)
        if not handled:
            # Not here (yet): tombstone so a late arrival is dropped at
            # execution entry and reported as cancelled.
            self.cw.mark_cancelled(task_id)
        if recursive:
            self.cw.cancel_children_of(task_id, force, recursive)
        return {"found": handled}

    async def rpc_kill_self(self, req):
        def _die():
            os._exit(0)

        asyncio.get_event_loop().call_later(0.05, _die)
        return {"ok": True}


def _apply_runtime_env(raw: str | None):
    """Apply this worker's runtime env before anything else imports.

    Reference: _private/runtime_env/ plugins — env_vars, working_dir and
    py_modules are fully supported; pip/conda/container provisioning needs
    package installation (network) and is rejected up-front so tasks fail
    with a clear error instead of silently running in the wrong env.
    """
    if not raw:
        return
    from ray_tpu._private import runtime_env_plugins
    from ray_tpu.runtime_env import UNSUPPORTED_FIELDS

    renv = json.loads(raw)
    # Built-in fields FIRST: shipped plugin classes usually live in
    # py_modules, so sys.path must be extended before plugin import.
    for key, value in (renv.get("env_vars") or {}).items():
        os.environ[str(key)] = str(value)
    working_dir = renv.get("working_dir")
    if working_dir:
        os.chdir(working_dir)
        sys.path.insert(0, working_dir)
    for mod_path in renv.get("py_modules") or []:
        sys.path.insert(0, mod_path)
    runtime_env_plugins.ensure_loaded(renv, strict=True)
    unsupported = (set(renv) & UNSUPPORTED_FIELDS) - runtime_env_plugins.plugin_fields()
    if unsupported:
        raise RuntimeError(
            f"runtime_env fields {sorted(unsupported)} require package "
            "installation, which this environment does not support; "
            "pre-install dependencies on the node image instead"
        )
    try:
        runtime_env_plugins.apply_plugins(
            renv, os.environ.get("RAY_TPU_SESSION_DIR", "/tmp/ray_tpu")
        )
    except Exception:
        logger.exception("runtime-env plugin application failed")
        raise


def main():
    import time as _time

    _boot_t0 = _time.monotonic()
    _trace = os.environ.get("RAY_TPU_BOOT_TRACE")

    def _mark(label):
        if _trace:
            print(f"[boot-trace {os.getpid()}] {label} +{(_time.monotonic() - _boot_t0) * 1e3:.1f}ms",
                  file=sys.stderr, flush=True)

    logging.basicConfig(
        level=logging.INFO,
        format=f"[worker %(process)d] %(levelname)s %(name)s: %(message)s",
    )
    # `ray_tpu stack` sends SIGUSR1; the dump lands in this worker's .err log
    # (the reference shells out to py-spy from the dashboard agent — not in
    # this image, so workers self-report via faulthandler).
    try:
        import faulthandler
        import signal

        faulthandler.register(signal.SIGUSR1, file=sys.stderr, all_threads=True)
    except Exception:
        pass
    _apply_runtime_env(os.environ.get("RAY_TPU_RUNTIME_ENV"))
    worker_id = os.environ["RAY_TPU_WORKER_ID"]
    node_id = os.environ["RAY_TPU_NODE_ID"]
    raylet_addr = json.loads(os.environ["RAY_TPU_RAYLET_ADDR"])
    gcs_addr = json.loads(os.environ["RAY_TPU_GCS_ADDR"])
    arena_name = os.environ["RAY_TPU_ARENA_NAME"]
    session_dir = os.environ["RAY_TPU_SESSION_DIR"]

    # Test runs pin jax to CPU: a sitecustomize may force jax_platforms to a
    # TPU plugin via jax.config.update, which only another config.update can
    # override (see tests/conftest.py). If no sitecustomize imported jax into
    # this process, the env var governs the (lazy) first import instead —
    # eagerly importing jax here cost ~2s on EVERY worker spawn, dominating
    # the actor-creation envelope.
    from ray_tpu._private.jax_platform import apply_forced_jax_platforms

    apply_forced_jax_platforms()

    from ray_tpu._private import worker_context
    from ray_tpu._private.core_worker import WORKER, CoreWorker
    from ray_tpu._private.ids import JobID

    _mark("imports")
    worker_env = os.environ.get("RAY_TPU_RUNTIME_ENV")
    cw = CoreWorker(
        mode=WORKER,
        gcs_address=gcs_addr,
        raylet_address=raylet_addr,
        arena_name=arena_name,
        node_id=node_id,
        session_dir=session_dir,
        job_id=JobID.from_int(0),
        worker_id=worker_id,
        # Nested tasks inherit this worker's runtime env by default
        # (reference semantics: children inherit the parent's env).
        job_runtime_env=json.loads(worker_env) if worker_env else None,
    )
    worker_context.set_core_worker(cw)
    _mark("core_worker")
    # Tasks run on THIS (main) thread: swap the default pool executor for
    # the main-thread drain loop and install the cancel signal handler —
    # both before register_worker, after which tasks may arrive.
    from ray_tpu.exceptions import TaskCancelledError

    cw._executor.shutdown(wait=False)
    cw._executor = _MainThreadExecutor()
    cw._main_thread_ident = threading.get_ident()

    def _cancel_handler(signum, frame):
        # Raise ONLY if the cancel target is still the task running on this
        # thread — a signal that lands after the task finished (or while
        # idle in the queue) is a no-op and the interrupted blocking call
        # is retried per PEP 475.
        target = cw._main_cancel_target
        if target is not None and target == cw._main_task_id:
            cw._main_cancel_target = None
            raise TaskCancelledError("task was cancelled by ray_tpu.cancel()")

    import signal

    signal.signal(signal.SIGUSR2, _cancel_handler)
    # Flight-recorder fatal-signal hook: a terminating signal stamps a final
    # `fatal_signal` event into the mmap ring before the process dies, so
    # `ray_tpu debug dump` shows WHY the ring ends where it does. (SIGKILL
    # needs no hook — the mmap file survives it as-is.)
    from ray_tpu._private import flight_recorder

    flight_recorder.install_signal_dump([signal.SIGTERM])
    executor = WorkerExecutor(cw, cw.raylet)
    reply = cw.raylet.call(
        "register_worker",
        {"worker_id": worker_id, "address": list(cw.address), "pid": os.getpid()},
    )
    if not (reply or {}).get("ok", True):
        # The raylet retired this worker id (e.g. a zygote spawn it gave up
        # on and replaced) — we're an orphan; exit instead of double-serving.
        sys.exit(0)
    _mark("registered")
    # Workers exit if their parent raylet dies (reference: core_worker.cc:926
    # ExitIfParentRayletDies).
    def _watch_raylet():
        import time

        while True:
            time.sleep(2.0)
            try:
                cw.raylet.call("store_contains", {"object_id": "00" * 28}, timeout=5)
            except Exception:
                logger.warning("parent raylet unreachable; worker exiting")
                os._exit(1)

    threading.Thread(target=_watch_raylet, daemon=True).start()
    cw._executor.run_forever()


if __name__ == "__main__":
    main()
