"""Control-plane scale simulation: hundreds of raylet shells in one process.

The real multi-node story (cluster_utils.Cluster) tops out around a dozen
raylets per box — each carries a shm arena, worker pool, object store, and
zygote. This module keeps everything the CONTROL PLANE sees real and stubs
only the data/execution plane:

- SimNode speaks the real GCS wire protocol over real sockets: register,
  versioned delta-sync heartbeats, rejoin with jittered backoff,
  object-location publish — the same code paths (``apply_heartbeat_view``,
  ``rejoin_backoff_delay``, ``ArgLocalityCache``) the production raylet runs.
- Each shell owns a real ``sched_core`` ledger mirroring the cluster view and
  places tasks with the same locality-then-hybrid policy, spilling over real
  peer RPC (bounded hops, like raylet spillback).
- The EXECUTOR is a stub: a task "runs" by holding its resources for a
  modeled duration on the event-loop timer, then releasing them. No worker
  process, no user code, no object payloads — completions are reported
  through an in-process callback, not the owner wire path (the honest
  fidelity gap; see PARITY.md).

That trade buys 1k nodes on one box: enough to drive GCS fan-in (heartbeat
reply bytes, node-death directory scans, task-event ingest) and the chaos
matrix at a scale where O(N^2) control-plane behavior is measurable, not
theoretical. See ``microbench.py --sim`` and ``tests/chaos_matrix.py``.
"""

from __future__ import annotations

import asyncio
import collections
import itertools
import logging
import math
import random
import time

from ray_tpu._private import flight_recorder
from ray_tpu._private.config import get_config, init_config
from ray_tpu._private.gcs import GcsServer
from ray_tpu._private.raylet import (
    ArgLocalityCache,
    OptimisticDebitLedger,
    apply_heartbeat_view,
    rejoin_backoff_delay,
)
from ray_tpu._private.rpc import EventLoopThread, RpcClient, RpcServer
from ray_tpu._private.sched_core import HYBRID, SPREAD, create_sched_core
from ray_tpu._private.task_spec import TaskSpec
from ray_tpu.exceptions import NodeDiedError, RayTpuError

logger = logging.getLogger(__name__)

# Spillback hop cap: a task bounced between saturated shells executes at the
# cap-holder instead of ping-ponging (the raylet path gets the same effect
# from queue-at-feasible semantics).
_MAX_SIM_HOPS = 3


def _percentile(samples: list, q: float) -> float:
    """Nearest-rank percentile; 0.0 on empty input."""
    if not samples:
        return 0.0
    s = sorted(samples)
    return s[min(len(s) - 1, int(math.ceil(q * len(s))) - 1)]


class SimNode:
    """One lightweight raylet shell.

    Real: GCS wire protocol (own RpcClient), RPC server (own listen socket,
    spillback target), sched_core ledger, delta-sync cluster view, locality
    cache, rejoin backoff. Stub: the executor — ``_start_exec`` holds the
    task's resources for ``runtime_env["sim_ms"]`` modeled milliseconds on
    the event-loop timer, then releases and reports via the in-process
    ``on_task_done`` callback.

    All task-path state (queue, timers, ledger) is touched ONLY from the
    process's IO loop (RPC handlers + timer callbacks + coroutines spawned
    there); driver-thread levers go through SimCluster, which hops onto the
    loop first.
    """

    def __init__(
        self,
        gcs_address,
        index: int,
        resources: dict | None = None,
        on_task_done=None,
    ):
        self.cfg = get_config()
        self.index = index
        # Deterministic hex id: stable across runs for seeded chaos cells.
        self.node_id = f"{index:032x}"
        self.resources_total = dict(resources or {"CPU": 4})
        self._sched = create_sched_core()
        self.cluster_view: dict[str, dict] = {}
        self._synced_peers: set[str] = set()
        self._view_version = 0
        self._rejoin_rng = random.Random(self.node_id)
        self._rejoin_attempts = 0
        self.on_task_done = on_task_done
        # Objects this shell "holds" — the modeled data plane. Locations are
        # published to the GCS for real, so locality lookups resolve.
        self.local_objects: set[str] = set()
        self.queue: collections.deque = collections.deque()
        # Hard-pinned (node:<id>) tasks whose target left the view: parked,
        # re-placed on view refresh (the node may rejoin) — NEVER run
        # locally, that would silently violate the pin.
        self.infeasible: list = []
        self.running = 0
        self.completed = 0
        self.forwarded = 0
        self.locality_hits = 0
        self.placement_s: list[float] = []
        self._dead = False
        self._draining = False
        self._partitioned = False
        self._timers: dict[str, asyncio.TimerHandle] = {}
        self._io = EventLoopThread.get()
        self._loop = self._io.loop
        self.server = RpcServer(f"sim{index}")
        self.server.register_all(self)
        self.server.start("127.0.0.1", 0)
        self.address = self.server.address
        self.gcs = RpcClient(gcs_address, label=f"sim{index}->gcs")
        self._arg_locality = ArgLocalityCache(self.gcs, self.cfg)
        self._opt_debits = OptimisticDebitLedger()
        self._peers: dict[str, RpcClient] = {}
        self._hb_task: asyncio.Future | None = None

    # ------------------------------------------------------------------
    # Membership: register / heartbeat / rejoin — the real wire protocol.
    # ------------------------------------------------------------------

    @property
    def resources_available(self) -> dict:
        return {
            k: self._sched.node_avail(self.node_id, k) for k in self.resources_total
        }

    async def start(self):
        self._sched.node_upsert(
            self.node_id, self.resources_total, dict(self.resources_total)
        )
        await self._register()
        for oid in list(self.local_objects):
            await self._publish_location(oid)
        self._hb_task = asyncio.ensure_future(self._heartbeat_loop())

    async def _register(self):
        await self.gcs.acall(
            "register_node",
            {
                "node_id": self.node_id,
                "address": list(self.address),
                "resources": self.resources_total,
                "labels": {"sim": "1"},
            },
        )

    async def _publish_location(self, oid: str):
        try:
            await self.gcs.acall(
                "add_object_location", {"object_id": oid, "node_id": self.node_id}
            )
        except Exception:
            pass  # GCS unreachable: the next rejoin republishes

    async def _heartbeat_loop(self):
        # De-synchronized start: 1k shells created in a tight loop must not
        # all heartbeat in the same millisecond every interval (the real
        # fleet is naturally staggered by boot time).
        await asyncio.sleep(
            self._rejoin_rng.uniform(0, self.cfg.heartbeat_interval_s)
        )
        while not self._dead:
            try:
                if not self._partitioned:
                    hb = {
                        "node_id": self.node_id,
                        "resources_available": self.resources_available,
                    }
                    if self.cfg.heartbeat_delta_sync:
                        hb["view_version"] = self._view_version
                    resp = await self.gcs.acall("heartbeat", hb, timeout=5, retries=0)
                    if resp.get("dead") or resp.get("unknown"):
                        # Declared dead (partition outlived the death timeout)
                        # or the GCS restarted and lost its node table.
                        await self._rejoin()
                        continue
                    apply_heartbeat_view(resp, self)
                    self._opt_debits.expire(self._sched)
                    self._rejoin_attempts = 0
                    await self._reschedule_queue()  # view refreshed
            except Exception:
                pass  # unreachable GCS: keep the cadence, try next interval
            await asyncio.sleep(self.cfg.heartbeat_interval_s)

    async def _rejoin(self):
        """Same contract as Raylet._rejoin: jittered backoff, re-register
        under the same node id, republish held object locations (the GCS
        dropped our rows at death)."""
        delay = rejoin_backoff_delay(self._rejoin_attempts, self.cfg, self._rejoin_rng)
        self._rejoin_attempts += 1
        if delay > 0:
            await asyncio.sleep(delay)
        await self._register()
        self._view_version = 0  # force a full-view resync on the next beat
        for oid in list(self.local_objects):
            await self._publish_location(oid)

    # ------------------------------------------------------------------
    # Task path: real placement, real spillback RPC, stub execution.
    # ------------------------------------------------------------------

    async def rpc_submit_task(self, req):
        if self._dead:
            raise NodeDiedError(f"sim node {self.node_id[:8]} is dead")
        spec = TaskSpec.from_wire(req["spec"])
        await self._queue_and_schedule(spec)
        return {"ok": True, "node_id": self.node_id}

    async def rpc_sim_stats(self, req):
        return {
            "node_id": self.node_id,
            "completed": self.completed,
            "running": self.running,
            "queued": len(self.queue),
            "forwarded": self.forwarded,
            "view_nodes": len(self.cluster_view),
            "view_version": self._view_version,
        }

    async def _queue_and_schedule(self, spec: TaskSpec):
        prefer = await self._locality_prefs(spec)
        target = self._pick_node(spec, prefer=prefer)
        if target is None:
            if (spec.scheduling_strategy or "").startswith("node:"):
                self.infeasible.append(spec)
            else:
                self._queue_local(spec)
            return
        if target == self.node_id:
            self._queue_local(spec)
            return
        hops = int(spec.runtime_env.get("sim_hops", 0))
        row = self.cluster_view.get(target)
        if hops >= _MAX_SIM_HOPS or row is None:
            self._queue_local(spec)
            return
        spec.runtime_env["sim_hops"] = hops + 1
        self.forwarded += 1
        # Optimistic mirror debit (same as Raylet._queue_and_schedule): a
        # burst must spread over fits-now peers, not dogpile the first one.
        # An authoritative heartbeat row overwrites it; the debit ledger
        # credits it back if none arrives (quiet peers send no delta rows).
        if self._sched.try_acquire(target, spec.resources):
            self._opt_debits.note(target, spec.resources, self.cfg.heartbeat_interval_s)
        try:
            await self._peer(target, row["address"]).acall(
                "submit_task", {"spec": spec.to_wire()}, timeout=10, retries=1
            )
        except Exception:
            # Peer died/partitioned mid-forward: keep the task here — it
            # queues until local resources free (or the driver's timeout
            # fires and the closed-loop user resubmits, typed).
            self._queue_local(spec)

    def _peer(self, node_id: str, address) -> RpcClient:
        client = self._peers.get(node_id)
        if client is None:
            client = RpcClient(
                tuple(address), label=f"sim{self.index}->peer"
            )
            self._peers[node_id] = client
        return client

    def _queue_local(self, spec: TaskSpec):
        self.queue.append(spec)
        self._drain_queue()

    async def _reschedule_queue(self):
        """Heartbeat-tick queue maintenance: drain whatever now fits
        locally, then re-run placement for head-blocked tasks that still
        have spill hops left — peers that freed up since the last view are
        only visible after a refresh (the raylet gets the same effect from
        _requeue_infeasible + _dispatch on its heartbeat)."""
        self._drain_queue()
        if self.infeasible:
            parked, self.infeasible = self.infeasible, []
            for spec in parked:
                await self._queue_and_schedule(spec)
        if not self.queue:
            return
        movable = [
            s
            for s in self.queue
            if int(s.runtime_env.get("sim_hops", 0)) < _MAX_SIM_HOPS
        ]
        if not movable:
            return
        kept = [
            s
            for s in self.queue
            if int(s.runtime_env.get("sim_hops", 0)) >= _MAX_SIM_HOPS
        ]
        self.queue.clear()
        self.queue.extend(kept)
        for spec in movable:
            await self._queue_and_schedule(spec)

    def _drain_queue(self):
        while self.queue and not self._dead:
            spec = self.queue[0]
            if not self._sched.try_acquire(self.node_id, spec.resources):
                return  # head blocked: FIFO per shell, like the raylet queue
            self.queue.popleft()
            self._start_exec(spec)

    def _start_exec(self, spec: TaskSpec):
        """Stub executor: resources held for the modeled duration, then
        released by a loop timer. Placement latency is measured HERE — the
        control-plane job is done once resources are acquired on a node."""
        submit = spec.hop_ts.get("sim_submit")
        if submit is not None:
            self.placement_s.append(time.monotonic() - submit)
        self.running += 1
        dur_s = max(0.0, float(spec.runtime_env.get("sim_ms", 1.0))) / 1000.0
        self._timers[spec.task_id] = self._loop.call_later(
            dur_s, self._finish_exec, spec
        )

    def _finish_exec(self, spec: TaskSpec):
        self._timers.pop(spec.task_id, None)
        if self._dead:
            return  # killed mid-flight: resources are gone with the node
        self._sched.release(self.node_id, spec.resources)
        self.running -= 1
        self.completed += 1
        for oid in spec.runtime_env.get("sim_creates", ()):
            # The task "produced" these objects: this shell becomes a
            # holder and publishes the location for real — downstream
            # locality decisions resolve against live GCS rows.
            self.local_objects.add(oid)
            asyncio.ensure_future(self._publish_location(oid))
        if self.on_task_done is not None:
            self.on_task_done(self.node_id, spec)
        self._drain_queue()

    # ------------------------------------------------------------------
    # Placement: the raylet's policy, verbatim semantics.
    # ------------------------------------------------------------------

    def _pick_node(self, spec: TaskSpec, prefer: list | None = None) -> str | None:
        strategy = spec.scheduling_strategy or "DEFAULT"
        if strategy.startswith("node:"):
            parts = strategy.split(":")
            node_id = parts[1]
            soft = len(parts) > 2 and parts[2] == "soft"
            if node_id == self.node_id or node_id in self.cluster_view:
                return node_id
            return self.node_id if soft else None
        if prefer:
            for nid in prefer:
                if nid == self.node_id:
                    if self._fits_now(spec):
                        self._note_locality_hit(spec, nid)
                        return nid
                elif nid in self.cluster_view and self._sched.node_fits(
                    nid, spec.resources
                ):
                    self._note_locality_hit(spec, nid)
                    return nid
        policy = SPREAD if strategy == "SPREAD" else HYBRID
        return self._sched.best_node(spec.resources, policy, self.node_id)

    def _fits_now(self, spec: TaskSpec) -> bool:
        return all(
            self._sched.node_avail(self.node_id, k) >= v - 1e-9
            for k, v in spec.resources.items()
            if v > 0
        )

    def _note_locality_hit(self, spec: TaskSpec, nid: str):
        self.locality_hits += 1
        flight_recorder.record("locality_hit", f"{spec.task_id[:8]}->{nid[:8]}")

    async def _locality_prefs(self, spec: TaskSpec) -> list | None:
        if not self.cfg.locality_aware_scheduling:
            return None
        if (spec.scheduling_strategy or "DEFAULT") != "DEFAULT":
            return None
        if len(self.cluster_view) <= 1:
            return None
        counts = await self._arg_locality.holders(spec)
        if not counts:
            return None
        return sorted(counts, key=lambda n: -counts[n])

    # ------------------------------------------------------------------
    # Chaos levers (loop-side halves; SimCluster hops threads).
    # ------------------------------------------------------------------

    def partition(self, on: bool = True):
        """Suppress heartbeats (and let inbound submits keep failing via
        peer timeouts) — models a switch losing the port. Past
        node_death_timeout_s the GCS declares the node dead; on heal the
        next heartbeat returns ``dead`` and the shell rejoins with backoff."""
        self._partitioned = on

    async def drain(self):
        """Graceful removal: the GCS tombstones the node out of the ALIVE
        view (peers stop spilling here), queued + in-flight stub tasks run
        to completion."""
        self._draining = True
        await self.gcs.acall("drain_node", {"node_id": self.node_id})

    async def akill(self):
        """Abrupt death, loop side: heartbeats stop, in-flight completions
        are cancelled (they never report), the queue is dropped. Drivers
        see timeouts and resubmit — typed, per SimTraffic's contract."""
        self._dead = True
        if self._hb_task is not None:
            self._hb_task.cancel()
        for handle in self._timers.values():
            handle.cancel()
        self._timers.clear()
        self.queue.clear()
        self.infeasible.clear()

    async def aclose_clients(self):
        try:
            self.gcs.close()
        except Exception:
            pass
        for client in self._peers.values():
            try:
                client.close()
            except Exception:
                pass
        self._peers.clear()

    def stop(self):
        """Full teardown; DRIVER thread only (server.stop hops the loop)."""
        self._io.run(self.akill())
        self.server.stop()
        self._io.run(self.aclose_clients())
        self._sched.close()


class SimCluster:
    """A GcsServer plus N SimNode shells in this process.

    Shells register over the real wire in batches; task submission enters
    through a bounded set of entry shells (round-robin), mirroring drivers
    connecting to their local raylet. Completion is observed via the
    in-process ``on_task_done`` callback feeding per-task waiters.
    """

    def __init__(
        self,
        num_nodes: int,
        resources_per_node: dict | None = None,
        _system_config: dict | None = None,
        seed: int = 0,
        num_entry_nodes: int = 16,
    ):
        if _system_config is not None:
            init_config(_system_config)
        self.cfg = get_config()
        self.gcs = GcsServer()
        self.seed = seed
        self._io = EventLoopThread.get()
        self.results: dict[str, str] = {}  # task_id -> completing node_id
        self._done_count = 0
        self._waiters: dict[str, asyncio.Future] = {}
        self._task_ids = itertools.count(1)
        self.nodes: list[SimNode] = [
            SimNode(
                self.gcs.address,
                i,
                resources=resources_per_node,
                on_task_done=self._on_done,
            )
            for i in range(num_nodes)
        ]
        self.entry_nodes = self.nodes[: max(1, min(num_entry_nodes, num_nodes))]
        self._entry_rr = itertools.cycle(range(len(self.entry_nodes)))
        self._entry_clients: dict[str, RpcClient] = {}

    # ------------------------------------------------------------------
    # Lifecycle
    # ------------------------------------------------------------------

    def start(self, batch: int = 64, timeout: float = 120.0):
        """Register every shell with the GCS, ``batch`` at a time (the real
        fleet's boot is staggered; an unbatched 1k-wide gather is also just
        slow to error out of)."""
        for i in range(0, len(self.nodes), batch):
            chunk = self.nodes[i : i + batch]
            self._io.run(self._start_batch(chunk), timeout=timeout)

    @staticmethod
    async def _start_batch(chunk: list):
        await asyncio.gather(*[n.start() for n in chunk])

    def wait_for_view(self, min_nodes: int | None = None, timeout: float = 30.0):
        """Block until every live shell's delta-synced cluster view holds at
        least ``min_nodes`` rows (default: all registered shells)."""
        want = min_nodes if min_nodes is not None else len(self.nodes)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            lagging = [
                n
                for n in self.nodes
                if not n._dead and not n._partitioned and len(n.cluster_view) < want
            ]
            if not lagging:
                return
            time.sleep(0.05)
        raise TimeoutError(
            f"{len(lagging)} sim shells never converged to a {want}-node view"
        )

    def shutdown(self):
        for i in range(0, len(self.nodes), 64):
            chunk = self.nodes[i : i + 64]
            self._io.run(self._kill_batch(chunk), timeout=30)
        for node in self.nodes:
            node.server.stop()
        for node in self.nodes:
            self._io.run(node.aclose_clients(), timeout=10)
            node._sched.close()
        for client in self._entry_clients.values():
            try:
                client.close()
            except Exception:
                pass
        self._entry_clients.clear()
        self.gcs.stop()

    @staticmethod
    async def _kill_batch(chunk: list):
        for n in chunk:
            await n.akill()

    # ------------------------------------------------------------------
    # Submission / completion
    # ------------------------------------------------------------------

    def make_spec(
        self,
        resources: dict | None = None,
        sim_ms: float = 1.0,
        args: list | None = None,
        strategy: str = "DEFAULT",
        creates: list | None = None,
    ) -> TaskSpec:
        runtime_env: dict = {"sim_ms": sim_ms}
        if creates:
            runtime_env["sim_creates"] = list(creates)
        return TaskSpec(
            task_id=f"t{next(self._task_ids):015d}",
            job_id="sim",
            name="sim_task",
            args=list(args or []),
            resources=dict(resources or {"CPU": 1}),
            scheduling_strategy=strategy,
            runtime_env=runtime_env,
        )

    def _entry_client(self, node: SimNode) -> RpcClient:
        client = self._entry_clients.get(node.node_id)
        if client is None:
            client = RpcClient(tuple(node.address), label="sim-driver")
            self._entry_clients[node.node_id] = client
        return client

    def next_entry(self) -> SimNode:
        return self.entry_nodes[next(self._entry_rr)]

    async def asubmit(self, spec: TaskSpec, entry: SimNode | None = None):
        """Submit over the real wire through an entry shell. Stamps the
        placement clock; the executing shell measures submit->acquire."""
        spec.hop_ts["sim_submit"] = time.monotonic()
        node = entry if entry is not None else self.next_entry()
        await self._entry_client(node).acall(
            "submit_task", {"spec": spec.to_wire()}, timeout=10, retries=1
        )

    def register_waiter(self, task_id: str) -> asyncio.Future:
        """Loop-side: create the completion future BEFORE submitting, so a
        fast completion can't race past its waiter."""
        fut = self._loop_future()
        self._waiters[task_id] = fut
        return fut

    def _loop_future(self) -> asyncio.Future:
        return asyncio.get_running_loop().create_future()

    def discard_waiter(self, task_id: str):
        self._waiters.pop(task_id, None)

    def _on_done(self, node_id: str, spec: TaskSpec):
        # Runs on the IO loop (timer callback chain).
        self.results[spec.task_id] = node_id
        self._done_count += 1
        fut = self._waiters.pop(spec.task_id, None)
        if fut is not None and not fut.done():
            fut.set_result(node_id)

    @property
    def done_count(self) -> int:
        return self._done_count

    def wait_done(self, n: int, timeout: float = 60.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._done_count >= n:
                return True
            time.sleep(0.02)
        return self._done_count >= n

    # ------------------------------------------------------------------
    # Chaos levers (driver-thread wrappers)
    # ------------------------------------------------------------------

    def kill_node(self, node: SimNode):
        flight_recorder.record("chaos_kill", f"simnode:{node.node_id[:8]}")
        self._io.run(node.akill())
        node.server.stop()

    def drain_node(self, node: SimNode):
        self._io.run(node.drain(), timeout=10)

    def partition_node(self, node: SimNode, on: bool = True):
        node.partition(on)

    def restart_gcs(self) -> GcsServer:
        """Stop the GCS and bring a fresh one up on the SAME address: every
        shell's next heartbeat hits ``unknown`` and rejoins — the rejoin
        storm the jittered backoff exists to flatten."""
        host, port = self.gcs.address
        self.gcs.stop()
        deadline = time.monotonic() + 10
        while True:
            try:
                self.gcs = GcsServer(host, port)
                return self.gcs
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.1)

    def seed_object(self, node: SimNode, oid: str):
        """Make ``node`` a holder of ``oid`` (modeled payload) and publish
        the location for real — locality tests build on this."""
        node.local_objects.add(oid)
        self._io.run(node._publish_location(oid), timeout=10)

    # ------------------------------------------------------------------
    # SLO material
    # ------------------------------------------------------------------

    def placement_latencies(self) -> list[float]:
        out: list[float] = []
        for n in self.nodes:
            out.extend(n.placement_s)
        return out

    def placement_p99_ms(self) -> float:
        return _percentile(self.placement_latencies(), 0.99) * 1000.0

    def alive_nodes(self) -> list[SimNode]:
        return [n for n in self.nodes if not n._dead and not n._draining]


class SimTraffic:
    """Closed-loop synthetic load with diurnal/bursty modulation.

    ``users`` concurrent loops each do submit -> await completion -> think.
    Think time is modulated over ``period_s``: ``diurnal`` sweeps a sine
    (smooth peak/trough), ``bursty`` a square wave (quiet half, 10x half).
    Everything is seeded — a scorecard reproduces from its seed.

    Failure contract: every failure a user observes is TYPED. A completion
    that never arrives (killed shell, dropped queue) or a submit into a dead
    entry surfaces as NodeDiedError — never a raw TimeoutError — is counted,
    and the task is resubmitted through a different entry (closed-loop
    retry, like a driver failing over its raylet connection).
    """

    def __init__(
        self,
        cluster: SimCluster,
        users: int = 8,
        pattern: str = "diurnal",
        period_s: float = 4.0,
        think_s: float = 0.02,
        sim_ms: float = 2.0,
        task_timeout_s: float = 5.0,
        resources: dict | None = None,
        seed: int = 1,
    ):
        assert pattern in ("diurnal", "bursty", "flat")
        self.cluster = cluster
        self.users = users
        self.pattern = pattern
        self.period_s = period_s
        self.think_s = think_s
        self.sim_ms = sim_ms
        self.task_timeout_s = task_timeout_s
        self.resources = dict(resources or {"CPU": 1})
        self.seed = seed

    def run(self, duration_s: float) -> dict:
        return self.cluster._io.run(
            self._run(duration_s), timeout=duration_s + 120
        )

    async def _run(self, duration_s: float) -> dict:
        stats = {
            "completed": 0,
            "submitted": 0,
            "resubmits": 0,
            "failures": {},
            "pattern": self.pattern,
            "users": self.users,
            "seed": self.seed,
        }
        t0 = time.monotonic()
        await asyncio.gather(
            *[self._user(i, t0, duration_s, stats) for i in range(self.users)]
        )
        stats["wall_s"] = time.monotonic() - t0
        return stats

    def _mult(self, t: float) -> float:
        phase = (t % self.period_s) / self.period_s
        if self.pattern == "bursty":
            return 0.1 if phase < 0.5 else 1.9
        if self.pattern == "diurnal":
            return 1.0 + 0.8 * math.sin(2 * math.pi * phase)
        return 1.0

    async def _user(self, idx: int, t0: float, duration_s: float, stats: dict):
        rng = random.Random((self.seed << 16) + idx)
        entries = self.cluster.entry_nodes
        while time.monotonic() - t0 < duration_s:
            await self._submit_once(rng, entries, stats)
            think = self.think_s * self._mult(time.monotonic() - t0)
            await asyncio.sleep(max(0.001, think * rng.uniform(0.5, 1.5)))

    async def _submit_once(self, rng, entries, stats, max_attempts: int = 3):
        for attempt in range(max_attempts):
            spec = self.cluster.make_spec(
                resources=self.resources, sim_ms=self.sim_ms
            )
            fut = self.cluster.register_waiter(spec.task_id)
            stats["submitted"] += 1
            entry = entries[rng.randrange(len(entries))]
            try:
                await self.cluster.asubmit(spec, entry=entry)
                await asyncio.wait_for(fut, self.task_timeout_s)
                stats["completed"] += 1
                return True
            except BaseException as e:  # noqa: BLE001 — typed below
                self.cluster.discard_waiter(spec.task_id)
                err = self._typed(e)
                name = type(err).__name__
                stats["failures"][name] = stats["failures"].get(name, 0) + 1
                if attempt + 1 < max_attempts:
                    stats["resubmits"] += 1
                    entries = self.cluster.alive_nodes() or self.cluster.entry_nodes
        return False

    @staticmethod
    def _typed(e: BaseException) -> RayTpuError:
        """Every user-visible failure is a RayTpuError subclass. A lost
        completion (timeout) or severed entry connection means the hosting
        shell died or was partitioned: NodeDiedError."""
        if isinstance(e, RayTpuError) and not isinstance(e, TimeoutError):
            return e
        return NodeDiedError(f"sim task lost to node failure: {type(e).__name__}")
