"""ctypes binding for the native scheduler core (_native/sched_core.cc).

TPU-native analog of the reference's C++ scheduling substrate
(src/ray/raylet/scheduling/cluster_resource_scheduler.cc + fixed_point.h):
the raylet delegates per-task resource acquire/release, bundle pools, and
placement scoring here. Arithmetic is integer milli-units, so thousands of
fractional acquire/release cycles stay exact (float dicts drift).

A pure-Python ``_PySchedCore`` with identical semantics is the fallback when
no compiler is available, and the differential test target.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

logger = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "_native"
)
_SRC = os.path.join(_NATIVE_DIR, "sched_core.cc")
_SO = os.path.join(_NATIVE_DIR, "build", "libsched_core.so")

_lib = None
_lib_lock = threading.Lock()
_SCALE = 1000


def _build_native() -> str | None:
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return _SO
    tmp = _SO + f".tmp{os.getpid()}"
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", _SRC, "-o", tmp, "-lpthread"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return _SO
    except Exception as e:
        logger.warning("native sched core build failed (%s); using Python fallback", e)
        return None


def _load_lib():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        so = _build_native()
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError as e:
            # A stale/foreign-arch cached .so must degrade to the Python
            # fallback, not crash raylet startup.
            logger.warning("native sched core load failed (%s); using Python fallback", e)
            return None
        u32p = ctypes.POINTER(ctypes.c_uint32)
        f64p = ctypes.POINTER(ctypes.c_double)
        lib.sc_create.restype = ctypes.c_int
        lib.sc_destroy.argtypes = [ctypes.c_int]
        lib.sc_intern.argtypes = [ctypes.c_int, ctypes.c_char_p]
        lib.sc_intern.restype = ctypes.c_uint32
        lib.sc_node_upsert.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_int, u32p, f64p, f64p]
        lib.sc_node_remove.argtypes = [ctypes.c_int, ctypes.c_char_p]
        lib.sc_try_acquire.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_int, u32p, f64p]
        lib.sc_try_acquire.restype = ctypes.c_int
        lib.sc_release.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_int, u32p, f64p]
        lib.sc_pool_upsert.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_int, u32p, f64p]
        lib.sc_pool_remove.argtypes = [ctypes.c_int, ctypes.c_char_p]
        lib.sc_pool_exists.argtypes = [ctypes.c_int, ctypes.c_char_p]
        lib.sc_pool_exists.restype = ctypes.c_int
        lib.sc_pool_try_acquire.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_int, u32p, f64p]
        lib.sc_pool_try_acquire.restype = ctypes.c_int
        lib.sc_pool_release.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_int, u32p, f64p]
        lib.sc_node_avail.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_uint32]
        lib.sc_node_avail.restype = ctypes.c_double
        lib.sc_pool_avail.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_uint32]
        lib.sc_pool_avail.restype = ctypes.c_double
        lib.sc_cluster_feasibility.argtypes = [ctypes.c_int, ctypes.c_int, u32p, f64p]
        lib.sc_cluster_feasibility.restype = ctypes.c_int
        lib.sc_best_node.argtypes = [
            ctypes.c_int, ctypes.c_int, u32p, f64p, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
        ]
        lib.sc_best_node.restype = ctypes.c_int
        _lib = lib
        return _lib


HYBRID, SPREAD = 0, 1


class _NativeSchedCore:
    def __init__(self, lib):
        self._lib = lib
        self._h = lib.sc_create()
        self._interned: dict[str, int] = {}

    def _vec(self, resources: dict):
        n = len(resources)
        idx = (ctypes.c_uint32 * n)()
        vals = (ctypes.c_double * n)()
        for i, (name, v) in enumerate(resources.items()):
            j = self._interned.get(name)
            if j is None:
                j = self._lib.sc_intern(self._h, name.encode())
                self._interned[name] = j
            idx[i] = j
            vals[i] = float(v)
        return n, idx, vals

    def node_upsert(self, node_id: str, total: dict, avail: dict):
        keys = {**total, **avail}
        n, idx, _ = self._vec(keys)
        tot = (ctypes.c_double * n)(*[float(total.get(k, 0)) for k in keys])
        av = (ctypes.c_double * n)(*[float(avail.get(k, 0)) for k in keys])
        self._lib.sc_node_upsert(self._h, node_id.encode(), n, idx, tot, av)

    def node_remove(self, node_id: str):
        self._lib.sc_node_remove(self._h, node_id.encode())

    def try_acquire(self, node_id: str, demand: dict) -> bool:
        n, idx, vals = self._vec(demand)
        return bool(self._lib.sc_try_acquire(self._h, node_id.encode(), n, idx, vals))

    def release(self, node_id: str, demand: dict):
        n, idx, vals = self._vec(demand)
        self._lib.sc_release(self._h, node_id.encode(), n, idx, vals)

    def pool_upsert(self, pool_key: str, caps: dict):
        n, idx, vals = self._vec(caps)
        self._lib.sc_pool_upsert(self._h, pool_key.encode(), n, idx, vals)

    def pool_remove(self, pool_key: str):
        self._lib.sc_pool_remove(self._h, pool_key.encode())

    def pool_exists(self, pool_key: str) -> bool:
        return bool(self._lib.sc_pool_exists(self._h, pool_key.encode()))

    def pool_try_acquire(self, pool_key: str, demand: dict) -> bool:
        n, idx, vals = self._vec(demand)
        return bool(self._lib.sc_pool_try_acquire(self._h, pool_key.encode(), n, idx, vals))

    def pool_release(self, pool_key: str, demand: dict):
        n, idx, vals = self._vec(demand)
        self._lib.sc_pool_release(self._h, pool_key.encode(), n, idx, vals)

    def node_avail(self, node_id: str, name: str) -> float:
        j = self._interned.get(name)
        if j is None:
            j = self._lib.sc_intern(self._h, name.encode())
            self._interned[name] = j
        return float(self._lib.sc_node_avail(self._h, node_id.encode(), j))

    def node_fits(self, node_id: str, demand: dict) -> bool:
        """Non-mutating fits-now check against a (possibly mirrored) node's
        availability — the locality-preference probe. Built over per-key
        node_avail so the native ABI stays unchanged."""
        return all(
            v <= 0 or self.node_avail(node_id, k) >= v - 1e-9
            for k, v in demand.items()
        )

    def pool_avail(self, pool_key: str, name: str) -> float:
        j = self._interned.get(name)
        if j is None:
            j = self._lib.sc_intern(self._h, name.encode())
            self._interned[name] = j
        return float(self._lib.sc_pool_avail(self._h, pool_key.encode(), j))

    def cluster_feasibility(self, demand: dict) -> int:
        n, idx, vals = self._vec(demand)
        return int(self._lib.sc_cluster_feasibility(self._h, n, idx, vals))

    def best_node(self, demand: dict, strategy: int, local_node: str) -> str | None:
        n, idx, vals = self._vec(demand)
        out = ctypes.create_string_buffer(128)
        ok = self._lib.sc_best_node(
            self._h, n, idx, vals, strategy, local_node.encode(), out, 128
        )
        return out.value.decode() if ok else None

    def close(self):
        self._lib.sc_destroy(self._h)

    @property
    def is_native(self) -> bool:
        return True


def _fp(v: float) -> int:
    # Match the C++ core bit-for-bit: half-away-from-zero, truncated cast
    # (round() would use banker's rounding and disagree at exact halves).
    x = v * _SCALE
    return int(x + 0.5) if x >= 0 else int(x - 0.5)


class _PySchedCore:
    """Reference semantics in Python (same milli-unit fixed point)."""

    is_native = False

    def __init__(self):
        self._nodes: dict[str, tuple[dict, dict]] = {}  # id -> (total, avail) in fp
        self._pools: dict[str, dict] = {}
        self._pool_caps: dict[str, dict] = {}

    @staticmethod
    def _to_fp(d: dict) -> dict:
        return {k: _fp(v) for k, v in d.items()}

    def node_upsert(self, node_id, total, avail):
        self._nodes[node_id] = (self._to_fp(total), self._to_fp(avail))

    def node_remove(self, node_id):
        self._nodes.pop(node_id, None)

    @staticmethod
    def _fits(avail: dict, demand: dict) -> bool:
        return all(amt <= 0 or avail.get(k, 0) >= amt for k, amt in demand.items())

    def try_acquire(self, node_id, demand) -> bool:
        node = self._nodes.get(node_id)
        if node is None:
            return False
        d = self._to_fp(demand)
        if not self._fits(node[1], d):
            return False
        for k, v in d.items():
            node[1][k] = node[1].get(k, 0) - v
        return True

    def release(self, node_id, demand):
        node = self._nodes.get(node_id)
        if node is None:
            return
        for k, v in self._to_fp(demand).items():
            node[1][k] = min(node[1].get(k, 0) + v, node[0].get(k, 0))

    def pool_upsert(self, pool_key, caps):
        fp = self._to_fp(caps)
        self._pool_caps[pool_key] = dict(fp)
        self._pools[pool_key] = dict(fp)

    def pool_remove(self, pool_key):
        self._pools.pop(pool_key, None)
        self._pool_caps.pop(pool_key, None)

    def pool_exists(self, pool_key) -> bool:
        return pool_key in self._pools

    def pool_try_acquire(self, pool_key, demand) -> bool:
        pool = self._pools.get(pool_key)
        if pool is None:
            return False
        d = self._to_fp(demand)
        if not self._fits(pool, d):
            return False
        for k, v in d.items():
            pool[k] = pool.get(k, 0) - v
        return True

    def pool_release(self, pool_key, demand):
        pool = self._pools.get(pool_key)
        if pool is None:
            return
        caps = self._pool_caps.get(pool_key, {})
        for k, v in self._to_fp(demand).items():
            pool[k] = min(pool.get(k, 0) + v, caps.get(k, 0))

    def node_avail(self, node_id, name) -> float:
        node = self._nodes.get(node_id)
        return node[1].get(name, 0) / _SCALE if node else 0.0

    def node_fits(self, node_id, demand) -> bool:
        node = self._nodes.get(node_id)
        if node is None:
            return not any(v > 0 for v in demand.values())
        return self._fits(node[1], self._to_fp(demand))

    def pool_avail(self, pool_key, name) -> float:
        pool = self._pools.get(pool_key)
        return pool.get(name, 0) / _SCALE if pool else 0.0

    def cluster_feasibility(self, demand) -> int:
        d = self._to_fp(demand)
        best = 0
        for total, avail in self._nodes.values():
            if self._fits(avail, d):
                return 2
            if self._fits(total, d):
                best = 1
        return best

    def best_node(self, demand, strategy, local_node) -> str | None:
        d = self._to_fp(demand)
        if strategy == SPREAD:
            best, best_score = None, -1.0
            for nid in sorted(self._nodes):
                total, avail = self._nodes[nid]
                if not self._fits(total, d):
                    continue
                score = sum(
                    avail.get(k, 0) / t for k, t in total.items() if t > 0
                )
                if score > best_score:
                    best, best_score = nid, score
            return best
        local = self._nodes.get(local_node)
        if local is not None and self._fits(local[1], d):
            return local_node
        feasible_peer = None
        for nid in sorted(self._nodes):
            if nid == local_node:
                continue
            total, avail = self._nodes[nid]
            if self._fits(avail, d):
                return nid
            if feasible_peer is None and self._fits(total, d):
                feasible_peer = nid
        if local is not None and self._fits(local[0], d):
            return local_node
        return feasible_peer

    def close(self):
        pass


def create_sched_core():
    """Native core when the toolchain allows, Python fallback otherwise."""
    if os.environ.get("RAY_TPU_DISABLE_NATIVE_SCHED"):
        return _PySchedCore()
    lib = _load_lib()
    if lib is None:
        return _PySchedCore()
    return _NativeSchedCore(lib)
