"""Object serialization.

TPU-native analog of the reference's SerializationContext
(python/ray/_private/serialization.py:107): cloudpickle for arbitrary Python
objects, pickle protocol 5 out-of-band buffers for zero-copy numpy/jax arrays
(the buffers land directly in the shm arena and deserialize as memoryview-backed
arrays without a copy), out-of-band ObjectRef tracking for refs nested inside
task args/returns, and device-array handling: ``jax.Array`` leaves the device
via a host DMA on serialize (the reference never stores GPU memory in plasma
either — device collectives ride the XLA/ICI plane instead, see
util/collective/).

Wire layout: msgpack header {p: pickle_len, b: [buffer sizes], r: [ref hexes]}
then the pickle bytes, then each out-of-band buffer 64-byte aligned.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

import cloudpickle
import msgpack
import pickle

_ALIGN = 64


def _align(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


class _SerializationThreadContext(threading.local):
    def __init__(self):
        self.contained_refs = None


_thread_ctx = _SerializationThreadContext()


def record_contained_ref(ref) -> None:
    """Called from ObjectRef.__reduce__ while a serialize() is in flight."""
    if _thread_ctx.contained_refs is not None:
        _thread_ctx.contained_refs.append(ref)


@dataclass
class SerializedObject:
    pickled: bytes
    buffers: list  # list of buffer-protocol objects
    contained_refs: list = field(default_factory=list)

    @property
    def header(self) -> bytes:
        return msgpack.packb(
            {
                "p": len(self.pickled),
                "b": [len(memoryview(b)) for b in self.buffers],
            },
            use_bin_type=True,
        )

    @property
    def total_size(self) -> int:
        header = self.header
        size = 4 + len(header)
        size = _align(size) + len(self.pickled)
        for b in self.buffers:
            size = _align(size) + len(memoryview(b))
        return size

    def write_to(self, view: memoryview) -> int:
        """Write the full wire format into view; returns bytes written."""
        header = self.header
        pos = 0
        view[pos : pos + 4] = len(header).to_bytes(4, "big")
        pos += 4
        view[pos : pos + len(header)] = header
        pos += len(header)
        pos = _align(pos)
        view[pos : pos + len(self.pickled)] = self.pickled
        pos += len(self.pickled)
        for b in self.buffers:
            mv = memoryview(b).cast("B")
            pos = _align(pos)
            view[pos : pos + len(mv)] = mv
            pos += len(mv)
        return pos

    def to_bytes(self) -> bytes:
        out = bytearray(self.total_size)
        n = self.write_to(memoryview(out))
        return bytes(out[:n])


def _reduce_jax_array(arr):
    import numpy as np

    return (np.asarray, (np.asarray(arr),))


class _Pickler(cloudpickle.CloudPickler):
    def reducer_override(self, obj):
        # Device arrays: pull to host once; payload then rides the zero-copy
        # buffer path below like any numpy array.
        tname = type(obj).__module__
        if tname.startswith("jaxlib") or tname.startswith("jax"):
            try:
                import jax

                if isinstance(obj, jax.Array):
                    return _reduce_jax_array(obj)
            except ImportError:
                pass
        # Delegate to cloudpickle's own reducer_override — it implements
        # by-value pickling of local/lambda functions and dynamic classes.
        return super().reducer_override(obj)


def serialize(obj) -> SerializedObject:
    import io

    buffers: list = []
    prev = _thread_ctx.contained_refs
    _thread_ctx.contained_refs = []
    try:
        sio = io.BytesIO()
        pickler = _Pickler(sio, protocol=5, buffer_callback=lambda b: buffers.append(b.raw()))
        pickler.dump(obj)
        pickled = sio.getvalue()
        refs = _thread_ctx.contained_refs
    finally:
        _thread_ctx.contained_refs = prev
    return SerializedObject(pickled=pickled, buffers=buffers, contained_refs=refs)


def deserialize(view) -> object:
    """Deserialize from a buffer (memoryview over shm => zero-copy arrays)."""
    view = memoryview(view).cast("B")
    header_len = int.from_bytes(view[:4], "big")
    header = msgpack.unpackb(view[4 : 4 + header_len], raw=False)
    pos = _align(4 + header_len)
    pickled = view[pos : pos + header["p"]]
    pos += header["p"]
    buffers = []
    for size in header["b"]:
        pos = _align(pos)
        buffers.append(pickle.PickleBuffer(view[pos : pos + size]))
        pos += size
    return pickle.loads(pickled, buffers=buffers)


def dumps(obj) -> bytes:
    """One-shot serialize to bytes (for RPC payload embedding)."""
    return serialize(obj).to_bytes()


def loads(data) -> object:
    return deserialize(data)
