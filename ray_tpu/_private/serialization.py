"""Object serialization.

TPU-native analog of the reference's SerializationContext
(python/ray/_private/serialization.py:107): cloudpickle for arbitrary Python
objects, pickle protocol 5 out-of-band buffers for zero-copy numpy/jax arrays
(the buffers land directly in the shm arena and deserialize as memoryview-backed
arrays without a copy), out-of-band ObjectRef tracking for refs nested inside
task args/returns, and device-array handling: ``jax.Array`` leaves the device
via a host DMA on serialize (the reference never stores GPU memory in plasma
either — device collectives ride the XLA/ICI plane instead, see
util/collective/).

Wire layout: msgpack header {p: pickle_len, b: [buffer sizes], r: [ref hexes]}
then the pickle bytes, then each out-of-band buffer 64-byte aligned.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

import cloudpickle
import msgpack
import pickle

_ALIGN = 64


def _align(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


class _SerializationThreadContext(threading.local):
    def __init__(self):
        self.contained_refs = None


_thread_ctx = _SerializationThreadContext()


def record_contained_ref(ref) -> None:
    """Called from ObjectRef.__reduce__ while a serialize() is in flight."""
    if _thread_ctx.contained_refs is not None:
        _thread_ctx.contained_refs.append(ref)


@dataclass
class SerializedObject:
    pickled: bytes  # payload: pickle bytes, or msgpack bytes when format="x"
    buffers: list  # list of buffer-protocol objects
    contained_refs: list = field(default_factory=list)
    # "pickle" (default, omitted from the header) or "x" — the
    # cross-language msgpack format any runtime can decode (reference:
    # cross-language serialization for C++/Java workers).
    format: str = "pickle"

    @property
    def header(self) -> bytes:
        h = {
            "p": len(self.pickled),
            "b": [len(memoryview(b)) for b in self.buffers],
        }
        if self.format != "pickle":
            h["f"] = self.format
        return msgpack.packb(h, use_bin_type=True)

    @property
    def total_size(self) -> int:
        header = self.header
        size = 4 + len(header)
        size = _align(size) + len(self.pickled)
        for b in self.buffers:
            size = _align(size) + len(memoryview(b))
        return size

    def write_to(self, view: memoryview) -> int:
        """Write the full wire format into view; returns bytes written."""
        header = self.header
        pos = 0
        view[pos : pos + 4] = len(header).to_bytes(4, "big")
        pos += 4
        view[pos : pos + len(header)] = header
        pos += len(header)
        pos = _align(pos)
        view[pos : pos + len(self.pickled)] = self.pickled
        pos += len(self.pickled)
        for b in self.buffers:
            mv = memoryview(b).cast("B")
            pos = _align(pos)
            view[pos : pos + len(mv)] = mv
            pos += len(mv)
        return pos

    def to_bytes(self) -> bytes:
        out = bytearray(self.total_size)
        n = self.write_to(memoryview(out))
        return bytes(out[:n])


def _encode_index(index, shape):
    """Shard index (tuple of slices into the global array) -> plain tuples."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    return tuple(out)


def _encode_pspec(spec):
    return tuple(tuple(p) if isinstance(p, (tuple, list)) else p for p in spec)


def _rebuild_sharded(global_shape, axis_names, mesh_ids, mesh_shape, pspec, uniq_bufs, shard_meta):
    """Reconstructor for a NamedSharding'ed jax.Array: device_put each unique
    host shard to its device(s) and reassemble WITHOUT a host gather.

    If this process cannot see the original device set (e.g. the object
    crossed to a host with a different topology), fall back to host-side
    assembly of the full array from the shards — still a jax.Array, default
    sharding.
    """
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    # Device identity is (platform, id): bare ids collide across backends
    # (a cpu:0 array must not land on tpu:0 just because id 0 exists).
    devmap = {(d.platform, d.id): d for d in jax.devices()}
    if all((p, int(i)) in devmap for p, i in mesh_ids):
        mesh_devs = np.array([devmap[(p, int(i))] for p, i in mesh_ids]).reshape(mesh_shape)
        sharding = NamedSharding(Mesh(mesh_devs, tuple(axis_names)), PartitionSpec(*pspec))
        singles = [
            jax.device_put(uniq_bufs[buf_idx][0], devmap[(p, int(i))])
            for (p, i), buf_idx in shard_meta
        ]
        return jax.make_array_from_single_device_arrays(tuple(global_shape), sharding, singles)
    # Topology mismatch: host-side reassembly from the unique shards.
    full = np.zeros(tuple(global_shape), dtype=np.asarray(uniq_bufs[0][0]).dtype)
    for buf, idx in uniq_bufs:
        full[tuple(slice(a, b) for a, b in idx)] = buf
    return jnp.asarray(full)


def _rebuild_single(host_arr, device_key):
    import jax
    import jax.numpy as jnp

    dev = {(d.platform, d.id): d for d in jax.devices()}.get(tuple(device_key))
    if dev is not None:
        return jax.device_put(host_arr, dev)
    return jnp.asarray(host_arr)


def _reduce_jax_array(arr):
    """Device arrays keep their type and sharding across the object store
    (SURVEY §2.3 object-plane row: device->host DMA on put, device_put with
    the original sharding on get — the round-1 np.asarray reduction silently
    returned numpy and lost the layout).

    Layout metadata (mesh device ids/axes + PartitionSpec + per-shard
    indices) travels with the object; replicated shards are deduped by index
    so a fully-replicated array costs 1x its size, not num_devices x.
    """
    import numpy as np
    import jax
    from jax.sharding import NamedSharding, SingleDeviceSharding

    sharding = arr.sharding
    if isinstance(sharding, SingleDeviceSharding):
        (dev,) = arr.devices()
        return (_rebuild_single, (np.asarray(arr), (dev.platform, dev.id)))
    if isinstance(sharding, NamedSharding) and arr.is_fully_addressable:
        mesh = sharding.mesh
        mesh_devs = mesh.devices
        mesh_ids = [(d.platform, int(d.id)) for d in mesh_devs.flat]
        uniq: dict = {}   # encoded index -> slot in uniq_bufs
        uniq_bufs: list = []  # (host array, encoded index)
        shard_meta: list = []  # ((platform, id), buffer slot) per addressable shard
        for s in arr.addressable_shards:
            idx = _encode_index(s.index, arr.shape)
            slot = uniq.get(idx)
            if slot is None:
                slot = len(uniq_bufs)
                uniq[idx] = slot
                uniq_bufs.append((np.asarray(s.data), idx))
            shard_meta.append(((s.device.platform, int(s.device.id)), slot))
        return (
            _rebuild_sharded,
            (
                tuple(arr.shape),
                tuple(mesh.axis_names),
                mesh_ids,
                tuple(mesh_devs.shape),
                _encode_pspec(sharding.spec),
                uniq_bufs,
                shard_meta,
            ),
        )
    if not arr.is_fully_addressable:
        raise TypeError(
            "cannot put() a multi-host jax.Array: this process only holds "
            f"{len(arr.addressable_shards)} of its shards. Put per-host shards "
            "as separate objects (e.g. put(arr.addressable_shards[i].data)) or "
            "move the value over the collective plane instead."
        )
    # Exotic shardings (Positional/GSPMD): host gather, still a jax.Array on get.
    import jax.numpy as jnp

    return (jnp.asarray, (np.asarray(arr),))


class _Pickler(cloudpickle.CloudPickler):
    def reducer_override(self, obj):
        # Device arrays: pull to host once; payload then rides the zero-copy
        # buffer path below like any numpy array.
        tname = type(obj).__module__
        if tname.startswith("jaxlib") or tname.startswith("jax"):
            try:
                import jax

                if isinstance(obj, jax.Array):
                    return _reduce_jax_array(obj)
            except ImportError:
                pass
        # Delegate to cloudpickle's own reducer_override — it implements
        # by-value pickling of local/lambda functions and dynamic classes.
        return super().reducer_override(obj)


class XLangBytes:
    """Marker: store these pre-encoded msgpack bytes as a format-"x" object
    (language-agnostic — a C++/Java driver decodes it without pickle).
    Produced by cross_language invokers; deserialize() returns the decoded
    plain data, so Python callers never see this wrapper."""

    __slots__ = ("data",)

    def __init__(self, data: bytes):
        self.data = bytes(data)


def serialize(obj) -> SerializedObject:
    import io

    if isinstance(obj, XLangBytes):
        return SerializedObject(pickled=obj.data, buffers=[], format="x")
    buffers: list = []
    prev = _thread_ctx.contained_refs
    _thread_ctx.contained_refs = []
    try:
        sio = io.BytesIO()
        pickler = _Pickler(sio, protocol=5, buffer_callback=lambda b: buffers.append(b.raw()))
        pickler.dump(obj)
        pickled = sio.getvalue()
        refs = _thread_ctx.contained_refs
    finally:
        _thread_ctx.contained_refs = prev
    return SerializedObject(pickled=pickled, buffers=buffers, contained_refs=refs)


def deserialize(view) -> object:
    """Deserialize from a buffer (memoryview over shm => zero-copy arrays)."""
    view = memoryview(view).cast("B")
    header_len = int.from_bytes(view[:4], "big")
    header = msgpack.unpackb(view[4 : 4 + header_len], raw=False)
    pos = _align(4 + header_len)
    payload = view[pos : pos + header["p"]]
    pos += header["p"]
    if header.get("f") == "x":
        # Cross-language msgpack object: plain data, no pickle involved.
        return msgpack.unpackb(bytes(payload), raw=False)
    if header.get("f") == "xe":
        # Cross-language task ERROR (produced by the C++ worker runtime,
        # cpp/ray_tpu_worker.cc): map onto the same TaskError the Python
        # execution path ships, so ray_tpu.get raises it identically.
        from ray_tpu.cross_language import CrossLanguageError
        from ray_tpu.exceptions import TaskError

        info = msgpack.unpackb(bytes(payload), raw=False)
        msg = info.get("message", "native task failed")
        return TaskError(
            cause=CrossLanguageError(msg),
            remote_traceback=msg,
            task_name=info.get("task_name", ""),
        )
    buffers = []
    for size in header["b"]:
        pos = _align(pos)
        buffers.append(pickle.PickleBuffer(view[pos : pos + size]))
        pos += size
    return pickle.loads(payload, buffers=buffers)


def peek_format(data) -> str:
    """The wire object's format tag without deserializing ("pickle" when
    the header omits "f") — the cpp-native routing gate reads this."""
    try:
        view = memoryview(data).cast("B")
        header_len = int.from_bytes(view[:4], "big")
        header = msgpack.unpackb(view[4 : 4 + header_len], raw=False)
        return header.get("f", "pickle")
    except Exception:
        return "unknown"


def dumps(obj) -> bytes:
    """One-shot serialize to bytes (for RPC payload embedding)."""
    return serialize(obj).to_bytes()


def loads(data) -> object:
    return deserialize(data)
