"""Task specification.

TPU-native analog of the reference's TaskSpecification
(src/ray/common/task/task_spec.h:186, built via TaskSpecBuilder
task_util.h:102): a msgpack-able description of one task/actor-creation/
actor-method invocation, carrying everything a remote worker needs to execute
it — function (by GCS function-table key), serialized/reference args, return
count, resource demand, retry policy, scheduling strategy, and owner address
for result routing.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

NORMAL_TASK = 0
ACTOR_CREATION_TASK = 1
ACTOR_TASK = 2


@dataclass
class TaskSpec:
    task_id: str  # hex
    job_id: str
    name: str
    task_type: int = NORMAL_TASK
    # Function table key in the GCS KV (see function_manager.py); workers
    # fetch-and-cache by this key (reference: _private/function_manager.py).
    function_key: str = ""
    # Each arg is ("v", <serialized bytes>) inline or ("r", <oid hex>, <owner addr>).
    args: list = field(default_factory=list)
    num_returns: int = 1
    resources: dict = field(default_factory=dict)
    max_retries: int = 0
    retry_exceptions: bool = False
    # Owner (submitting process) core-worker RPC address, [host, port].
    owner_addr: list | None = None
    owner_worker_id: str = ""
    # Actor fields.
    actor_id: str = ""
    method_name: str = ""
    seq_no: int = -1  # per-caller ordering for actor tasks
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    actor_name: str = ""  # named actor registration
    namespace: str = ""
    get_if_exists: bool = False
    # Device object plane (experimental/device_object/): non-empty on an
    # actor-creation spec makes every top-level jax.Array the actor returns
    # stay device-resident (the actor is the holder; callers get a
    # descriptor that resolves out of band).
    tensor_transport: str = ""
    # Scheduling.
    placement_group_id: str = ""
    placement_group_bundle_index: int = -1
    scheduling_strategy: str = "DEFAULT"  # DEFAULT | SPREAD | node:<id> | node:<id>:soft
    # Tracing span context propagated across process boundaries (reference:
    # util/tracing/tracing_helper.py — span context rides task metadata).
    trace_ctx: dict = field(default_factory=dict)
    runtime_env: dict = field(default_factory=dict)
    # Execution language (reference: TaskSpecification language field,
    # src/ray/common/task/task_spec.h — drives worker-pool selection).
    # "py" workers run pickled functions; "cpp" specs carry a
    # self-describing "cpp!<library>!<symbol>" function key and route to
    # the native worker runtime (cpp/ray_tpu_worker.cc).
    language: str = "py"
    # Non-empty marks this spec as a WORKER-LEASE REQUEST (reference:
    # direct_task_transport.cc lease requests ride the task scheduler): it
    # flows through the raylet queue like a task, but dispatch grants the
    # worker to the owner instead of pushing a task onto it.
    lease_id: str = ""
    # Hop-level dispatch timestamps (config.hop_timing): stage name ->
    # CLOCK_MONOTONIC seconds. Same-host comparable across processes; each
    # stage stamps as the spec passes through (owner submit/ship, raylet
    # recv/dispatch on the classic path, worker recv), and the completion
    # payload carries the worker-side stamps back. Empty (elided from the
    # wire) unless instrumentation is on.
    hop_ts: dict = field(default_factory=dict)

    def to_wire(self) -> dict:
        """Delta-encoded against field defaults: a typical no-frills task
        ships ~8 keys instead of 26, which matters at 1k+ tasks/s — wire
        size and msgpack time are on the submit hot path (the reference gets
        the same effect from protobuf default-field elision)."""
        return {
            k: v for k, v in self.__dict__.items() if _WIRE_DEFAULTS.get(k, _MISSING) != v
        }

    @classmethod
    def from_wire(cls, d: dict) -> "TaskSpec":
        return cls(**d)

    def return_object_ids(self) -> list[str]:
        from ray_tpu._private.ids import ObjectID, TaskID

        if not isinstance(self.num_returns, int):
            return []  # streaming: return ids are dynamic (yielded one by one)
        tid = TaskID.from_hex(self.task_id)
        return [ObjectID.for_return(tid, i).hex() for i in range(self.num_returns)]

    def is_streaming(self) -> bool:
        return self.num_returns == "streaming"

    def is_actor_task(self) -> bool:
        return self.task_type == ACTOR_TASK

    def is_actor_creation(self) -> bool:
        return self.task_type == ACTOR_CREATION_TASK


import dataclasses as _dataclasses

_MISSING = object()
_WIRE_DEFAULTS = {}
for _f in _dataclasses.fields(TaskSpec):
    if _f.default is not _dataclasses.MISSING:
        _WIRE_DEFAULTS[_f.name] = _f.default
    elif _f.default_factory is not _dataclasses.MISSING:  # type: ignore[misc]
        _WIRE_DEFAULTS[_f.name] = _f.default_factory()  # type: ignore[misc]
