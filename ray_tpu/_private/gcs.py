"""GCS — Global Control Service.

TPU-native analog of the reference's GCS server
(src/ray/gcs/gcs_server/gcs_server.cc:119-160): the cluster control plane,
wiring per-domain managers over one RPC server:

- node membership + health checking (gcs_node_manager.h, gcs_health_check_manager.h:39)
- actor lifecycle + restart state machine (gcs_actor_manager.h:281)
- placement groups with 2-phase reserve/commit (gcs_placement_group_manager.h)
- cluster KV store, also the function table (gcs_kv_manager.h, gcs_function_manager.h)
- object directory (reference: ownership-based directory; centralised here —
  ownership_based_object_directory.h — acceptable at the per-pod scale this
  control plane targets, revisit for 2k-node envelopes)
- pub/sub fan-out (src/ray/pubsub/publisher.h:307)
- task-event history (gcs_task_manager.h) powering the state API and timeline
- job table

Storage is in-memory (reference default) with snapshot + write-ahead-log
durability (reference: redis_store_client.h — every committed mutation is
durable before it is acknowledged). Mutating handlers append the changed
table entry to an append-only WAL and flush BEFORE replying; the debounced
snapshot acts as WAL compaction (each snapshot truncates the log). On
restart: load snapshot, then replay the WAL tail — so an acknowledged
mutation survives a GCS kill at any point after the reply.
"""

from __future__ import annotations

import asyncio
import collections
import json
import logging
import os
import time

from ray_tpu._private.config import get_config
from ray_tpu._private.rpc import EventLoopThread, RpcClient, RpcServer, schema
from ray_tpu._private.task_spec import TaskSpec

logger = logging.getLogger(__name__)

# Actor states (reference: src/ray/design_docs/actor_states.rst)
PENDING_CREATION = "PENDING_CREATION"
ALIVE = "ALIVE"
RESTARTING = "RESTARTING"
DEAD = "DEAD"


class GcsServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0, persist_path: str | None = None):
        self.cfg = get_config()
        self.server = RpcServer("gcs")
        self.server.register_all(self)
        self.persist_path = persist_path

        # Tables.
        self.nodes: dict[str, dict] = {}
        self.actors: dict[str, dict] = {}
        self.named_actors: dict[tuple[str, str], str] = {}  # (namespace, name) -> actor_id
        self.kv: dict[str, bytes] = {}
        self.object_locations: dict[str, set[str]] = {}
        # Reverse index: node_id -> oids it holds. _on_node_death used to
        # scan EVERY location row (O(objects) per death — a fan-in hot spot
        # at 1k nodes); with the index a death touches only that node's rows.
        self._locations_by_node: dict[str, set[str]] = {}
        self.placement_groups: dict[str, dict] = {}
        self.jobs: dict[str, dict] = {}
        # Drop-oldest ring: event fan-in at sim scale must degrade
        # observability (oldest history), never liveness or memory.
        self.task_events: collections.deque = collections.deque(
            maxlen=max(1, self.cfg.task_events_buffer_size)
        )
        self.events_dropped_total = 0
        self._overload_flight_ts = 0.0
        self._job_counter = 0
        # Versioned cluster-view sync (delta heartbeats). Every MATERIAL
        # node-row change (register, death, drain, changed availability)
        # bumps _view_version and stamps the row; a heartbeat carrying the
        # client's last seen version gets only rows newer than it plus
        # removal tombstones. Idle heartbeats don't bump anything, so the
        # steady-state reply is empty — per-interval bytes go from O(N) per
        # raylet (O(N^2) cluster-wide) to O(changes).
        self._view_version = 0
        self._view_removals: collections.deque = collections.deque()
        # Clients whose version predates pruned tombstones get a full-view
        # resync (also covers a GCS restart: versions restart at 0, so a
        # client arriving "from the future" falls back to full view).
        self._removals_floor = 0
        # Heartbeat reply accounting for the scale bench (rows/bytes per
        # reply). Payload measurement costs one msgpack encode per reply, so
        # it is off unless the sim harness turns it on.
        self.hb_account = False
        self.hb_stats = {"replies": 0, "rows": 0, "full_replies": 0, "view_bytes": 0}
        # Bumped by mutating handlers; the persist loop skips unchanged state.
        self._mutations = 0
        self._subscribers: dict[str, list] = {}  # channel -> [writer]
        self._raylet_clients: dict[str, RpcClient] = {}
        # actor_id -> in-flight creation-schedule future (register retries
        # share one schedule; NOT in the actor info dict — that is
        # WAL-persisted and a Future is unserializable).
        self._creation_inflight: dict = {}
        self._io = EventLoopThread.get()
        # Write-ahead log (reference durability bar: redis_store_client.h).
        # Restore + open the WAL BEFORE the server starts accepting: a
        # mutation acknowledged while _wal_file were still None would skip
        # logging, and replay racing live handlers could clobber fresh
        # entries with stale values — both break the "acknowledged means
        # durable" contract documented above.
        self._wal_path = persist_path + ".wal" if persist_path else None
        self._wal_file = None
        self._wal_records = 0
        # RAY_TPU_WAL_FSYNC: "0" flush-only, "1" per-mutation fsync,
        # "everysec" batched fdatasync (default; redis everysec class).
        # An unrecognized value must not silently mean flush-only.
        self._wal_fsync = str(get_config().wal_fsync).lower()
        if self._wal_fsync not in ("0", "1", "everysec"):
            logger.warning(
                "unknown wal_fsync=%r; falling back to 'everysec'", self._wal_fsync
            )
            self._wal_fsync = "everysec"
        self._wal_dirty = False
        self._wal_dirty_epoch = 0
        restored = False
        if persist_path and os.path.exists(persist_path):
            self._load_snapshot()
            restored = True
        if self._wal_path:
            restored = self._replay_wal() or restored
            # Append mode: replayed records stay until the next snapshot
            # truncates them (replay is idempotent — records are full values).
            self._wal_file = open(self._wal_path, "ab")
        self.server.start(host, port)
        self.address = self.server.address
        self._health_task = self._io.spawn(self._health_check_loop())
        if restored:
            self._io.spawn(self._recover_loaded_actors())
            self._io.spawn(self._recover_loaded_pgs())
        self._persist_task = (
            self._io.spawn(self._persist_loop()) if persist_path else None
        )

    # ------------------------------------------------------------------
    # Nodes & health
    # ------------------------------------------------------------------

    @schema(node_id=str, address=list, resources=dict)
    async def rpc_register_node(self, req):
        self._mutations += 1
        node_id = req["node_id"]
        self.nodes[node_id] = {
            "node_id": node_id,
            "address": req["address"],
            "resources_total": req["resources"],
            "resources_available": dict(req["resources"]),
            "labels": req.get("labels", {}),
            "arena_name": req.get("arena_name", ""),
            "state": "ALIVE",
            "last_heartbeat": time.monotonic(),
            "store_usage": {},
        }
        self._bump_view(node_id)
        await self._publish("node_updates", {"node_id": node_id, "state": "ALIVE"})
        # New capacity may make parked placement groups feasible.
        asyncio.ensure_future(self._retry_pending_pgs())
        return {"ok": True}

    @schema(node_id=str)
    async def rpc_heartbeat(self, req):
        node = self.nodes.get(req["node_id"])
        if node is None:
            # Not "dead" — we may have restarted and lost the (non-persisted)
            # node table; the raylet re-registers and carries on (reference:
            # HandleRayletNotifyGCSRestart, core_worker.cc:3149).
            return {"ok": False, "unknown": True}
        if node["state"] == "DEAD":
            return {"ok": False, "dead": True}
        node["last_heartbeat"] = time.monotonic()
        avail = req.get("resources_available")
        if avail is not None and avail != node["resources_available"]:
            # Material change: peers mirror availability into their local
            # sched_core ledgers, so it must flow. Idle heartbeats (same
            # availability) stamp nothing — the delta reply stays empty.
            node["resources_available"] = avail
            self._bump_view(req["node_id"])
        node["store_usage"] = req.get("store_usage", node["store_usage"])
        node["load"] = req.get("load", [])
        node["num_active_workers"] = req.get("num_active_workers", 0)
        # Return the cluster resource view: this doubles as the resource
        # syncer (reference: src/ray/common/ray_syncer/ray_syncer.h:86).
        resp = {"ok": True, "tracing": bool(self.kv.get("tracing:enabled"))}
        client_ver = req.get("view_version")
        if client_ver is None:
            # Legacy client: full view every interval (O(N) per reply).
            resp["nodes"] = self._cluster_view()
            self._account_hb(resp["nodes"], full=True)
            return resp
        if (
            client_ver == 0
            or client_ver > self._view_version
            or client_ver < self._removals_floor
        ):
            # First contact, a GCS restart (client from the future), or the
            # client missed so many generations its tombstones were pruned:
            # full-view resync.
            resp["view"] = self._cluster_view()
            resp["view_removed"] = []
            resp["view_full"] = True
            self._account_hb(resp["view"], full=True)
        else:
            resp["view"] = {
                nid: self._view_row(n)
                for nid, n in self.nodes.items()
                if n["state"] == "ALIVE" and n.get("view_ver", 0) > client_ver
            }
            resp["view_removed"] = [
                nid for ver, nid in self._view_removals if ver > client_ver
            ]
            resp["view_full"] = False
            self._account_hb(resp["view"], full=False)
        resp["view_version"] = self._view_version
        return resp

    def _view_row(self, n: dict) -> dict:
        return {
            "address": n["address"],
            "resources_total": n["resources_total"],
            "resources_available": n["resources_available"],
            "labels": n["labels"],
            "state": n["state"],
        }

    def _cluster_view(self):
        return {
            nid: self._view_row(n)
            for nid, n in self.nodes.items()
            if n["state"] == "ALIVE"
        }

    def _bump_view(self, node_id: str, removed: bool = False):
        """Stamp one node-row change into the versioned view. ``removed``
        appends a tombstone (death/drain — the row leaves the ALIVE view);
        tombstone history is bounded, with the pruned floor forcing lagging
        clients onto the full-resync path."""
        self._view_version += 1
        if removed:
            self._view_removals.append((self._view_version, node_id))
            while len(self._view_removals) > 1024:
                pruned_ver, _ = self._view_removals.popleft()
                self._removals_floor = pruned_ver
        else:
            node = self.nodes.get(node_id)
            if node is not None:
                node["view_ver"] = self._view_version

    def _account_hb(self, rows: dict, full: bool):
        self.hb_stats["replies"] += 1
        self.hb_stats["rows"] += len(rows)
        if full:
            self.hb_stats["full_replies"] += 1
        if self.hb_account and rows:
            import msgpack

            try:
                self.hb_stats["view_bytes"] += len(
                    msgpack.packb(rows, use_bin_type=True)
                )
            except Exception:
                pass

    async def rpc_get_nodes(self, req):
        return {"nodes": self.nodes}

    @schema(node_id=str, stats=dict)
    async def rpc_report_node_stats(self, req):
        """Per-node dashboard agent report (dashboard/agent.py): host CPU/mem,
        per-worker process stats, accelerator presence."""
        node = self.nodes.get(req["node_id"])
        if node is None:
            return {"ok": False}
        node["stats"] = req.get("stats", {})
        return {"ok": True}

    async def rpc_drain_node(self, req):
        node = self.nodes.get(req["node_id"])
        if node is not None:
            node["state"] = "DRAINING"
            # Leaves the ALIVE view: delta clients must see the removal.
            self._bump_view(req["node_id"], removed=True)
        return {"ok": True}

    async def _health_check_loop(self):
        # Reference: GcsHealthCheckManager (gcs_health_check_manager.h:39).
        while True:
            await asyncio.sleep(self.cfg.heartbeat_interval_s)
            now = time.monotonic()
            for node_id, node in list(self.nodes.items()):
                if node["state"] != "ALIVE":
                    continue
                if now - node["last_heartbeat"] > self.cfg.node_death_timeout_s:
                    await self._on_node_death(node_id)

    async def _on_node_death(self, node_id: str):
        node = self.nodes.get(node_id)
        if node is None or node["state"] == "DEAD":
            return
        node["state"] = "DEAD"
        logger.warning("GCS: node %s declared dead", node_id[:8])
        self._bump_view(node_id, removed=True)
        # Drop its object copies from the directory — via the per-node
        # reverse index: O(rows on the dead node), not O(all rows). The
        # legacy full scan is kept behind the config toggle as the measured
        # baseline arm for the scale bench.
        if self.cfg.gcs_location_index:
            for oid in self._locations_by_node.pop(node_id, set()):
                locs = self.object_locations.get(oid)
                if locs is not None:
                    locs.discard(node_id)
                    if not locs:
                        del self.object_locations[oid]
        else:
            self._locations_by_node.pop(node_id, None)
            for oid, locs in list(self.object_locations.items()):
                locs.discard(node_id)
                if not locs:
                    del self.object_locations[oid]
        # Restart or kill its actors.
        for actor_id, info in list(self.actors.items()):
            if info.get("node_id") == node_id and info["state"] in (ALIVE, PENDING_CREATION):
                await self._handle_actor_failure(actor_id, f"node {node_id[:8]} died")
        await self._publish("node_updates", {"node_id": node_id, "state": "DEAD"})

    # ------------------------------------------------------------------
    # Actors (reference: gcs_actor_manager.h:281 + gcs_actor_scheduler.h)
    # ------------------------------------------------------------------

    async def rpc_register_actor(self, req):
        self._mutations += 1
        spec = TaskSpec.from_wire(req["spec"])
        actor_id = spec.actor_id
        # IDEMPOTENT under at-least-once delivery: owners now retry a
        # register whose reply was lost (bounded per-attempt timeout), and
        # re-running the body would clobber a live actor's state back to
        # PENDING_CREATION and schedule a DUPLICATE creation. Serve the
        # remembered outcome instead; if the first attempt registered but
        # could not schedule, re-drive just the scheduling.
        prior = self.actors.get(actor_id)
        if prior is not None and prior["state"] != DEAD:
            return await self._ensure_creation_scheduled(actor_id)
        if spec.actor_name:
            key = (spec.namespace, spec.actor_name)
            existing = self.named_actors.get(key)
            if existing is not None and self.actors[existing]["state"] != DEAD:
                if spec.get_if_exists:
                    return {"ok": True, "existing": True, "actor_id": existing}
                return {"ok": False, "error": f"actor name {spec.actor_name!r} taken"}
            self.named_actors[key] = actor_id
        self.actors[actor_id] = {
            "actor_id": actor_id,
            "state": PENDING_CREATION,
            "spec": req["spec"],
            "address": None,
            "node_id": None,
            "worker_id": None,
            "name": spec.actor_name,
            "namespace": spec.namespace,
            "num_restarts": 0,
            "max_restarts": spec.max_restarts,
            "death_cause": "",
        }
        self._wal("actors", actor_id)
        if spec.actor_name:
            self._wal("named_actors", (spec.namespace, spec.actor_name))
        return await self._ensure_creation_scheduled(actor_id)

    async def _ensure_creation_scheduled(self, actor_id: str) -> dict:
        """Schedule the creation AT MOST ONCE even under concurrent
        register retries: an owner whose first reply was lost re-enters
        while the first schedule may still be awaiting its raylet ack —
        both must share ONE in-flight schedule (kept OUTSIDE the actor
        info dict: that dict is WAL-persisted and a Future is not
        serializable) instead of racing duplicate creations."""
        info = self.actors[actor_id]
        if info.get("create_scheduled"):
            return {"ok": True, "existing": False, "actor_id": actor_id}
        fut = self._creation_inflight.get(actor_id)
        if fut is None:
            fut = self._creation_inflight[actor_id] = asyncio.ensure_future(
                self._schedule_actor_creation(actor_id)
            )
        try:
            ok = await fut
        finally:
            if self._creation_inflight.get(actor_id) is fut:
                self._creation_inflight.pop(actor_id, None)
        if not ok:
            return {"ok": False, "error": "no feasible node for actor"}
        info["create_scheduled"] = True
        return {"ok": True, "existing": False, "actor_id": actor_id}

    async def _schedule_actor_creation(self, actor_id: str) -> bool:
        """Forward the creation task to a raylet (GcsActorScheduler analog).
        A target that cannot be REACHED (partitioned/resetting — its
        heartbeat may not have lapsed yet) is excluded and the creation
        fails over to the next feasible node: an unreachable first pick
        used to surface as a bogus 'no feasible node' with two healthy
        nodes sitting idle."""
        info = self.actors[actor_id]
        spec = TaskSpec.from_wire(info["spec"])
        tried: set[str] = set()
        for _ in range(3):
            target = self._pick_node_for(spec, exclude=tried)
            if target is None:
                return False
            client = self._raylet_client(target)
            try:
                # Two bounded attempts per node, then fail over (the
                # transport default of 3 retries would turn 10s into ~40s
                # per node and eat the owner's whole register budget inside
                # one pick; zero retries let a single silently-dropped
                # reply burn a healthy node — three drops exhausted the
                # whole candidate list into a bogus 'no feasible node').
                # A PARTITIONED pick still fails over in ~0.2s: its
                # ConnectionLost is fail-fast, only silent drops pay the
                # 10s slice. A reply lost AFTER the raylet accepted can
                # double-submit; the actor_alive incumbent guard resolves
                # that (duplicate worker exits).
                await client.acall(
                    "submit_task", {"spec": info["spec"]}, timeout=10, retries=1
                )
                return True
            except Exception:
                tried.add(target)
                logger.warning(
                    "failed to submit actor creation to node %s; failing over",
                    target[:8],
                )
        return False

    def _pick_node_for(self, spec: TaskSpec, exclude: set | None = None) -> str | None:
        # Least-loaded feasible node.
        best, best_score = None, None
        for node_id, node in self.nodes.items():
            if node["state"] != "ALIVE":
                continue
            if exclude and node_id in exclude:
                continue
            total = node["resources_total"]
            if any(total.get(k, 0) < v for k, v in spec.resources.items()):
                continue
            avail = node["resources_available"]
            score = sum(avail.get(k, 0) / max(total.get(k, 1), 1) for k in ("CPU", "TPU"))
            if best_score is None or score > best_score:
                best, best_score = node_id, score
        return best

    async def rpc_actor_alive(self, req):
        info = self.actors.get(req["actor_id"])
        if info is None:
            return {"ok": False}
        if info.get("state") == ALIVE and info.get("worker_id") not in (None, req.get("worker_id")):
            # A second worker created the same actor (e.g. restart-recovery
            # raced an in-flight creation): the incumbent wins, the duplicate
            # process must exit. Remember it so its death report is ignored
            # even if the incumbent's state changes before the report lands.
            info.setdefault("rejected_workers", []).append(req.get("worker_id"))
            return {"ok": False, "duplicate": True}
        self._mutations += 1
        info.update(
            state=ALIVE,
            address=req["address"],
            node_id=req["node_id"],
            worker_id=req.get("worker_id"),
        )
        self._wal("actors", req["actor_id"])
        await self._publish("actor_updates", {"actor_id": req["actor_id"], "state": ALIVE, "address": req["address"]})
        return {"ok": True}

    async def rpc_report_worker_death(self, req):
        """Raylet reports a dead worker and any actor it hosted."""
        self._mutations += 1
        reporter = req.get("worker_id")
        for actor_id in req.get("actor_ids", []):
            info = self.actors.get(actor_id)
            if info is not None and reporter:
                rejected = info.get("rejected_workers") or []
                if reporter in rejected:
                    # A rejected duplicate exiting — expected, regardless of
                    # the incumbent's current state.
                    rejected.remove(reporter)
                    continue
                if (
                    info.get("state") == ALIVE
                    and info.get("worker_id")
                    and info["worker_id"] != reporter
                ):
                    # A different worker than the actor's registered host
                    # died; the incumbent is healthy — ignore.
                    continue
            await self._handle_actor_failure(actor_id, req.get("reason", "worker died"))
        return {"ok": True}

    async def _handle_actor_failure(self, actor_id: str, reason: str):
        info = self.actors.get(actor_id)
        if info is None or info["state"] == DEAD:
            return
        self._mutations += 1
        max_restarts = info["max_restarts"]
        if max_restarts == -1 or info["num_restarts"] < max_restarts:
            info["num_restarts"] += 1
            info["state"] = RESTARTING
            info["address"] = None
            from ray_tpu._private import flight_recorder, self_metrics

            flight_recorder.record(
                "actor_restart", f"{actor_id[:8]}:n={info['num_restarts']}"
            )
            try:
                self_metrics.instruments()["actor_restarts"].inc()
            except Exception:
                pass
            self._wal("actors", actor_id)
            await self._publish("actor_updates", {"actor_id": actor_id, "state": RESTARTING})
            ok = await self._schedule_actor_creation(actor_id)
            if ok:
                return
            reason += " (restart scheduling failed)"
        info["state"] = DEAD
        info["death_cause"] = reason
        info["address"] = None
        self._wal("actors", actor_id)
        await self._publish("actor_updates", {"actor_id": actor_id, "state": DEAD, "reason": reason})

    async def rpc_kill_actor(self, req):
        self._mutations += 1
        actor_id = req["actor_id"]
        info = self.actors.get(actor_id)
        if info is None:
            return {"ok": False}
        no_restart = req.get("no_restart", True)
        addr = info.get("address")
        if no_restart:
            info["state"] = DEAD
            info["death_cause"] = "ray_tpu.kill"
            self._wal("actors", actor_id)
            if info.get("name"):
                self.named_actors.pop((info["namespace"], info["name"]), None)
                self._wal("named_actors", (info["namespace"], info["name"]))
        if addr:
            client = None
            try:
                client = RpcClient(tuple(addr), label="actor-worker")
                # Best-effort and BOUNDED: the worker address is ephemeral
                # and may have been reused by an unrelated listener that
                # accepts but never replies (observed: a cycled port landing
                # on a non-framework server hung this await — and with it
                # the caller's no-timeout kill() — forever). The worker
                # reaper + actor-updates publish cover delivery failure.
                # Outer wait_for: acall RETRIES TimeoutError internally, so
                # a per-attempt timeout alone would still take 4x + sleeps.
                await asyncio.wait_for(
                    client.acall("kill_self", {"no_restart": no_restart}, timeout=5),
                    timeout=5,
                )
            except Exception:
                pass
            finally:
                if client is not None:
                    client.close()  # timeout path must not leak the socket
        if no_restart:
            await self._publish("actor_updates", {"actor_id": actor_id, "state": DEAD, "reason": "killed"})
        return {"ok": True}

    async def rpc_get_actor(self, req):
        actor_id = req.get("actor_id")
        if actor_id is None:
            key = (req.get("namespace", ""), req["name"])
            actor_id = self.named_actors.get(key)
            if actor_id is None:
                return {"found": False}
        info = self.actors.get(actor_id)
        if info is None:
            return {"found": False}
        out = {k: v for k, v in info.items() if k != "spec"}
        return {"found": True, "info": out}

    async def rpc_list_actors(self, req):
        return {
            "actors": [
                {k: v for k, v in info.items() if k != "spec"} for info in self.actors.values()
            ]
        }

    # ------------------------------------------------------------------
    # KV store (reference: gcs_kv_manager.h; function table rides on this)
    # ------------------------------------------------------------------

    @schema(key=str, value=bytes)
    async def rpc_kv_put(self, req):
        self._mutations += 1
        overwrite = req.get("overwrite", True)
        key = req["key"]
        if not overwrite and key in self.kv:
            return {"ok": False, "added": False}
        self.kv[key] = req["value"]
        self._wal("kv", key)
        return {"ok": True, "added": True}

    @schema(key=str)
    async def rpc_kv_get(self, req):
        value = self.kv.get(req["key"])
        return {"found": value is not None, "value": value}

    @schema(key=str)
    async def rpc_kv_del(self, req):
        self._mutations += 1
        existed = self.kv.pop(req["key"], None) is not None
        if existed:
            self._wal("kv", req["key"])
        return {"ok": True, "existed": existed}

    async def rpc_kv_keys(self, req):
        prefix = req.get("prefix", "")
        return {"keys": [k for k in self.kv if k.startswith(prefix)]}

    # ------------------------------------------------------------------
    # Object directory
    # ------------------------------------------------------------------

    @schema(object_id=str, node_id=str)
    async def rpc_add_object_location(self, req):
        self.object_locations.setdefault(req["object_id"], set()).add(req["node_id"])
        self._locations_by_node.setdefault(req["node_id"], set()).add(req["object_id"])
        return {"ok": True}

    @schema(object_id=str, node_id=str)
    async def rpc_remove_object_location(self, req):
        locs = self.object_locations.get(req["object_id"])
        if locs:
            locs.discard(req["node_id"])
            if not locs:
                del self.object_locations[req["object_id"]]
        by_node = self._locations_by_node.get(req["node_id"])
        if by_node:
            by_node.discard(req["object_id"])
            if not by_node:
                del self._locations_by_node[req["node_id"]]
        return {"ok": True}

    @schema(object_id=str)
    async def rpc_get_object_locations(self, req):
        locs = self.object_locations.get(req["object_id"], set())
        out = []
        for nid in locs:
            node = self.nodes.get(nid)
            if node and node["state"] == "ALIVE":
                out.append({"node_id": nid, "address": node["address"]})
        return {"locations": out}

    # ------------------------------------------------------------------
    # Placement groups (reference: gcs_placement_group_manager.h, 2PC in
    # gcs_placement_group_scheduler.h; bundle policies PACK/SPREAD/
    # STRICT_PACK/STRICT_SPREAD in policy/bundle_scheduling_policy.h:31)
    # ------------------------------------------------------------------

    async def rpc_create_placement_group(self, req):
        self._mutations += 1
        pg_id = req["pg_id"]
        bundles = req["bundles"]  # list[dict resource->qty]
        strategy = req.get("strategy", "PACK")
        self.placement_groups[pg_id] = {
            "pg_id": pg_id,
            "bundles": bundles,
            "strategy": strategy,
            "state": "PENDING",
            "bundle_nodes": [None] * len(bundles),
            "name": req.get("name", ""),
        }
        self._wal("placement_groups", pg_id)
        ok = await self._schedule_placement_group(pg_id)
        return {"ok": ok, "state": self.placement_groups[pg_id]["state"]}

    async def _schedule_placement_group(self, pg_id: str) -> bool:
        # In-flight guard: concurrent retries (two nodes registering in the
        # same window both kick _retry_pending_pgs) must not run the 2PC
        # twice — prepare_bundle is not idempotent and a double
        # prepare+commit double-acquires the bundle's resources.
        inflight = getattr(self, "_pg_scheduling", None)
        if inflight is None:
            inflight = self._pg_scheduling = set()
        if pg_id in inflight:
            return False
        inflight.add(pg_id)
        try:
            return await self._schedule_placement_group_inner(pg_id)
        finally:
            inflight.discard(pg_id)

    async def _schedule_placement_group_inner(self, pg_id: str) -> bool:
        pg = self.placement_groups[pg_id]
        bundles, strategy = pg["bundles"], pg["strategy"]
        alive = [(nid, n) for nid, n in self.nodes.items() if n["state"] == "ALIVE"]
        plan = self._plan_bundles(bundles, strategy, alive)
        if plan is None:
            pg["state"] = "PENDING"  # infeasible now; retried on node join
            return False
        # Phase 1: prepare (reserve) on each node; Phase 2: commit.
        reserved = []
        try:
            for idx, node_id in enumerate(plan):
                client = self._raylet_client(node_id)
                resp = await client.acall(
                    "prepare_bundle",
                    {"pg_id": pg_id, "bundle_index": idx, "resources": bundles[idx]},
                )
                if not resp.get("ok"):
                    raise RuntimeError(f"bundle {idx} reserve failed on {node_id[:8]}")
                reserved.append((idx, node_id))
            for idx, node_id in reserved:
                await self._raylet_client(node_id).acall(
                    "commit_bundle", {"pg_id": pg_id, "bundle_index": idx}
                )
        except Exception as e:
            logger.warning("PG %s scheduling rolled back: %s", pg_id[:8], e)
            for idx, node_id in reserved:
                try:
                    await self._raylet_client(node_id).acall(
                        "return_bundle", {"pg_id": pg_id, "bundle_index": idx}
                    )
                except Exception:
                    pass
            return False
        pg["bundle_nodes"] = list(plan)
        pg["state"] = "CREATED"
        self._wal("placement_groups", pg_id)
        await self._publish("pg_updates", {"pg_id": pg_id, "state": "CREATED"})
        return True

    def _plan_bundles(self, bundles, strategy, alive):
        """Bin-pack bundles onto nodes honoring the placement strategy."""
        avail = {nid: dict(n["resources_available"]) for nid, n in alive}

        def fits(nid, res):
            return all(avail[nid].get(k, 0) >= v for k, v in res.items())

        def take(nid, res):
            for k, v in res.items():
                avail[nid][k] = avail[nid].get(k, 0) - v

        plan: list[str | None] = [None] * len(bundles)
        if strategy == "STRICT_PACK":
            # All bundles on a single node (maps to "one ICI slice" for TPU
            # gang scheduling — see util/placement_group.py).
            for nid, _ in alive:
                trial = dict(avail[nid])
                ok = True
                for b in bundles:
                    if all(trial.get(k, 0) >= v for k, v in b.items()):
                        for k, v in b.items():
                            trial[k] = trial.get(k, 0) - v
                    else:
                        ok = False
                        break
                if ok:
                    return [nid] * len(bundles)
            return None
        if strategy == "STRICT_SPREAD":
            if len(bundles) > len(alive):
                return None
            used_nodes: set[str] = set()
            for i, b in enumerate(bundles):
                placed = False
                for nid, _ in alive:
                    if nid in used_nodes:
                        continue
                    if fits(nid, b):
                        take(nid, b)
                        plan[i] = nid
                        used_nodes.add(nid)
                        placed = True
                        break
                if not placed:
                    return None
            return plan
        # PACK / SPREAD best-effort.
        order = list(alive)
        for i, b in enumerate(bundles):
            if strategy == "SPREAD":
                order = sorted(alive, key=lambda kv: sum(1 for p in plan if p == kv[0]))
            placed = False
            for nid, _ in order:
                if fits(nid, b):
                    take(nid, b)
                    plan[i] = nid
                    placed = True
                    break
            if not placed:
                return None
        return plan

    async def rpc_remove_placement_group(self, req):
        self._mutations += 1
        pg = self.placement_groups.get(req["pg_id"])
        if pg is None:
            return {"ok": False}
        for idx, node_id in enumerate(pg["bundle_nodes"]):
            if node_id is None:
                continue
            try:
                await self._raylet_client(node_id).acall(
                    "return_bundle", {"pg_id": req["pg_id"], "bundle_index": idx}
                )
            except Exception:
                pass
        pg["state"] = "REMOVED"
        self._wal("placement_groups", req["pg_id"])
        return {"ok": True}

    async def rpc_get_placement_group(self, req):
        pg = self.placement_groups.get(req["pg_id"])
        if pg is None:
            return {"found": False}
        return {"found": True, "info": pg}

    # ------------------------------------------------------------------
    # Jobs
    # ------------------------------------------------------------------

    async def rpc_next_job_id(self, req):
        self._mutations += 1
        self._job_counter += 1
        job_id = f"{self._job_counter:08x}"
        self.jobs[job_id] = {"job_id": job_id, "state": "RUNNING", "start_time": time.time()}
        self._wal("job_counter")
        self._wal("jobs", job_id)
        return {"job_id": job_id}

    async def rpc_list_jobs(self, req):
        return {"jobs": list(self.jobs.values())}

    async def rpc_mark_job_finished(self, req):
        self._mutations += 1
        job = self.jobs.get(req["job_id"])
        if job is not None:
            job["state"] = req.get("state", "SUCCEEDED")
            job["end_time"] = time.time()
            self._wal("jobs", req["job_id"])
        return {"ok": job is not None}

    async def rpc_list_placement_groups(self, req):
        out = []
        for pg_id, pg in self.placement_groups.items():
            entry = {k: v for k, v in pg.items() if k != "client"}
            entry.setdefault("pg_id", pg_id)
            out.append(entry)
        return {"placement_groups": out}

    # ------------------------------------------------------------------
    # Task events (reference: gcs_task_manager.h; powers `ray timeline`)
    # ------------------------------------------------------------------

    @schema(events=list)
    async def rpc_record_task_events(self, req):
        events = req["events"]
        ring = self.task_events
        overflow = len(ring) + len(events) - ring.maxlen
        ring.extend(events)  # deque(maxlen=...) drops oldest — never blocks
        if overflow > 0:
            self.events_dropped_total += overflow
            from ray_tpu._private import flight_recorder, self_metrics

            try:
                self_metrics.instruments()["gcs_events_dropped"].inc(overflow)
            except Exception:
                pass
            now = time.monotonic()
            if now - self._overload_flight_ts >= 5.0:
                # Rate-limited: the overload condition is per-burst news,
                # per-batch stamps would themselves flood the flight ring.
                self._overload_flight_ts = now
                flight_recorder.record(
                    "gcs_overload",
                    f"task_events dropped={self.events_dropped_total}",
                )
        return {"ok": True, "dropped": max(0, overflow)}

    async def rpc_get_task_events(self, req):
        limit = req.get("limit", 1000)
        events = list(self.task_events)
        return {"events": events[-limit:]}

    # ------------------------------------------------------------------
    # Pub/sub (reference: src/ray/pubsub/publisher.h:307)
    # ------------------------------------------------------------------

    @schema(channel=str)
    async def rpc_subscribe(self, req):
        """Register the requesting connection for pushes on a channel.

        Channels are fanned out over dedicated RpcClient connections the
        subscriber opens toward GCS; the subscriber passes its own push-back
        address and we connect back (long-poll-free push).
        """
        channel = req["channel"]
        addr = tuple(req["address"]) if isinstance(req["address"], list) else req["address"]
        subs = self._subscribers.setdefault(channel, [])
        # Idempotent per (channel, address): subscribers periodically
        # re-subscribe so a restarted GCS regains them without duplicates.
        for existing in list(subs):
            if getattr(existing, "address", None) == addr:
                subs.remove(existing)
                existing.close()
        client = RpcClient(addr, label=f"sub-{channel}")
        subs.append(client)
        return {"ok": True}

    async def _publish(self, channel: str, message: dict):
        subs = self._subscribers.get(channel, [])
        dead = []
        # Snapshot: rpc_subscribe may mutate the list between awaits.
        for client in list(subs):
            try:
                await client.apush("pubsub", {"channel": channel, "message": message})
            except Exception:
                dead.append(client)
        for d in dead:
            try:
                subs.remove(d)
            except ValueError:
                pass  # a concurrent re-subscribe already replaced it

    @schema(channel=str, message=None)
    async def rpc_publish(self, req):
        await self._publish(req["channel"], req["message"])
        return {"ok": True}

    # ------------------------------------------------------------------
    # Persistence (reference: HA GCS via redis_store_client.h + gcs_init_data.h)
    # ------------------------------------------------------------------

    def _snapshot(self) -> dict:
        # Actor/PG/job tables reload on restart (reference: gcs_init_data.h
        # repopulates managers from Redis). Per-actor RPC clients and the
        # node table are rebuilt live as raylets re-register. Pickled, not
        # JSON: actor specs embed serialized (bytes) arguments.
        return {
            "kv": dict(self.kv),
            "named_actors": dict(self.named_actors),
            "job_counter": self._job_counter,
            "actors": dict(self.actors),
            "placement_groups": self.placement_groups,
            "jobs": self.jobs,
        }

    async def _recover_loaded_actors(self):
        """Re-drive creation of actors snapshotted mid-flight: an actor
        persisted as PENDING_CREATION/RESTARTING has no worker yet and nothing
        else will ever schedule it after a restart. Waits for raylets to
        re-register first."""
        pending = [
            aid
            for aid, a in self.actors.items()
            if a.get("state") in (PENDING_CREATION, RESTARTING)
        ]
        if not pending:
            return
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if any(n["state"] == "ALIVE" for n in self.nodes.values()):
                break
            await asyncio.sleep(0.2)
        # Grace period: an in-flight creation on a surviving raylet may still
        # land (worker spawn takes seconds); only resubmit actors that remain
        # PENDING after it. rpc_actor_alive also rejects duplicates.
        await asyncio.sleep(self.cfg.gcs_actor_recovery_grace_s)
        for aid in pending:
            info = self.actors.get(aid)
            if info is None or info.get("state") not in (PENDING_CREATION, RESTARTING):
                continue
            try:
                await self._schedule_actor_creation(aid)
            except Exception:
                logger.exception("recovery scheduling of actor %s failed", aid[:8])

    async def _retry_pending_pgs(self):
        """Drive parked (infeasible) placement groups; called on node join
        and after a restore (reference: GcsPlacementGroupManager retries
        pending PGs on node add, gcs_placement_group_manager.cc)."""
        for pg_id, pg in list(self.placement_groups.items()):
            if pg.get("state") == "PENDING":
                try:
                    await self._schedule_placement_group(pg_id)
                except Exception:
                    logger.exception("pending PG %s retry failed", pg_id[:8])

    async def _recover_loaded_pgs(self):
        """Re-drive placement groups snapshotted mid-creation: a PG restored
        as PENDING would otherwise wait for a node JOIN that may never come
        (the raylets merely re-register). CREATED PGs need nothing — their
        bundles live on the surviving raylets, which keep their node ids."""
        if not any(pg.get("state") == "PENDING" for pg in self.placement_groups.values()):
            return
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if any(n["state"] == "ALIVE" for n in self.nodes.values()):
                break
            await asyncio.sleep(0.2)
        await asyncio.sleep(self.cfg.gcs_actor_recovery_grace_s)
        await self._retry_pending_pgs()

    async def _persist_loop(self):
        """Mutation-triggered snapshots with a short debounce (the analog of
        the reference's write-through Redis store, gcs_table_storage.h:
        every committed mutation is durable). Heartbeats don't bump
        _mutations, so the steady-state cost is one integer compare per
        tick; a mutation burst coalesces into one snapshot ~150ms later —
        the crash-loss window is that debounce, not a fixed 2s period."""
        saved_at = -1
        last_fsync = time.monotonic()
        while True:
            await asyncio.sleep(0.1)
            # everysec WAL policy: batched fdatasync at most once per second
            # while dirty — host-crash loss window is bounded by ~1s.
            if (
                self._wal_dirty
                and self._wal_file is not None
                and time.monotonic() - last_fsync >= 1.0
            ):
                # Off-loop: a slow disk's fdatasync must not stall heartbeat
                # and lease RPC handling (redis offloads everysec fsync to a
                # background thread for the same reason). Appends landing
                # during the sync bump the epoch, keeping the tail dirty;
                # only a successful sync of an unchanged epoch clears it.
                epoch = self._wal_dirty_epoch
                try:
                    fd = self._wal_file.fileno()
                    await asyncio.get_event_loop().run_in_executor(
                        None, os.fdatasync, fd
                    )
                    if self._wal_dirty_epoch == epoch:
                        self._wal_dirty = False
                except Exception:
                    logger.debug("wal fdatasync failed", exc_info=True)
                last_fsync = time.monotonic()
            if self._mutations == saved_at:
                continue  # nothing changed since the last snapshot
            await asyncio.sleep(0.05)  # coalesce the rest of the burst
            try:
                saved_at = self._mutations
                self._do_save()
            except Exception:
                logger.debug("gcs snapshot failed", exc_info=True)

    # ---- write-ahead log ----

    def _wal(self, table: str, key=None):
        """Append one table entry's NEW value (None = deleted) to the WAL and
        flush, BEFORE the mutating handler replies: an acknowledged mutation
        survives a GCS kill at any later instant (the debounced snapshot
        alone had a ~150ms loss window). Runs on the IO loop thread only."""
        f = self._wal_file
        if f is None:
            return
        import pickle

        if table == "job_counter":
            rec = ("job_counter", None, self._job_counter)
        else:
            rec = (table, key, getattr(self, table).get(key))
        try:
            data = pickle.dumps(rec, protocol=pickle.HIGHEST_PROTOCOL)
            f.write(len(data).to_bytes(4, "big") + data)
            # flush reaches the page cache: survives process kill. Host-crash
            # durability is the fsync policy's job (wal_fsync, redis
            # appendfsync analog): "1" syncs before the handler replies,
            # "everysec" batches fdatasync in _persist_loop (~1s loss
            # window on host crash), "0" stops at the page cache.
            f.flush()
            if self._wal_fsync == "1":
                try:
                    os.fsync(f.fileno())
                except OSError:
                    # The sync-before-reply guarantee cannot hold under I/O
                    # error; say so loudly and hand the tail to the everysec
                    # retry path instead of silently acking as durable.
                    logger.error(
                        "WAL fsync failed; acknowledged mutation is NOT yet "
                        "host-crash durable (will retry via fdatasync)",
                        exc_info=True,
                    )
                    self._wal_dirty = True
                    self._wal_dirty_epoch += 1
            elif self._wal_fsync == "everysec":
                self._wal_dirty = True
                self._wal_dirty_epoch += 1
            self._wal_records += 1
        except Exception:
            logger.debug("wal append failed", exc_info=True)

    def _replay_wal(self) -> bool:
        """Apply the WAL tail over the loaded snapshot. Torn trailing record
        (crash mid-append, pre-ack) is discarded — it was never acknowledged."""
        if not self._wal_path or not os.path.exists(self._wal_path):
            return False
        import pickle

        try:
            with open(self._wal_path, "rb") as f:
                buf = f.read()
        except OSError:
            return False
        pos, applied = 0, 0
        while pos + 4 <= len(buf):
            length = int.from_bytes(buf[pos : pos + 4], "big")
            if pos + 4 + length > len(buf):
                break  # torn tail
            try:
                table, key, value = pickle.loads(buf[pos + 4 : pos + 4 + length])
            except Exception:
                break  # corrupt tail
            pos += 4 + length
            if table == "job_counter":
                self._job_counter = max(self._job_counter, value)
            elif table in ("actors", "named_actors", "kv", "placement_groups", "jobs"):
                tbl = getattr(self, table)
                if value is None:
                    tbl.pop(key, None)
                else:
                    tbl[key] = value
            applied += 1
        if applied:
            logger.info("replayed %d WAL records over the GCS snapshot", applied)
        return applied > 0

    def _do_save(self):
        """Write the snapshot. MUST run on the IO loop thread — tables are
        mutated by RPC handlers on that loop, so this is the only thread from
        which pickling them is race-free. Doubles as WAL compaction: state up
        to this instant is in the snapshot, so the log restarts empty."""
        if not self.persist_path:
            return
        import pickle

        tmp = self.persist_path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(self._snapshot(), f)
            # Under a syncing WAL policy the snapshot must be host-crash
            # durable BEFORE it replaces the old one and truncates the WAL —
            # otherwise compaction trades fsynced WAL records for page-cache
            # bytes and an acknowledged "durable" mutation can vanish.
            if self._wal_fsync != "0":
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, self.persist_path)
        if self._wal_fsync != "0":
            try:
                dfd = os.open(os.path.dirname(self.persist_path) or ".", os.O_RDONLY)
                try:
                    os.fsync(dfd)  # make the rename itself durable
                finally:
                    os.close(dfd)
            except OSError:
                pass
        if self._wal_file is not None:
            self._wal_file.close()
            self._wal_file = open(self._wal_path, "wb")
            self._wal_dirty = False
            self._wal_records = 0

    def save_snapshot(self):
        """Thread-safe snapshot: marshals onto the IO loop."""
        if not self.persist_path:
            return

        async def _save():
            self._do_save()

        self._io.run(_save())

    def _load_snapshot(self):
        import pickle

        try:
            with open(self.persist_path, "rb") as f:
                snap = pickle.load(f)
        except Exception:
            # Legacy JSON snapshot (or corruption): best-effort partial load;
            # never block GCS startup on an unreadable snapshot.
            try:
                with open(self.persist_path) as f:
                    legacy = json.load(f)
                snap = {
                    "kv": {k: bytes.fromhex(v) for k, v in legacy.get("kv", {}).items()},
                    "named_actors": {
                        tuple(k.split("\x00", 1)): a
                        for k, a in legacy.get("named_actors", {}).items()
                    },
                    "job_counter": legacy.get("job_counter", 0),
                }
            except Exception:
                logger.warning("unreadable GCS snapshot %s; starting fresh", self.persist_path)
                return
        self.kv = dict(snap.get("kv", {}))
        self.named_actors.update(snap.get("named_actors", {}))
        self._job_counter = snap.get("job_counter", 0)
        self.actors.update(snap.get("actors", {}))
        self.placement_groups.update(snap.get("placement_groups", {}))
        self.jobs.update(snap.get("jobs", {}))

    def _raylet_client(self, node_id: str) -> RpcClient:
        client = self._raylet_clients.get(node_id)
        if client is None:
            node = self.nodes[node_id]
            client = RpcClient(tuple(node["address"]), label=f"raylet-{node_id[:8]}")
            self._raylet_clients[node_id] = client
        return client

    def stop(self):
        self._health_task.cancel()
        if self._persist_task is not None:
            self._persist_task.cancel()
        self.save_snapshot()
        if self._wal_file is not None:
            try:
                self._wal_file.close()
            except Exception:
                pass
            self._wal_file = None
        for c in self._raylet_clients.values():
            c.close()
        self.server.stop()


def main():
    import argparse

    logging.basicConfig(level=logging.INFO)
    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--address-file", default="")
    parser.add_argument("--persist-path", default="")
    args = parser.parse_args()
    server = GcsServer(args.host, args.port, persist_path=args.persist_path or None)
    if args.address_file:
        tmp = args.address_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"address": list(server.address)}, f)
        os.replace(tmp, args.address_file)
    import threading

    threading.Event().wait()


if __name__ == "__main__":
    main()
