"""Node — session bootstrap and daemon lifecycle.

TPU-native analog of the reference's Node/process-tree launcher
(python/ray/_private/node.py:37, start_ray_processes node.py:1186,
services.py): creates the session directory and brings up the GCS and the
node's raylet.

Deviation from the reference (documented): daemons run in-process on the IO
event-loop thread rather than as separate OS processes — every interaction
still crosses a real socket, so the distributed protocol is identical and
multi-raylet "clusters" on one host (the reference's cluster_utils.Cluster
trick, python/ray/cluster_utils.py:99) work the same way; worker processes are
real subprocesses either way. `gcs.py`/`raylet.py` keep standalone `main()`s
for out-of-process deployment.

TPU detection reads /dev/accel* (TPU chips appear as accelerator devices) —
deliberately without importing jax, because initialising the TPU runtime in
the driver would take the host's TPU client lock and starve worker processes
(see SURVEY.md §7 hard part 5).
"""

from __future__ import annotations

import glob
import os
import time

from ray_tpu._private.config import get_config, init_config
from ray_tpu._private.gcs import GcsServer
from ray_tpu._private.raylet import Raylet


def detect_tpu_chips() -> int:
    if os.environ.get("RAY_TPU_NUM_TPUS"):
        return int(os.environ["RAY_TPU_NUM_TPUS"])
    return len(glob.glob("/dev/accel*"))


def detect_tpu_labels() -> dict:
    labels = {}
    env_type = os.environ.get("TPU_ACCELERATOR_TYPE") or os.environ.get("ACCELERATOR_TYPE")
    if env_type:
        labels["tpu_accelerator_type"] = env_type
    worker_id = os.environ.get("TPU_WORKER_ID")
    if worker_id:
        labels["tpu_worker_id"] = worker_id
    return labels


class Node:
    def __init__(
        self,
        head: bool = True,
        gcs_address=None,
        num_cpus: int | None = None,
        num_tpus: int | None = None,
        resources: dict | None = None,
        object_store_memory: int | None = None,
        labels: dict | None = None,
        session_dir: str | None = None,
        _system_config: dict | None = None,
    ):
        cfg = init_config(_system_config) if head else get_config()
        ts = time.strftime("%Y%m%d-%H%M%S")
        import uuid as _uuid

        # uuid suffix: two inits in the same process+second (common in test
        # suites) must not share a session directory.
        self.session_dir = session_dir or os.path.join(
            cfg.session_dir_root, f"session_{ts}_{os.getpid()}_{_uuid.uuid4().hex[:6]}"
        )
        os.makedirs(os.path.join(self.session_dir, "logs"), exist_ok=True)

        self.gcs_server: GcsServer | None = None
        if head:
            self.gcs_server = GcsServer()
            self.gcs_address = self.gcs_server.address
        else:
            assert gcs_address is not None
            self.gcs_address = tuple(gcs_address)

        node_resources = dict(resources or {})
        node_resources.setdefault("CPU", num_cpus if num_cpus is not None else (os.cpu_count() or 1))
        tpus = num_tpus if num_tpus is not None else detect_tpu_chips()
        if tpus:
            node_resources.setdefault("TPU", tpus)
        node_labels = dict(labels or {})
        node_labels.update(detect_tpu_labels())

        self.raylet = Raylet(
            self.gcs_address,
            self.session_dir,
            resources=node_resources,
            labels=node_labels,
            object_store_memory=object_store_memory,
        )
        self.node_id = self.raylet.node_id

    def stop(self):
        self.raylet.stop()
        if self.gcs_server is not None:
            self.gcs_server.stop()
