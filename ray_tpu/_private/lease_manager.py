"""Owner-side worker-lease transport for normal tasks.

TPU-native analog of the reference's direct task transport
(src/ray/core_worker/transport/direct_task_transport.cc:304 lease
pipelining + lease_policy.h): the owner leases whole WORKERS from the
raylet — lease requests ride the normal scheduling queue, so placement,
fairness and resource accounting are unchanged — and then ships ready
tasks DIRECTLY to the leased worker, pipelined, with results flowing back
over the worker->owner channel that actor calls already use.

The effect on the per-task control plane: the raylet sees one lease
request per held worker instead of four RPCs per task
(submit -> dispatch -> push_task -> task_finished), which is what limited
the task microbenchmark to sync-rate regardless of pipelining depth.

Leases are keyed by (runtime_env, resource shape). A lease is returned
when its shape's queue drains (after a short linger so sync call loops
reuse it), renewed periodically, and failed over: if the worker dies, its
in-flight specs are resubmitted up to each task's max_retries
(reference: task_manager.cc retriable-failure path).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from collections import deque
from dataclasses import dataclass, field

from ray_tpu._private import flight_recorder, self_metrics
from ray_tpu._private.concurrency import any_thread, loop_only
from ray_tpu._private.rpc import RpcClient
from ray_tpu._private.task_spec import TaskSpec
from ray_tpu.exceptions import WorkerCrashedError

logger = logging.getLogger(__name__)


def _bg(coro):
    """Fire-and-forget on the current loop, consuming exceptions (best-effort
    control RPCs like return_worker_lease race shutdown by design)."""
    task = asyncio.ensure_future(coro)
    task.add_done_callback(lambda t: t.cancelled() or t.exception())
    return task


class _LeaseStats:
    """Plain-int lease counters — _feed runs once per staged chunk on the
    dispatch hot loop, where an instrument lock + tag-dict per inc is
    measurable. Folded into ray_tpu_lease_* Counters at metrics-flush
    cadence (self_metrics collector), like rpc.WIRE."""

    __slots__ = ("grants", "reuses", "tasks")

    def __init__(self):
        self.grants = 0
        self.reuses = 0
        self.tasks = 0


LEASE_STATS = _LeaseStats()


class _Lease:
    __slots__ = (
        "lease_id", "worker_id", "address", "client", "shape", "inflight",
        "last_active", "raylet_addr", "ever_used",
    )

    def __init__(self, lease_id, worker_id, address, client, shape, raylet_addr):
        self.lease_id = lease_id
        self.worker_id = worker_id
        self.address = address
        self.client = client
        self.shape = shape
        self.inflight: dict[str, TaskSpec] = {}
        self.last_active = time.monotonic()
        # The raylet holding the lease record — a PEER when the request was
        # spilled; renew/return against anything else silently no-ops and
        # the granting raylet reaps the healthy worker at lease expiry.
        self.raylet_addr = raylet_addr
        # Observability: once a first batch has shipped, later batches count
        # as warm reuses (the hit side of the warm-lease hit ratio).
        self.ever_used = False


@dataclass(eq=False)  # identity hash: shapes are collected in sets
class _Shape:
    key: tuple
    resources: dict
    runtime_env: dict
    queue: deque = field(default_factory=deque)
    leases: dict = field(default_factory=dict)  # lease_id -> _Lease
    pending_requests: set = field(default_factory=set)
    # EMA of observed task duration; drives the staging-depth policy.
    avg_task_s: float | None = None


class LeaseManager:
    """All state lives on the owner's IO loop thread; submit() is the only
    cross-thread entry point."""

    def __init__(self, cw):
        self.cw = cw
        self.cfg = cw.cfg
        self._shapes: dict[tuple, _Shape] = {}
        self._task_lease: dict[str, _Lease] = {}
        self._attempts: dict[str, int] = {}
        self._maintenance_task = None
        self._closed = False
        import threading

        self._submit_lock = threading.Lock()
        self._submit_buf: list = []
        self._submit_scheduled = False
        self._raylet_clients: dict[tuple, RpcClient] = {}
        self._metrics = self_metrics.instruments()

    def _update_pool_gauge(self):
        try:
            self._metrics["lease_pool"].set(
                sum(len(s.leases) for s in self._shapes.values())
            )
        except Exception:
            pass

    def _raylet_for(self, addr):
        """Control client for the raylet holding a lease record (the LOCAL
        raylet unless the request was spilled to a peer)."""
        if addr is None or tuple(addr) == tuple(self.cw.raylet.address):
            return self.cw.raylet
        key = tuple(addr)
        client = self._raylet_clients.get(key)
        if client is None:
            client = self._raylet_clients[key] = RpcClient(key, label=f"lease-raylet")
        return client

    # ---- entry points ----

    @any_thread
    def submit(self, spec: TaskSpec):
        """Any-thread entry: queue the ready-to-run spec for lease dispatch.
        Bursts coalesce into ONE loop hop (a per-spec call_soon_threadsafe
        was measurable at 100-in-flight submission rates)."""
        with self._submit_lock:
            self._submit_buf.append(spec)
            if self._submit_scheduled:
                return
            self._submit_scheduled = True
        self.cw._io.loop.call_soon_threadsafe(self._drain_entry)

    @loop_only
    def _drain_entry(self):
        """Loop callback. The warm sync ping-pong case — ONE pending spec,
        a warm lease with room — stages and writes the lease_exec frame
        synchronously right here: zero further loop hops between the user
        thread's wakeup of the loop and the frame hitting the socket.
        Bursts fall back to the coalescing async drain."""
        with self._submit_lock:
            single = len(self._submit_buf) == 1
            if single:
                batch, self._submit_buf = self._submit_buf, []
                self._submit_scheduled = False
        if not single:
            asyncio.ensure_future(self._drain_submits())
            return
        spec = batch[0]
        shape = self._shape_for(spec)
        shape.queue.append(spec)
        self._pump(shape)

    async def _drain_submits(self):
        await asyncio.sleep(0)  # let the submitting thread's burst accumulate
        with self._submit_lock:
            batch, self._submit_buf = self._submit_buf, []
            self._submit_scheduled = False
        shapes = []
        for spec in batch:
            shape = self._shape_for(spec)
            shape.queue.append(spec)
            if shape not in shapes:
                shapes.append(shape)
        for shape in shapes:
            self._pump(shape)

    def _shape_for(self, spec: TaskSpec) -> _Shape:
        key = (
            json.dumps(spec.runtime_env, sort_keys=True) if spec.runtime_env else "",
            tuple(sorted(spec.resources.items())),
        )
        shape = self._shapes.get(key)
        if shape is None:
            shape = self._shapes[key] = _Shape(
                key=key, resources=dict(spec.resources), runtime_env=dict(spec.runtime_env)
            )
        return shape

    # ---- dispatch ----

    @loop_only
    def _pump(self, shape: _Shape):
        """Synchronous (IO-loop-only): stages ready specs onto warm leases —
        writing the lease_exec frames inline on warm connections — and tops
        up lease requests. Only the RPC *acks* are awaited, in background
        tasks, so one dead worker's 15s timeout can never head-of-line
        block other shapes/leases."""
        if self._closed:
            return
        for lease in list(shape.leases.values()):
            if not shape.queue:
                break
            self._feed(lease)
        want = min(len(shape.queue), self.cfg.lease_max_per_shape) - (
            len(shape.leases) + len(shape.pending_requests)
        )
        for _ in range(max(0, want)):
            asyncio.ensure_future(self._request_lease(shape))
        if self._maintenance_task is None or self._maintenance_task.done():
            self._maintenance_task = asyncio.ensure_future(self._maintenance_loop())

    @loop_only
    def _feed(self, lease: _Lease):
        shape = lease.shape
        # Staging depth adapts to OBSERVED task duration: short tasks stack
        # up to lease_max_inflight (the per-completion round trip would
        # otherwise dominate), long tasks go 1-per-worker — stacking them
        # would serialize work on one lease while other leased workers
        # idle, and parallelism for long tasks comes from MORE leases.
        # Unknown duration (nothing completed yet) is treated as long: the
        # first completion of a fast burst unlocks stacking within ~1ms.
        if shape.avg_task_s is not None and shape.avg_task_s < 0.05:
            depth = self.cfg.lease_max_inflight
        else:
            depth = 1
        room = depth - len(lease.inflight)
        if room <= 0 or not shape.queue:
            return
        chunk = []
        while shape.queue and len(chunk) < room:
            chunk.append(shape.queue.popleft())
        # Warm-lease hit accounting: plain ints on the hottest owner-side
        # loop, folded into instruments at flush time. The flight EVENT is
        # sampled 1-in-64 (with the cumulative reuse count in the detail):
        # task_ship already narrates the ring per task, and a per-chunk
        # reuse event was a measurable slice of the sync-loop budget.
        LEASE_STATS.tasks += len(chunk)
        if lease.ever_used:
            reuses = LEASE_STATS.reuses = LEASE_STATS.reuses + len(chunk)
            if reuses & 63 < len(chunk):
                flight_recorder.record(
                    "lease_reuse", f"{lease.lease_id[:8]}:n={reuses}"
                )
        else:
            lease.ever_used = True
        now = time.monotonic()
        for s in chunk:
            lease.inflight[s.task_id] = s
            self._task_lease[s.task_id] = lease
            if s.hop_ts:
                s.hop_ts["ship"] = now  # worker-direct: no raylet stage
        lease.last_active = now
        payload = {"specs": [s.to_wire() for s in chunk]}
        # Warm connection: the frame is written synchronously HERE (no
        # task-scheduling iteration between staging and the wire); only the
        # accepted-ack is awaited in the background.
        fut = lease.client.send_nowait("lease_exec", payload)
        if fut is not None:
            _bg(self._await_exec_ack(lease, fut))
        else:
            _bg(self._send_exec(lease, payload))

    async def _await_exec_ack(self, lease: _Lease, fut):
        try:
            await asyncio.wait_for(fut, 15)
        except Exception:
            await self._lease_failed(lease, "lease_exec failed")

    async def _send_exec(self, lease: _Lease, payload: dict):
        try:
            await lease.client.acall("lease_exec", payload, timeout=15)
        except Exception:
            await self._lease_failed(lease, "lease_exec failed")

    async def _request_lease(self, shape: _Shape):
        lease_id = os.urandom(12).hex()
        shape.pending_requests.add(lease_id)
        # Locality hint: the head-of-queue task's REFERENCE args ride the
        # lease request (oid + owner only — never inline bytes), so the
        # raylet can prefer a holder node when placing the lease
        # (raylet._locality_prefs; the lease is what spills back).
        head = shape.queue[0] if shape.queue else None
        ref_args = (
            [a for a in head.args if isinstance(a, (list, tuple)) and a and a[0] == "r"]
            if head is not None
            else []
        )
        rep = TaskSpec(
            task_id=lease_id,
            job_id=self.cw.job_id.hex(),
            name="__lease__",
            args=ref_args[: self.cfg.locality_max_args],
            resources=dict(shape.resources),
            runtime_env=dict(shape.runtime_env),
            owner_addr=list(self.cw.address),
            owner_worker_id=self.cw.worker_id,
            lease_id=lease_id,
        )
        try:
            resp = await self.cw.raylet.acall(
                "request_worker_lease",
                # backlog rides the lease request so the autoscaler still
                # sees owner-side queue depth as demand (reference:
                # direct_task_transport.cc backlog_size reporting).
                {"spec": rep.to_wire(), "backlog": len(shape.queue)},
                timeout=self.cfg.worker_lease_timeout_s + 10,
            )
        except Exception:
            resp = {"granted": False}
        shape.pending_requests.discard(lease_id)
        if self._closed or not resp.get("granted"):
            if self._closed and resp.get("granted"):
                _bg(self._raylet_for(resp.get("raylet_address")).acall(
                    "return_worker_lease", {"lease_id": lease_id}))
                return
            if not resp.get("granted"):
                # Make sure no stale request/future lingers at the raylet
                # (e.g. our acall failed at transport level before the
                # server-side timeout resolved it).
                _bg(self.cw.raylet.acall("cancel_lease_request", {"lease_id": lease_id}))
            # No grant (cluster saturated / timeout). If work remains and
            # nothing is coming, retry after a beat instead of spinning.
            if shape.queue and not shape.leases and not shape.pending_requests:
                await asyncio.sleep(0.2)
                self._pump(shape)
            return
        client = RpcClient(tuple(resp["address"]), label=f"lease-{resp['worker_id'][:8]}")
        lease = _Lease(
            lease_id, resp["worker_id"], tuple(resp["address"]), client, shape,
            tuple(resp.get("raylet_address") or self.cw.raylet.address),
        )
        shape.leases[lease_id] = lease
        flight_recorder.record(
            "lease_grant", f"{lease_id[:8]}:worker={resp['worker_id'][:8]}"
        )
        LEASE_STATS.grants += 1
        self._update_pool_gauge()
        self._feed(lease)

    # ---- completion / failure ----

    @loop_only
    def cancel_queued(self, task_id: str) -> bool:
        """Recall a spec still staged owner-side (pre-ship). IO-loop only."""
        with self._submit_lock:
            for s in self._submit_buf:
                if s.task_id == task_id:
                    self._submit_buf.remove(s)
                    return True
        for shape in self._shapes.values():
            for s in shape.queue:
                if s.task_id == task_id:
                    shape.queue.remove(s)
                    self._attempts.pop(task_id, None)
                    return True
        return False

    @loop_only
    def lease_for(self, task_id: str):
        """The lease (worker) a shipped task is in flight on, if any."""
        return self._task_lease.get(task_id)

    @loop_only
    def on_task_done(self, task_id: str, duration_s: float | None = None):
        """Bookkeeping on result arrival (the payload itself is handled by
        CoreWorker._handle_task_done). Returns the shape to top up."""
        self._attempts.pop(task_id, None)
        lease = self._task_lease.pop(task_id, None)
        if lease is None:
            return None
        lease.inflight.pop(task_id, None)
        lease.last_active = time.monotonic()
        shape = lease.shape
        if duration_s is not None:
            shape.avg_task_s = (
                duration_s
                if shape.avg_task_s is None
                else 0.8 * shape.avg_task_s + 0.2 * duration_s
            )
        return shape

    @loop_only
    def topup(self, shapes):
        for shape in shapes:
            if shape is not None and (shape.queue or shape.pending_requests):
                self._pump(shape)

    @loop_only
    def on_lease_revoked(self, lease_id: str, oom: bool = False, reason: str = "revoked by raylet"):
        for shape in self._shapes.values():
            lease = shape.leases.get(lease_id)
            if lease is not None:
                asyncio.ensure_future(self._lease_failed(lease, reason, oom=oom))
                return

    async def _lease_failed(self, lease: _Lease, reason: str, oom: bool = False):
        shape = lease.shape
        if shape.leases.pop(lease.lease_id, None) is None:
            return  # already handled
        flight_recorder.record("lease_revoked", f"{lease.lease_id[:8]}:{reason[:40]}")
        self._update_pool_gauge()
        logger.warning("lease %s failed (%s); %d tasks to retry",
                       lease.lease_id[:8], reason, len(lease.inflight))
        lease.client.close()
        respecs = list(lease.inflight.values())
        lease.inflight.clear()
        _bg(self._raylet_for(lease.raylet_addr).acall(
            "return_worker_lease", {"lease_id": lease.lease_id}))
        for s in respecs:
            self._task_lease.pop(s.task_id, None)
            pending = self.cw.pending_tasks.get(s.task_id)
            if pending is not None and pending.cancel_requested:
                # Cancelled task caught in the failover (e.g. force-kill of
                # the leased worker): surface cancellation, never resubmit.
                self._attempts.pop(s.task_id, None)
                self.cw._fail_task(s.task_id, self.cw._cancel_error(s))
                continue
            attempts = self._attempts.get(s.task_id, 0)
            if attempts < s.max_retries:
                self._attempts[s.task_id] = attempts + 1
                shape.queue.append(s)
            else:
                self._attempts.pop(s.task_id, None)
                if oom:
                    from ray_tpu.exceptions import OutOfMemoryError

                    err: Exception = OutOfMemoryError(
                        f"task {s.name} ({s.task_id[:8]}) failed: {reason}"
                    )
                else:
                    err = WorkerCrashedError(
                        f"worker {lease.worker_id[:8]} died executing leased task "
                        f"({reason}); retries exhausted"
                    )
                self.cw._fail_task(s.task_id, err)
        self._pump(shape)

    # ---- maintenance ----

    async def _maintenance_loop(self):
        while not self._closed:
            await asyncio.sleep(2.0)
            now = time.monotonic()
            by_raylet: dict[tuple, list] = {}
            for shape in self._shapes.values():
                for lease in list(shape.leases.values()):
                    if (
                        not lease.inflight
                        and not shape.queue
                        and now - lease.last_active > self.cfg.lease_idle_release_s
                    ):
                        shape.leases.pop(lease.lease_id, None)
                        lease.client.close()
                        flight_recorder.record("lease_release", lease.lease_id[:8])
                        self._update_pool_gauge()
                        _bg(self._raylet_for(lease.raylet_addr).acall(
                            "return_worker_lease", {"lease_id": lease.lease_id}))
                        continue
                    by_raylet.setdefault(lease.raylet_addr, []).append(lease.lease_id)
                    if lease.inflight and now - lease.last_active > 30.0:
                        # No completion in a long time: probe the worker; a
                        # dead one fails over without waiting for the raylet.
                        asyncio.ensure_future(self._probe(lease))
            # Renew against the raylet that HOLDS each lease (spilled grants
            # live on peers). The LOCAL raylet's renewal also carries the
            # owner's current per-shape backlog: under warm leases the
            # initial request's backlog figure goes stale while the lease is
            # held, and the autoscaler must keep seeing the live queue depth
            # (reference: backlog_size reporting in ReportWorkerBacklog).
            local = tuple(self.cw.raylet.address)
            for addr, ids in by_raylet.items():
                payload = {"lease_ids": ids, "owner": self.cw.worker_id}
                if tuple(addr) == local:
                    payload["backlogs"] = [
                        [dict(s.resources), len(s.queue)]
                        for s in self._shapes.values()
                    ]
                try:
                    resp = await self._raylet_for(addr).acall(
                        "renew_worker_leases", payload, timeout=10
                    )
                    for lid in resp.get("revoked", []):
                        self.on_lease_revoked(lid)
                except Exception:
                    pass

    async def _probe(self, lease: _Lease):
        try:
            await lease.client.acall("lease_ping", {}, timeout=5)
            lease.last_active = time.monotonic()
        except Exception:
            await self._lease_failed(lease, "worker unresponsive")

    @any_thread
    def close(self):
        self._closed = True
        if self._maintenance_task is not None:
            # asyncio.Task.cancel is NOT threadsafe and close() runs on the
            # caller's (shutdown) thread: hop to the loop. Found by graftlint
            # while annotating this file.
            self.cw._io.loop.call_soon_threadsafe(self._maintenance_task.cancel)

        async def _release_all():
            for shape in self._shapes.values():
                for lease in list(shape.leases.values()):
                    lease.client.close()
                    try:
                        await self._raylet_for(lease.raylet_addr).acall(
                            "return_worker_lease", {"lease_id": lease.lease_id}, timeout=2
                        )
                    except Exception:
                        pass
                shape.leases.clear()
            for client in self._raylet_clients.values():
                client.close()

        try:
            self.cw._io.spawn(_release_all()).result(timeout=5)
        except Exception:
            pass
