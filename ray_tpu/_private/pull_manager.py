"""Receiver-side object pull manager.

TPU-native analog of the reference's PullManager
(src/ray/object_manager/pull_manager.h:52). The round-1 pull path fetched
chunks strictly serially from whichever location the GCS listed first and
retried the same ordering after a failure; this manager replaces it with:

- **Pipelined chunk requests**: up to ``pull_pipeline_depth`` fetches in
  flight per source (the push plane's pacing, mirrored).
- **Striping**: when >1 replica exists, chunks round-robin across up to
  ``pull_max_sources`` sources, so a pull drains multiple NICs instead of
  one.
- **Ranked failover**: a source that errors is demoted (timestamped, sorted
  last on the next ranking) and the failed chunk immediately retries on the
  next healthy source — a SIGKILLed replica mid-pull costs one chunk
  timeout, not the pull.
- **Admission control**: concurrent pulls acquire from an aggregate byte
  budget (``pull_admission_budget_bytes``) before allocating arena space;
  past it they queue (``admission_stall`` flight event) instead of
  over-committing the arena. A pull larger than the whole budget still
  admits alone so it cannot deadlock.
- **Raw frames**: chunk requests carry ``raw=True``; a capable source
  answers with a raw frame whose payload the client-side sink scatters
  straight into the arena at ``offset+start`` — no msgpack decode, no
  intermediate ``bytes``. Sources that answer in msgpack (mixed-version)
  are handled transparently per response.
"""

from __future__ import annotations

import asyncio
import logging
import time

from ray_tpu._private import flight_recorder
from ray_tpu._private.concurrency import any_thread, loop_only
from ray_tpu._private.config import get_config
from ray_tpu._private.transfer_stats import TRANSFER

logger = logging.getLogger(__name__)

# Per-attempt ceiling on one chunk RPC: long enough for a multi-MiB chunk on
# a congested link, short enough that a hung source — or a silently lost
# chunk request/reply — costs one bounded stall before the chunk fails over
# to the next healthy replica (the 30s it used to be meant one lost frame
# ate most of a caller's pull budget before failover even started).
_CHUNK_TIMEOUT_S = 10.0

# A demotion stamp this old no longer counts against a source: one transient
# error during startup congestion must not derank (or, with more replicas
# than pull_max_sources, permanently EXCLUDE) a healthy replica forever, and
# pruning aged stamps keeps the penalty table from growing one entry per
# ever-demoted node over a long-lived raylet.
_PENALTY_DECAY_S = 60.0


class PullManager:
    def __init__(self, raylet):
        cfg = get_config()
        self.raylet = raylet
        self.chunk = cfg.object_transfer_chunk_bytes
        self.pipeline_depth = cfg.pull_pipeline_depth
        self.max_sources = max(1, cfg.pull_max_sources)
        self.budget = cfg.pull_admission_budget_bytes
        self.raw_enabled = cfg.transfer_raw_frames
        self._inflight: dict[str, asyncio.Future] = {}
        self._admitted = 0
        self._admit_event = asyncio.Event()
        # node_id -> monotonic stamp of the last transfer error: ranking
        # sorts ascending, so clean sources lead and the most recent
        # offender goes last (demoted, not retried first).
        self._penalty: dict[str, float] = {}

    @any_thread
    def inflight_ids(self) -> set[str]:
        return set(self._inflight)

    @any_thread
    def stats(self) -> dict:
        return {
            "active_pulls": len(self._inflight),
            "admitted_bytes": self._admitted,
            "demoted_sources": len(self._penalty),
        }

    @loop_only
    def _demote(self, node_id: str):
        self._penalty[node_id] = time.monotonic()
        TRANSFER.source_demotions += 1
        flight_recorder.record("pull_source_demoted", node_id[:12])

    def _rank(self, locs: list) -> list:
        cutoff = time.monotonic() - _PENALTY_DECAY_S
        for nid, ts in list(self._penalty.items()):
            if ts < cutoff:
                del self._penalty[nid]
        return sorted(locs, key=lambda l: self._penalty.get(l["node_id"], 0.0))

    # ---- admission (the pull_manager.h:52 byte budget) ----

    async def _admit(self, object_id: str, size: int, deadline: float) -> bool:
        """Acquire `size` bytes of the aggregate pull budget; returns whether
        a reservation was actually taken (budget disabled -> False)."""
        if self.budget <= 0:
            return False
        if self._admitted and self._admitted + size > self.budget:
            TRANSFER.admission_stalls += 1
            flight_recorder.record("admission_stall", f"{object_id[:12]}:{size}")
        while self._admitted and self._admitted + size > self.budget:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"pull of {object_id} timed out waiting for admission "
                    f"({self._admitted}/{self.budget} bytes committed)"
                )
            self._admit_event.clear()
            # Single-threaded loop: _admitted cannot change between the
            # while-check and this wait, so a release cannot be lost.
            try:
                await asyncio.wait_for(self._admit_event.wait(), remaining)
            except asyncio.TimeoutError:
                continue  # re-check -> raises above
        self._admitted += size
        return True

    @loop_only
    def _release_admission(self, size: int):
        self._admitted -= size
        self._admit_event.set()

    # ---- the pull itself ----

    async def pull(self, object_id: str, timeout: float | None) -> bool:
        """Fetch `object_id` into the local store; concurrent callers for the
        same object coalesce onto one pull."""
        fut = self._inflight.get(object_id)
        if fut is not None:
            await fut
            return True
        fut = asyncio.get_event_loop().create_future()
        self._inflight[object_id] = fut
        try:
            deadline = time.monotonic() + (timeout if timeout is not None else 3600.0)
            poll = 0.02
            while time.monotonic() < deadline:
                if self.raylet.store.contains(object_id):
                    # A local task (or inbound push) produced AND SEALED it
                    # while we were looking remotely; an unsealed rival
                    # session doesn't count — it may still be aborted.
                    fut.set_result(True)
                    return True
                resp = await self.raylet.gcs.acall(
                    "get_object_locations", {"object_id": object_id}
                )
                locs = [
                    l for l in resp["locations"] if l["node_id"] != self.raylet.node_id
                ]
                if not locs:
                    await asyncio.sleep(poll)
                    poll = min(poll * 1.5, 0.5)
                    continue
                if await self._attempt(object_id, locs, deadline):
                    fut.set_result(True)
                    return True
                await asyncio.sleep(0.05)
            raise TimeoutError(f"pull of {object_id} timed out")
        except BaseException as e:
            if not fut.done():
                fut.set_exception(e)
            raise
        finally:
            self._inflight.pop(object_id, None)
            if not fut.done():
                fut.set_result(False)

    async def _attempt(self, object_id: str, locs: list, deadline: float) -> bool:
        """One pull attempt over the current location set; False = retry
        after the outer loop refreshes locations."""
        ranked = self._rank(locs)[: self.max_sources]
        infos = await asyncio.gather(
            *(
                self.raylet._peer(loc["node_id"], loc["address"]).acall(
                    "fetch_object_info",
                    {"object_id": object_id},
                    timeout=10,
                    retries=0,
                )
                for loc in ranked
            ),
            return_exceptions=True,
        )
        sources, size = [], None
        for loc, info in zip(ranked, infos):
            if isinstance(info, Exception):
                self._demote(loc["node_id"])
            elif info.get("found"):
                sources.append(loc)
                size = info["size"]
        if not sources:
            return False
        admitted = await self._admit(object_id, size, deadline)
        try:
            offset = await self.raylet.store.create(object_id, size)
            if offset is None:
                # Rival creator appeared during create: sealed -> done;
                # unsealed -> let the outer loop wait for it to resolve.
                return self.raylet.store.contains(object_id)
            # Liveness token for this attempt's raw sinks: once the attempt
            # ends (seal OR abort) a straggling raw response must not write
            # through the captured offset — after an abort the block may
            # already belong to another object (defense in depth on top of
            # rpc.acall unregistering sinks on per-attempt timeout).
            live = {"ok": True}
            try:
                used = await self._fetch_striped(
                    object_id, offset, size, sources, live
                )
            except Exception as e:
                logger.debug("pull attempt for %s failed: %s", object_id[:8], e)
                live["ok"] = False
                self.raylet.store.abort(object_id)
                return False
            finally:
                live["ok"] = False
            self.raylet.store.seal(object_id)
            await self.raylet.gcs.acall(
                "add_object_location",
                {"object_id": object_id, "node_id": self.raylet.node_id},
            )
            TRANSFER.pulls += 1
            TRANSFER.pull_sources += len(used)
            flight_recorder.record(
                "transfer_pull", f"{object_id[:12]}:{size}:{len(used)}src"
            )
            return True
        finally:
            if admitted:
                self._release_admission(size)

    async def _fetch_striped(
        self, object_id: str, offset: int, size: int, sources: list, live: dict
    ) -> set:
        """Fetch all chunks, striped round-robin across `sources` with
        pipeline_depth requests in flight per source; failed sources demote
        and their chunks fail over to the remaining healthy ones. Returns
        the node ids that served at least one chunk."""
        healthy = list(sources)
        sems = {
            loc["node_id"]: asyncio.Semaphore(self.pipeline_depth) for loc in sources
        }
        used: set[str] = set()

        def next_source(idx: int, tried: set):
            if not healthy:
                return None
            shift = idx % len(healthy)
            for src in healthy[shift:] + healthy[:shift]:
                if src["node_id"] not in tried:
                    return src
            return None

        async def fetch(idx: int, start: int):
            length = min(self.chunk, size - start)
            tried: set[str] = set()
            while True:
                src = next_source(idx, tried)
                if src is None:
                    raise RuntimeError(
                        f"chunk {object_id[:8]}@{start}: all sources failed"
                    )
                nid = src["node_id"]
                peer = self.raylet._peer(nid, src["address"])
                try:
                    async with sems[nid]:
                        payload = {
                            "object_id": object_id,
                            "start": start,
                            "length": length,
                        }
                        sink = None
                        if self.raw_enabled:
                            payload["raw"] = True

                            def sink(frame, _start=start, _length=length):
                                # Scatter straight into the arena while the
                                # frame's buffer view is valid — the one and
                                # only copy on the receive side.
                                if not live["ok"]:
                                    # Attempt already sealed/aborted; the
                                    # captured offset may be reused memory.
                                    raise ValueError("stale chunk response")
                                if frame.start != _start or len(frame.payload) > _length:
                                    raise ValueError("raw chunk out of bounds")
                                self.raylet.arena.write(
                                    offset + _start, frame.payload
                                )
                                TRANSFER.chunks_raw_in += 1
                                return {"len": len(frame.payload), "raw": True}

                        resp = await peer.acall(
                            "fetch_object_chunk",
                            payload,
                            timeout=_CHUNK_TIMEOUT_S,
                            retries=0,
                            raw_sink=sink,
                        )
                        if resp.get("raw"):
                            got = resp["len"]
                        else:
                            data = resp["data"]  # msgpack fallback path
                            self.raylet.arena.write(offset + start, data)
                            TRANSFER.chunks_msgpack_in += 1
                            got = len(data)
                        if got != length:
                            raise RuntimeError(f"short chunk: {got} != {length}")
                        TRANSFER.bytes_in += length
                        used.add(nid)
                        # A served chunk is proof of health: clear any stale
                        # demotion so the next ranking treats it as clean.
                        self._penalty.pop(nid, None)
                        return
                except Exception as e:
                    tried.add(nid)
                    self._demote(nid)
                    if src in healthy:
                        healthy.remove(src)
                    logger.debug(
                        "chunk %s@%d from %s failed (%s); failing over",
                        object_id[:8], start, nid[:8], e,
                    )

        tasks = [
            asyncio.ensure_future(fetch(i, start))
            for i, start in enumerate(range(0, size, self.chunk))
        ]
        try:
            await asyncio.gather(*tasks)
        except BaseException:
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            raise
        return used
