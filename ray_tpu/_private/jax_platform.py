"""One place for the force-jax-platforms pin.

A sitecustomize (e.g. a TPU-plugin environment) may pin ``jax_platforms``
via ``jax.config.update`` at interpreter startup — and config BEATS the
``JAX_PLATFORMS`` env var, so a CPU-pinned run must re-update the config in
EVERY process that already imported jax, and set the env var for processes
that haven't. Used by both the driver (``ray_tpu.init``) and workers
(``worker_main``); keep the semantics identical.
"""

from __future__ import annotations

import logging
import os
import sys

logger = logging.getLogger(__name__)


def apply_forced_jax_platforms(forced: str | None = None) -> None:
    """Pin jax to ``forced`` platforms (default: the
    RAY_TPU_JAX_CONFIG_PLATFORMS env var; no-op when unset).

    Overwrites JAX_PLATFORMS (the pin is authoritative — a stale
    conflicting value would dial the wrong backend on the lazy first
    import) and, when jax is already imported, re-updates the config. A
    failed config update is WARNED about, not swallowed: the symptom it
    leads to is a multi-minute TPU-tunnel hang holding the chip claim.
    """
    if forced is None:
        forced = os.environ.get("RAY_TPU_JAX_CONFIG_PLATFORMS")
    if not forced:
        return
    os.environ["JAX_PLATFORMS"] = forced
    if "jax" in sys.modules:
        try:
            import jax

            if jax.config.jax_platforms != forced:
                jax.config.update("jax_platforms", forced)
        except Exception:
            logger.warning(
                "could not re-pin jax_platforms to %r — this process may "
                "initialize the wrong jax backend (and hang dialing a TPU "
                "plugin)",
                forced,
                exc_info=True,
            )
