"""Worker fork-server ("zygote").

TPU-native answer to the reference's worker-pool startup latency problem
(src/ray/raylet/worker_pool.cc:426 prestarts whole processes): instead of
paying a fresh interpreter boot + ~170ms of imports per worker, the raylet
keeps ONE warm process that has already imported the worker runtime and
``os.fork()``s it per worker. On the single-core hosts of the scalability
envelope this turns worker spawn from ~200-300ms of serialized CPU into a
few ms, which is what makes the 40k-actor envelope shape reachable.

Fork-safety rules enforced here:
- the zygote is single-threaded (plain blocking socket + select loop, no
  asyncio, no EventLoopThread) so a fork can never duplicate a held lock;
- nothing TPU-touching is imported pre-fork (jax stays lazy in workers; the
  raylet only uses the zygote on nodes without a TPU resource, so the axon
  sitecustomize dial never runs in this process tree);
- children only inherit imported MODULES, never live sockets (all fds above
  stdio are closed post-fork) or RNG state (ids.py draws from os.urandom).

Protocol (length-prefixed msgpack over one unix-socket control connection
from the raylet):
  -> {"op": "spawn", "req_id": n, "env": {k: v}, "log_out": p, "log_err": p}
  <- {"req_id": n, "pid": pid}            (spawn reply)
  <- {"exit": pid, "returncode": rc}      (async child-exit notification)
Control-connection EOF means the raylet is gone; workers notice on their own
(worker_main's raylet watchdog) so the zygote just exits.
"""

from __future__ import annotations

import os
import select
import socket
import sys

import msgpack


def _pack(msg) -> bytes:
    body = msgpack.packb(msg, use_bin_type=True)
    return len(body).to_bytes(4, "big") + body


class _FrameReader:
    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.buf = b""

    def read_available(self) -> list:
        """Drain readable bytes; return complete frames. MSG_DONTWAIT keeps
        the READ side non-blocking while the socket itself stays blocking —
        sendall() on a non-blocking socket raises on a full buffer, which
        once killed the zygote under an exit-notification burst."""
        try:
            chunk = self.sock.recv(1 << 16, socket.MSG_DONTWAIT)
        except BlockingIOError:
            return []
        if not chunk:
            raise EOFError
        self.buf += chunk
        frames = []
        while len(self.buf) >= 4:
            length = int.from_bytes(self.buf[:4], "big")
            if len(self.buf) < 4 + length:
                break
            frames.append(msgpack.unpackb(self.buf[4 : 4 + length], raw=False))
            self.buf = self.buf[4 + length :]
        return frames


def _child_exec(req: dict):
    """Post-fork path: become a regular worker process. Never returns."""
    try:
        # Stdio to the per-worker log files the raylet chose (same layout as
        # Popen-spawned workers — the log pipeline tails these).
        out_fd = os.open(req["log_out"], os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        err_fd = os.open(req["log_err"], os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        os.dup2(out_fd, 1)
        os.dup2(err_fd, 2)
        # Close everything else we inherited (listener, control conn, the
        # just-dup2'd originals).
        os.closerange(3, 1024)
        for key, value in (req.get("env") or {}).items():
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[str(key)] = str(value)
        # PYTHONPATH is only read at interpreter boot, which a forked child
        # never does — apply it to sys.path by hand (the driver ships its
        # sys.path so unpickled-by-reference functions import).
        pythonpath = os.environ.get("PYTHONPATH", "")
        for p in reversed([p for p in pythonpath.split(os.pathsep) if p]):
            if p not in sys.path:
                sys.path.insert(0, p)
        from ray_tpu._private import worker_main

        worker_main.main()
        os._exit(0)
    except SystemExit as e:
        os._exit(int(e.code or 0))
    except BaseException:
        import traceback

        traceback.print_exc()
        os._exit(97)


def main(socket_path: str):
    # Warm the import graph BEFORE accepting spawns: this is the entire
    # point of the zygote. worker_main's heavy imports live inside main()
    # (they would otherwise run at module import), so pull the real stack
    # explicitly: core_worker -> rpc/serialization -> numpy/msgpack/
    # cloudpickle; ray_tpu's public API is what unpickled user functions
    # reference. jax stays lazy — see module docstring.
    import ray_tpu  # noqa: F401
    import ray_tpu._private.core_worker  # noqa: F401
    import ray_tpu._private.worker_context  # noqa: F401
    import ray_tpu._private.worker_main  # noqa: F401
    import ray_tpu.util.tracing  # noqa: F401

    # dlopen'd native libs survive fork: pre-load the shm arena/index so a
    # child's StoreClient attach is two mmaps, not a build-freshness check +
    # CDLL load (~15ms of its ~20ms boot).
    from ray_tpu._private.store import arena as _arena
    from ray_tpu._private.store import index as _index

    _arena._load_lib()
    _index._load_lib()

    listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        os.unlink(socket_path)
    except OSError:
        pass
    listener.bind(socket_path)
    listener.listen(1)
    # Readiness handshake: the raylet waits for this byte-on-connect.
    conn, _ = listener.accept()
    conn.sendall(_pack({"ready": True}))
    reader = _FrameReader(conn)
    children: set[int] = set()

    def _send(frame) -> bool:
        """Blocking send; False means the raylet is gone. The raylet's
        reader task drains continuously, so a full buffer only ever stalls
        briefly."""
        try:
            conn.sendall(_pack(frame))
            return True
        except (BrokenPipeError, ConnectionResetError, OSError):
            return False

    while True:
        readable, _, _ = select.select([conn], [], [], 0.2)
        if readable:
            try:
                frames = reader.read_available()
            except EOFError:
                os._exit(0)  # raylet is gone
            for req in frames:
                if req.get("op") == "spawn":
                    pid = os.fork()
                    if pid == 0:
                        listener.close()
                        conn.close()
                        _child_exec(req)  # never returns
                    children.add(pid)
                    if not _send({"req_id": req["req_id"], "pid": pid}):
                        os._exit(0)
                elif req.get("op") == "shutdown":
                    os._exit(0)
        # Reap exited children; report so the raylet sees real return codes
        # (a zygote child is not the raylet's child — it cannot waitpid it).
        while children:
            try:
                pid, status = os.waitpid(-1, os.WNOHANG)
            except ChildProcessError:
                children.clear()
                break
            if pid == 0:
                break
            children.discard(pid)
            rc = -(status & 0x7F) if (status & 0x7F) else (status >> 8)
            if not _send({"exit": pid, "returncode": rc}):
                os._exit(0)


async def _aread_frame(reader):
    header = await reader.readexactly(4)
    body = await reader.readexactly(int.from_bytes(header, "big"))
    return msgpack.unpackb(body, raw=False)


class ZygoteWorkerProc:
    """Popen-alike for a zygote-forked worker. The worker is the ZYGOTE's
    child, not ours, so there is no waitpid: liveness comes from kill(0) and
    real exit codes arrive via the zygote's exit notifications."""

    def __init__(self, pid: int):
        self.pid = pid
        self.returncode: int | None = None

    def poll(self):
        if self.returncode is None:
            try:
                os.kill(self.pid, 0)
            except (ProcessLookupError, PermissionError):
                self.returncode = -9  # vanished without a notification
        return self.returncode

    def _signal(self, sig):
        try:
            os.kill(self.pid, sig)
        except (ProcessLookupError, PermissionError):
            pass

    def terminate(self):
        import signal as _signal

        self._signal(_signal.SIGTERM)

    def kill(self):
        import signal as _signal

        self._signal(_signal.SIGKILL)

    def wait(self, timeout: float | None = None):
        import subprocess as _subprocess
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        while self.poll() is None:
            if deadline is not None and _time.monotonic() > deadline:
                raise _subprocess.TimeoutExpired(f"pid {self.pid}", timeout)
            _time.sleep(0.02)
        return self.returncode


class ZygoteClient:
    """Raylet-side handle to the fork-server. All methods run on the raylet's
    IO loop. The zygote process is started lazily on first spawn and
    restarted transparently if it dies; callers fall back to Popen on
    failure (see Raylet._start_worker)."""

    def __init__(self, session_dir: str, base_env: dict, on_exit):
        self.session_dir = session_dir
        self.socket_path = os.path.join(session_dir, f"zyg_{os.getpid()}_{os.urandom(3).hex()}.sock")
        self.base_env = base_env
        self.on_exit = on_exit  # callback(pid, returncode), IO-loop context
        self.proc = None
        self._writer = None
        self._read_task = None
        self._pending: dict[int, object] = {}
        self._req_id = 0
        self._lock = None  # created lazily on the running loop

    async def _start(self):
        import asyncio
        import subprocess
        import time as _time

        log_dir = os.path.join(self.session_dir, "logs")

        def _spawn():
            # fork+exec plus the log-file open are milliseconds of syscalls —
            # off-loop so a slow disk can't stall every RPC on the raylet's
            # loop while the fork-server boots (graftlint:
            # blocking/subprocess-in-async).
            os.makedirs(log_dir, exist_ok=True)
            with open(os.path.join(log_dir, "zygote.log"), "ab") as log:
                return subprocess.Popen(
                    [sys.executable, "-m", "ray_tpu._private.zygote", self.socket_path],
                    env=self.base_env,
                    stdout=log,
                    stderr=subprocess.STDOUT,
                    cwd=os.getcwd(),
                )

        self.proc = await asyncio.get_event_loop().run_in_executor(None, _spawn)
        deadline = _time.monotonic() + 30.0
        while True:
            try:
                reader, writer = await asyncio.open_unix_connection(self.socket_path)
                break
            except OSError:
                if self.proc.poll() is not None:
                    raise RuntimeError(
                        f"zygote exited with code {self.proc.returncode} before listening"
                    )
                if _time.monotonic() > deadline:
                    raise RuntimeError("zygote did not come up within 30s")
                await asyncio.sleep(0.02)
        ready = await _aread_frame(reader)
        if not ready.get("ready"):
            raise RuntimeError(f"unexpected zygote handshake: {ready!r}")
        self._writer = writer
        self._read_task = asyncio.ensure_future(self._read_loop(reader))

    async def _read_loop(self, reader):
        try:
            while True:
                frame = await _aread_frame(reader)
                if "req_id" in frame:
                    fut = self._pending.pop(frame["req_id"], None)
                    if fut is not None and not fut.done():
                        fut.set_result(frame["pid"])
                    else:
                        # Reply for an abandoned spawn (caller timed out and
                        # fell back to Popen): the forked child is an
                        # untracked orphan — reap it.
                        try:
                            os.kill(frame["pid"], 9)
                        except (ProcessLookupError, PermissionError):
                            pass
                elif "exit" in frame:
                    try:
                        self.on_exit(frame["exit"], frame["returncode"])
                    except Exception:
                        pass
        except (EOFError, OSError, Exception):
            pass
        finally:
            self._writer = None
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(RuntimeError("zygote connection lost"))
            self._pending.clear()

    async def spawn(self, env_delta: dict, log_out: str, log_err: str, timeout=60.0) -> int:
        import asyncio

        if self._lock is None:
            self._lock = asyncio.Lock()
        async with self._lock:
            if self._writer is None or (self.proc is not None and self.proc.poll() is not None):
                await self._start()
            self._req_id += 1
            rid = self._req_id
            fut = asyncio.get_event_loop().create_future()
            self._pending[rid] = fut
            self._writer.write(
                _pack(
                    {
                        "op": "spawn",
                        "req_id": rid,
                        "env": env_delta,
                        "log_out": log_out,
                        "log_err": log_err,
                    }
                )
            )
            await self._writer.drain()
        import asyncio as _a

        try:
            return await _a.wait_for(fut, timeout)
        except BaseException:
            # Leave no pending entry behind: a late reply for this req_id
            # must be treated as an orphan (killed in _read_loop), not
            # delivered to a future nobody awaits.
            self._pending.pop(rid, None)
            raise

    def close(self):
        if self._read_task is not None:
            self._read_task.cancel()
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=2)
            except Exception:
                self.proc.kill()
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass


if __name__ == "__main__":
    main(sys.argv[1])
