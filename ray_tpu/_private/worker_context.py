"""Process-global core-worker handle (analog of the reference's global_worker
in python/ray/_private/worker.py:408)."""

from __future__ import annotations

import threading

import contextlib

_lock = threading.Lock()
_core_worker = None
# Thread-local override: the client server executes driver work on behalf of
# thin clients inside a process whose global slot may hold something else (or
# nothing) — e.g. serialization registering deserialized ObjectRefs must bind
# them to the SERVER's driver core worker.
_tls = threading.local()


def set_core_worker(cw) -> None:
    global _core_worker
    with _lock:
        _core_worker = cw


@contextlib.contextmanager
def override(cw):
    prev = getattr(_tls, "cw", None)
    _tls.cw = cw
    try:
        yield
    finally:
        _tls.cw = prev


def get_core_worker():
    cw = getattr(_tls, "cw", None) or _core_worker
    if cw is None:
        raise RuntimeError(
            "ray_tpu has not been initialized; call ray_tpu.init() first."
        )
    return cw


def get_core_worker_if_initialized():
    return getattr(_tls, "cw", None) or _core_worker
