"""Process-global core-worker handle (analog of the reference's global_worker
in python/ray/_private/worker.py:408)."""

from __future__ import annotations

import threading

_lock = threading.Lock()
_core_worker = None


def set_core_worker(cw) -> None:
    global _core_worker
    with _lock:
        _core_worker = cw


def get_core_worker():
    if _core_worker is None:
        raise RuntimeError(
            "ray_tpu has not been initialized; call ray_tpu.init() first."
        )
    return _core_worker


def get_core_worker_if_initialized():
    return _core_worker
