"""Runtime self-metrics — the ``ray_tpu_`` instrument registry.

The reference exports scheduler/store/RPC internals as first-class metrics
(src/ray/stats/metric_defs.cc) next to user-defined instruments; until this
module, our ``/metrics`` endpoint carried **only** user metrics. Every
runtime component (lease transport, dispatch path, object store, RPC plane,
compiled-DAG channels, Serve router, Data executor) now feeds the instruments
below through the existing ``util.metrics`` KV-flush -> ``/metrics`` path —
zero new dependencies, one namespace (``ray_tpu_*``), HELP/TYPE on every
family.

Instruments are created lazily on first use (``instruments()``); hot paths
that cannot afford an instrument lock per event (the RPC frame pump) keep
plain int counters and fold them in via a flush-time collector
(``util.metrics.register_collector``).
"""

from __future__ import annotations

import threading

_lock = threading.Lock()
_instruments: dict | None = None

# Dispatch latency buckets: the warm-lease sync path sits around 1-3 ms on a
# loaded dev box and ~100 µs at the hardware floor; classic/raylet dispatch
# and cold leases land in the 10-100 ms decades.
_LATENCY_BOUNDS = [0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 1.0, 5.0]


def instruments() -> dict:
    """The process-wide ray_tpu_* instrument set (created on first call)."""
    global _instruments
    if _instruments is not None:
        return _instruments
    with _lock:
        if _instruments is not None:
            return _instruments
        from ray_tpu.util import metrics as m

        inst = {
            # --- warm-lease transport (lease_manager.py) ---
            "lease_grants": m.Counter(
                "ray_tpu_lease_grants_total",
                "Worker leases granted to this owner (cold path: one raylet round trip).",
            ),
            "lease_reuses": m.Counter(
                "ray_tpu_lease_reuses_total",
                "Tasks shipped onto an already-warm lease (zero raylet RPCs).",
            ),
            "lease_tasks": m.Counter(
                "ray_tpu_lease_tasks_total",
                "Tasks shipped over the lease transport; hit ratio = reuses/tasks.",
            ),
            "lease_pool": m.Gauge(
                "ray_tpu_lease_pool_size",
                "Currently-held worker leases in this owner.",
            ),
            # --- dispatch latency (sampled hop stamps; config.hop_sample_n) ---
            "dispatch_latency": m.Histogram(
                "ray_tpu_dispatch_latency_s",
                "End-to-end dispatch latency (submit -> completion visible at "
                "owner) from always-on 1-in-N sampled hop stamps.",
                boundaries=_LATENCY_BOUNDS,
                tag_keys=("path",),
            ),
            # --- object store arena (store/object_store.py) ---
            "store_bytes": m.Gauge(
                "ray_tpu_store_bytes_used", "Arena bytes currently allocated."
            ),
            "store_capacity": m.Gauge(
                "ray_tpu_store_capacity_bytes", "Arena capacity in bytes."
            ),
            "store_objects": m.Gauge(
                "ray_tpu_store_objects", "Objects resident in the node store."
            ),
            "store_seals": m.Counter(
                "ray_tpu_store_seals_total", "Objects sealed into the store."
            ),
            "store_spills": m.Counter(
                "ray_tpu_store_spills_total", "Objects spilled to external storage."
            ),
            "store_spilled_bytes": m.Counter(
                "ray_tpu_store_spilled_bytes_total", "Bytes spilled to external storage."
            ),
            "store_evictions": m.Counter(
                "ray_tpu_store_evictions_total",
                "Arena blocks evicted (freed after spill) under memory pressure.",
            ),
            # --- RPC plane (rpc.py WIRE counters via collector) ---
            "rpc_frames": m.Counter(
                "ray_tpu_rpc_frames_total",
                "Wire frames by direction.",
                tag_keys=("dir",),
            ),
            "rpc_bytes": m.Counter(
                "ray_tpu_rpc_bytes_total",
                "Wire bytes by direction.",
                tag_keys=("dir",),
            ),
            "rpc_connects": m.Counter(
                "ray_tpu_rpc_connects_total", "Client connections established."
            ),
            "rpc_resets": m.Counter(
                "ray_tpu_rpc_resets_total", "Client connections lost/reset."
            ),
            "rpc_hwm_stalls": m.Counter(
                "ray_tpu_rpc_write_hwm_stalls_total",
                "Writes that hit the socket write high-water mark (backpressure).",
            ),
            # --- transfer plane (push_manager.py / pull_manager.py) ---
            "transfer_bytes": m.Counter(
                "ray_tpu_transfer_bytes_total",
                "Object chunk payload bytes moved node-to-node, by direction.",
                tag_keys=("dir",),
            ),
            "transfer_chunks": m.Counter(
                "ray_tpu_transfer_chunks_total",
                "Object chunks moved node-to-node, by direction and wire "
                "framing (raw = zero-copy raw frames, msgpack = negotiated "
                "fallback).",
                tag_keys=("dir", "frame"),
            ),
            "transfer_pushes": m.Counter(
                "ray_tpu_transfer_pushes_total", "Outbound pushes committed."
            ),
            "transfer_pulls": m.Counter(
                "ray_tpu_transfer_pulls_total", "Pulls sealed into the local store."
            ),
            "transfer_relays": m.Counter(
                "ray_tpu_transfer_relays_total",
                "Cut-through broadcast relays completed (chunks forwarded "
                "downstream before the local copy sealed).",
            ),
            "transfer_pull_sources": m.Counter(
                "ray_tpu_transfer_pull_sources_total",
                "Source replicas that served chunks of a striped pull "
                "(per-pull average = this / pulls).",
            ),
            "transfer_admission_stalls": m.Counter(
                "ray_tpu_transfer_admission_stalls_total",
                "Pulls that queued on pull_admission_budget_bytes before "
                "allocating arena space.",
            ),
            "transfer_source_demotions": m.Counter(
                "ray_tpu_transfer_source_demotions_total",
                "Pull sources demoted to the back of the ranking after an "
                "error mid-transfer.",
            ),
            # --- compiled-DAG channel plane (experimental/channel/) ---
            "channel_writes": m.Counter(
                "ray_tpu_channel_writes_total", "Envelopes published to channels."
            ),
            "channel_backpressure": m.Counter(
                "ray_tpu_channel_backpressure_total",
                "Channel writes that blocked on a full ring.",
            ),
            "channel_occupancy": m.Gauge(
                "ray_tpu_channel_ring_occupancy",
                "Unconsumed slots observed at the last sampled channel write "
                "in this process (per-channel tags would leak one stale "
                "series per torn-down channel).",
            ),
            # --- MPMD pipeline / descriptor channel plane (PR 12) ---
            "pipeline_microbatches": m.Counter(
                "ray_tpu_pipeline_microbatches_total",
                "Resident-loop stage iterations completed in this process "
                "(one microbatch through one stage).",
            ),
            "pipeline_stall": m.Counter(
                "ray_tpu_pipeline_stall_seconds_total",
                "Seconds resident-loop stages spent blocked on input "
                "channels (pipeline bubble + upstream latency).",
            ),
            "pipeline_resolve_latency": m.Histogram(
                "ray_tpu_pipeline_resolve_latency_s",
                "Descriptor-slot resolution latency (KIND_DEVICE envelope "
                "to live value: inbox take / pull fallback / local).",
                boundaries=_LATENCY_BOUNDS,
            ),
            # --- Serve router (serve/_private/router.py) ---
            "serve_requests": m.Counter(
                "ray_tpu_serve_requests_total",
                "Requests routed to replicas.",
                tag_keys=("deployment",),
            ),
            "serve_queue_depth": m.Gauge(
                "ray_tpu_serve_router_queue_depth",
                "In-flight requests across this router's replicas.",
                tag_keys=("deployment",),
            ),
            "serve_migrations": m.Counter(
                "ray_tpu_serve_migrations_total",
                "Streaming requests migrated mid-stream to another replica "
                "after a replica death (proxy-side teacher-forced resume).",
                tag_keys=("deployment",),
            ),
            "serve_drains": m.Counter(
                "ray_tpu_serve_drains_total",
                "Replica drains completed before deliberate retirement "
                "(downscale / rolling update), by outcome.",
                tag_keys=("outcome",),
            ),
            "serve_latency": m.Histogram(
                "ray_tpu_serve_replica_latency_s",
                "Replica request latency observed at the handle (assign -> result).",
                boundaries=_LATENCY_BOUNDS,
                tag_keys=("deployment",),
            ),
            # --- continuous-batching LLM engine (serve/llm/engine.py) ---
            "serve_llm_running": m.Gauge(
                "ray_tpu_serve_llm_running_sequences",
                "Sequences occupying a decode slot in this process's engine.",
            ),
            "serve_llm_waiting": m.Gauge(
                "ray_tpu_serve_llm_waiting_sequences",
                "Prompts queued for a decode slot / KV blocks.",
            ),
            "serve_llm_kv_util": m.Gauge(
                "ray_tpu_serve_llm_kv_block_utilization",
                "Allocated fraction of the paged KV block pool (0..1; "
                "includes refs-0 prefix-cache blocks held for reuse).",
            ),
            "serve_llm_prefix_hits": m.Counter(
                "ray_tpu_serve_llm_prefix_hits_total",
                "Prompt blocks served from the prefix cache at admission "
                "(prefill skipped for those tokens).",
            ),
            "serve_llm_prefix_misses": m.Counter(
                "ray_tpu_serve_llm_prefix_misses_total",
                "Hashable prompt blocks that had to be prefilled.",
            ),
            "serve_llm_preemptions": m.Counter(
                "ray_tpu_serve_llm_preemptions_total",
                "Sequences preempted for KV blocks (recompute on readmission).",
            ),
            "serve_llm_evictions": m.Counter(
                "ray_tpu_serve_llm_prefix_evictions_total",
                "refs-0 prefix-cache blocks evicted under allocation pressure.",
            ),
            "serve_llm_handoffs": m.Counter(
                "ray_tpu_serve_llm_handoffs_total",
                "Completed prefill→decode KV handoffs (sealed payload "
                "imported on the decode side; descriptors only in-band, "
                "payloads on the direct-mailbox p2p plane).",
            ),
            "serve_llm_prefix_imports": m.Counter(
                "ray_tpu_serve_llm_prefix_imports_total",
                "Cluster-prefix-tier KV import attempts by outcome: hit "
                "(payload landed), miss (no registry row for any probed "
                "depth), error (row existed but the payload was gone or "
                "the fetch failed).",
                tag_keys=("outcome",),
            ),
            "serve_llm_ttft": m.Histogram(
                "ray_tpu_serve_llm_ttft_s",
                "Time to first token: submit -> first token emitted.",
                boundaries=_LATENCY_BOUNDS,
            ),
            "serve_llm_tpot": m.Histogram(
                "ray_tpu_serve_llm_time_per_output_token_s",
                "Per-request mean inter-token latency (first -> last token).",
                boundaries=_LATENCY_BOUNDS,
            ),
            # --- Data executor (data/_internal/) ---
            "data_rows": m.Counter(
                "ray_tpu_data_output_rows_total",
                "Rows produced per Data operator.",
                tag_keys=("op",),
            ),
            "data_bytes": m.Counter(
                "ray_tpu_data_output_bytes_total",
                "Bytes produced per Data operator.",
                tag_keys=("op",),
            ),
            "data_blocks": m.Counter(
                "ray_tpu_data_output_blocks_total",
                "Blocks produced per Data operator.",
                tag_keys=("op",),
            ),
            # --- device object plane (experimental/device_object/) ---
            "devobj_resident": m.Gauge(
                "ray_tpu_devobj_resident",
                "Device-resident objects held by this process.",
            ),
            "devobj_resident_bytes": m.Gauge(
                "ray_tpu_devobj_resident_bytes",
                "Bytes of device-resident object payloads held by this process.",
            ),
            "devobj_transfers": m.Counter(
                "ray_tpu_devobj_transfers_total",
                "Device-object resolutions by transfer kind "
                "(local = same-process zero-copy, collective = group p2p, "
                "host = inline/arena fallback).",
                tag_keys=("kind",),
            ),
            "devobj_spills": m.Counter(
                "ray_tpu_devobj_spills_total",
                "Device objects spilled device->host into the arena.",
            ),
            "devobj_restores": m.Counter(
                "ray_tpu_devobj_restores_total",
                "Spilled device objects restored host->device.",
            ),
            # --- group collectives (util/collective, PR 15) ---
            "collective_broadcasts": m.Counter(
                "ray_tpu_collective_broadcasts_total",
                "Group broadcasts fanned out by this process (one per "
                "device_object.broadcast on the holder).",
            ),
            "collective_broadcast_bytes": m.Counter(
                "ray_tpu_collective_broadcast_bytes_total",
                "Serialized payload bytes delivered by group broadcasts "
                "(payload size x delivered ranks).",
            ),
            "collective_bcast_recvs": m.Counter(
                "ray_tpu_collective_bcast_recvs_total",
                "Payloads this process took from its broadcast landing zone "
                "(descriptor resolves + explicit bcast_recv_payload).",
            ),
            "collective_bcast_fallbacks": m.Counter(
                "ray_tpu_collective_bcast_fallbacks_total",
                "Per-rank broadcast deliveries that fell back to the GCS-KV "
                "mailbox (member without a registered address).",
            ),
            "collective_bcast_failed_ranks": m.Counter(
                "ray_tpu_collective_bcast_failed_ranks_total",
                "Ranks a group broadcast could not deliver to (dead or "
                "severed members; named in CollectiveBroadcastError).",
            ),
            "collective_timeouts": m.Counter(
                "ray_tpu_collective_timeouts_total",
                "Typed collective timeouts raised (CollectiveTimeoutError: "
                "ring _collect and broadcast recv).",
            ),
            # --- relay-tree collectives (PR 16) ---
            "collective_tree_sends": m.Counter(
                "ray_tpu_collective_tree_broadcasts_total",
                "Group broadcasts that rode the binomial relay tree "
                "(vs the flat per-rank fan-out).",
            ),
            "collective_bcast_retries": m.Counter(
                "ray_tpu_collective_bcast_retries_total",
                "Ranks re-delivered DIRECTLY after a relay failure orphaned "
                "them (tree broadcast flat-fallback recoveries).",
            ),
            "collective_root_egress_bytes": m.Counter(
                "ray_tpu_collective_root_egress_bytes_total",
                "Payload bytes this process pushed as a broadcast ROOT — "
                "sub-O(K) on the tree topology (the relay fan-out carries "
                "the rest).",
            ),
            "collective_relay_forwards": m.Counter(
                "ray_tpu_collective_relay_forwards_total",
                "Relay legs completed by this process (every chunk of one "
                "tree broadcast forwarded to one child).",
            ),
            "collective_relay_bytes": m.Counter(
                "ray_tpu_collective_relay_bytes_total",
                "Payload bytes this process forwarded mid-tree (cut-through "
                "relay; counted at the forwarding member, not the root).",
            ),
            "collective_reduce_sends": m.Counter(
                "ray_tpu_collective_reduce_sends_total",
                "Tree-reduce participations by this process (one per "
                "group_reduce_send call that completed).",
            ),
            "collective_reduce_bytes": m.Counter(
                "ray_tpu_collective_reduce_bytes_total",
                "Combined-partial bytes this process pushed up the reduce "
                "tree toward its parent.",
            ),
            "collective_allreduces": m.Counter(
                "ray_tpu_collective_allreduces_total",
                "Allreduce participations (tree reduce up + broadcast "
                "back down) by this process.",
            ),
            "collective_reducescatters": m.Counter(
                "ray_tpu_collective_reducescatters_total",
                "Reduce-scatter participations (tree reduce up + per-rank "
                "shard fan-out from the root) by this process.",
            ),
            "collective_scatter_bytes": m.Counter(
                "ray_tpu_collective_scatter_bytes_total",
                "Serialized reduce-scatter shard bytes this process pushed "
                "to members as the scatter root.",
            ),
            "collective_host_sync_fallbacks": m.Counter(
                "ray_tpu_collective_host_sync_fallbacks_total",
                "Broadcast payloads a GROUP MEMBER had to resolve over the "
                "host pull path instead of its broadcast inbox — a fleet "
                "quietly riding pull-resolve (off the elastic fast path) "
                "shows up here, not in silence.",
            ),
            "collective_member_changes": m.Counter(
                "ray_tpu_collective_member_changes_total",
                "Roster epoch advances published by this process "
                "(join/rejoin/leave/death/advance of elastic group "
                "membership).",
            ),
            # --- actor lifecycle (gcs.py) ---
            "actor_restarts": m.Counter(
                "ray_tpu_actor_restarts_total", "Actor restarts driven by the GCS."
            ),
            # --- GCS fan-in hardening (gcs.py) ---
            "gcs_events_dropped": m.Counter(
                "ray_tpu_gcs_events_dropped_total",
                "Task events dropped (oldest-first) by the GCS ingest ring "
                "under overload — observability degrades, liveness never "
                "does (paired with the gcs_overload flight event).",
            ),
            "locality_hits": m.Counter(
                "ray_tpu_sched_locality_hits_total",
                "Tasks placed on a node already holding their reference "
                "args (locality-aware scheduling fast path).",
            ),
            # --- chaos fault-injection plane (chaos.py) ---
            "chaos_injected": m.Counter(
                "ray_tpu_chaos_injected_total",
                "Faults injected at the RPC frame seam by the active chaos "
                "plan, by kind (zero in production: no plan installed).",
                tag_keys=("kind",),
            ),
        }
        m.register_collector(_collect_wire_stats)
        m.register_collector(_collect_chaos_stats)
        m.register_collector(_collect_serve_llm_stats)
        m.register_collector(_collect_transfer_stats)
        m.register_collector(_collect_lease_stats)
        m.register_collector(_collect_channel_stats)
        m.register_collector(_collect_pipeline_stats)
        m.register_collector(_collect_devobj_stats)
        m.register_collector(_collect_collective_stats)
        _instruments = inst
    return _instruments


# Last-folded values per (source, attr): the plain-int stats objects are
# monotonic, Counters need deltas.
_folded: dict = {}


def _fold(source_key: str, stats_obj, pairs) -> None:
    """Fold monotonic plain-int attrs of a hot-path stats object into
    Counters. ``pairs`` = [(attr, counter, tags-or-None)]."""
    inst = _instruments
    if inst is None:
        return
    for attr, counter, tags in pairs:
        cur = getattr(stats_obj, attr)
        key = (source_key, attr)
        delta = cur - _folded.get(key, 0)
        if delta > 0:
            _folded[key] = cur
            counter.inc(delta, tags=tags)


def _collect_wire_stats():
    from ray_tpu._private.rpc import WIRE

    inst = _instruments
    if inst is None:
        return
    _fold("wire", WIRE, [
        ("frames_out", inst["rpc_frames"], {"dir": "out"}),
        ("frames_in", inst["rpc_frames"], {"dir": "in"}),
        ("bytes_out", inst["rpc_bytes"], {"dir": "out"}),
        ("bytes_in", inst["rpc_bytes"], {"dir": "in"}),
        ("connects", inst["rpc_connects"], None),
        ("resets", inst["rpc_resets"], None),
        ("hwm_stalls", inst["rpc_hwm_stalls"], None),
    ])


def _collect_chaos_stats():
    from ray_tpu._private.chaos import CHAOS_STATS

    inst = _instruments
    if inst is None:
        return
    _fold("chaos", CHAOS_STATS, [
        ("drops", inst["chaos_injected"], {"kind": "drop"}),
        ("delays", inst["chaos_injected"], {"kind": "delay"}),
        ("dups", inst["chaos_injected"], {"kind": "dup"}),
        ("resets", inst["chaos_injected"], {"kind": "reset"}),
        ("partition_blocks", inst["chaos_injected"], {"kind": "partition"}),
        ("kills", inst["chaos_injected"], {"kind": "kill"}),
    ])


def _collect_transfer_stats():
    from ray_tpu._private.transfer_stats import TRANSFER

    inst = _instruments
    if inst is None:
        return
    _fold("transfer", TRANSFER, [
        ("bytes_out", inst["transfer_bytes"], {"dir": "out"}),
        ("bytes_in", inst["transfer_bytes"], {"dir": "in"}),
        ("chunks_raw_out", inst["transfer_chunks"], {"dir": "out", "frame": "raw"}),
        ("chunks_msgpack_out", inst["transfer_chunks"], {"dir": "out", "frame": "msgpack"}),
        ("chunks_raw_in", inst["transfer_chunks"], {"dir": "in", "frame": "raw"}),
        ("chunks_msgpack_in", inst["transfer_chunks"], {"dir": "in", "frame": "msgpack"}),
        ("pushes", inst["transfer_pushes"], None),
        ("pulls", inst["transfer_pulls"], None),
        ("relays", inst["transfer_relays"], None),
        ("pull_sources", inst["transfer_pull_sources"], None),
        ("admission_stalls", inst["transfer_admission_stalls"], None),
        ("source_demotions", inst["transfer_source_demotions"], None),
    ])


def _collect_channel_stats():
    from ray_tpu.experimental.channel.channel import CHANNEL_STATS

    inst = _instruments
    if inst is None:
        return
    _fold("channel", CHANNEL_STATS, [
        ("writes", inst["channel_writes"], None),
        ("backpressure", inst["channel_backpressure"], None),
    ])
    if CHANNEL_STATS.writes:
        inst["channel_occupancy"].set(CHANNEL_STATS.last_occupancy)


def _collect_pipeline_stats():
    from ray_tpu.experimental.channel.channel import PIPELINE_STATS

    inst = _instruments
    if inst is None:
        return
    _fold("pipeline", PIPELINE_STATS, [
        ("microbatches", inst["pipeline_microbatches"], None),
    ])
    # Stall is kept as plain ns on the hot path; fold the delta as seconds.
    cur = PIPELINE_STATS.stall_ns
    key = ("pipeline", "stall_ns")
    delta = cur - _folded.get(key, 0)
    if delta > 0:
        _folded[key] = cur
        inst["pipeline_stall"].inc(delta / 1e9)
    # Drain buffered resolve-latency observations into the histogram at
    # flush cadence (the resolver appends plain floats, no instrument lock
    # per microbatch).
    samples = PIPELINE_STATS.resolve_samples
    while True:
        try:
            s = samples.popleft()
        except IndexError:
            break
        inst["pipeline_resolve_latency"].observe(s)


def _collect_devobj_stats():
    from ray_tpu.experimental.device_object.manager import DEVOBJ_STATS, active_manager

    inst = _instruments
    if inst is None:
        return
    _fold("devobj", DEVOBJ_STATS, [
        ("transfers_local", inst["devobj_transfers"], {"kind": "local"}),
        ("transfers_collective", inst["devobj_transfers"], {"kind": "collective"}),
        ("transfers_host", inst["devobj_transfers"], {"kind": "host"}),
        ("chan_sends", inst["devobj_transfers"], {"kind": "chan_send"}),
        ("chan_recvs", inst["devobj_transfers"], {"kind": "chan_recv"}),
        ("spills", inst["devobj_spills"], None),
        ("restores", inst["devobj_restores"], None),
    ])
    mgr = active_manager()
    if mgr is not None:
        usage = mgr.usage()
        inst["devobj_resident"].set(usage["resident_count"])
        inst["devobj_resident_bytes"].set(usage["resident_bytes"])


def _collect_collective_stats():
    from ray_tpu.util.collective.p2p import COLL

    inst = _instruments
    if inst is None:
        return
    _fold("collective", COLL, [
        ("bcast_sends", inst["collective_broadcasts"], None),
        ("bcast_send_bytes", inst["collective_broadcast_bytes"], None),
        ("bcast_recvs", inst["collective_bcast_recvs"], None),
        ("bcast_fallbacks", inst["collective_bcast_fallbacks"], None),
        ("bcast_failed_ranks", inst["collective_bcast_failed_ranks"], None),
        ("timeouts", inst["collective_timeouts"], None),
        ("tree_sends", inst["collective_tree_sends"], None),
        ("bcast_retries", inst["collective_bcast_retries"], None),
        ("root_egress_bytes", inst["collective_root_egress_bytes"], None),
        ("relay_forwards", inst["collective_relay_forwards"], None),
        ("relay_bytes", inst["collective_relay_bytes"], None),
        ("reduce_sends", inst["collective_reduce_sends"], None),
        ("reduce_bytes", inst["collective_reduce_bytes"], None),
        ("allreduces", inst["collective_allreduces"], None),
        ("reducescatters", inst["collective_reducescatters"], None),
        ("scatter_bytes", inst["collective_scatter_bytes"], None),
        ("host_sync_fallbacks", inst["collective_host_sync_fallbacks"], None),
        ("member_changes", inst["collective_member_changes"], None),
    ])


def _collect_serve_llm_stats():
    from ray_tpu.serve.llm.stats import ENGINES, LLM

    inst = _instruments
    if inst is None:
        return
    _fold("serve_llm", LLM, [
        ("prefix_hit_blocks", inst["serve_llm_prefix_hits"], None),
        ("prefix_miss_blocks", inst["serve_llm_prefix_misses"], None),
        ("preemptions", inst["serve_llm_preemptions"], None),
        ("evicted_blocks", inst["serve_llm_evictions"], None),
        ("handoffs", inst["serve_llm_handoffs"], None),
        ("prefix_import_hits", inst["serve_llm_prefix_imports"], {"outcome": "hit"}),
        ("prefix_import_misses", inst["serve_llm_prefix_imports"], {"outcome": "miss"}),
        ("prefix_import_errors", inst["serve_llm_prefix_imports"], {"outcome": "error"}),
    ])
    engines = list(ENGINES)
    if not engines and not LLM.admitted:
        return  # no engine has ever lived in this process
    # Gauges are summed across LIVE engines at flush time (best-effort
    # plain-int reads, like LLMEngine.stats()): several engines fold into
    # one series, and once the last scheduler exits the sums — and the
    # exported gauges — honestly drop to zero instead of going stale.
    running = waiting = used = total = 0
    for eng in engines:
        running += sum(r is not None for r in eng._slots)
        waiting += len(eng._waiting)
        used += (eng.num_blocks - 1) - len(eng._free)
        total += eng.num_blocks - 1
    inst["serve_llm_running"].set(running)
    inst["serve_llm_waiting"].set(waiting)
    inst["serve_llm_kv_util"].set(used / total if total else 0.0)


def _collect_lease_stats():
    from ray_tpu._private.lease_manager import LEASE_STATS

    inst = _instruments
    if inst is None:
        return
    _fold("lease", LEASE_STATS, [
        ("grants", inst["lease_grants"], None),
        ("reuses", inst["lease_reuses"], None),
        ("tasks", inst["lease_tasks"], None),
    ])
