"""Sender-side object push manager.

TPU-native analog of the reference's PushManager
(src/ray/object_manager/push_manager.h:29): owner/holder-initiated chunked
pushes with per-destination concurrency caps and pipelined chunk RPCs, plus
receiver-side admission control (the receiver can refuse a push session when
saturated — reference: pull_manager.h:52 admission control — and the sender
backs off and retries).

The round-1 transfer path was pull-only (a node fetched chunks on demand);
pushes make broadcast possible: the holder streams an object out without the
receiver asking, and `rpc_broadcast_object` (raylet.py) fans out over a
binomial tree so a 1 GiB broadcast to N nodes costs the root O(log N) object
sends instead of N.
"""

from __future__ import annotations

import asyncio
import logging

from ray_tpu._private.config import get_config

logger = logging.getLogger(__name__)


class PushManager:
    def __init__(self, raylet):
        cfg = get_config()
        self.raylet = raylet
        self.chunk = cfg.object_transfer_chunk_bytes
        self.pipeline_depth = cfg.push_pipeline_depth
        self.max_per_dest = cfg.push_max_concurrent_per_dest
        self.admission_retries = cfg.push_admission_retries
        self._dest_sems: dict[str, asyncio.Semaphore] = {}
        self._active: dict[tuple, asyncio.Future] = {}

    def stats(self) -> dict:
        return {"active_pushes": len(self._active)}

    async def push(self, object_id: str, node_id: str, address) -> bool:
        """Push a sealed local object to one destination node. Deduplicates
        concurrent identical pushes; returns True once the object is sealed
        remotely (or already present there)."""
        key = (object_id, node_id)
        fut = self._active.get(key)
        if fut is not None:
            return await fut
        fut = asyncio.get_event_loop().create_future()
        self._active[key] = fut
        ok = False
        try:
            ok = await self._push_once(object_id, node_id, address)
        except Exception as e:
            logger.debug("push %s -> %s failed: %s", object_id[:8], node_id[:8], e)
        finally:
            # Resolve in the finally so deduplicated waiters are released even
            # if this task is CANCELLED (CancelledError skips `except
            # Exception`; an unresolved future would hang them forever).
            self._active.pop(key, None)
            if not fut.done():
                fut.set_result(ok)
        return ok

    async def _push_once(self, object_id: str, node_id: str, address) -> bool:
        sem = self._dest_sems.setdefault(node_id, asyncio.Semaphore(self.max_per_dest))
        async with sem:
            peer = self.raylet._peer(node_id, address)
            offset, size = await self.raylet.store.get(object_id)  # pins the object
            try:
                accepted = False
                for attempt in range(self.admission_retries):
                    begin = await peer.acall(
                        "push_begin", {"object_id": object_id, "size": size}
                    )
                    if begin.get("already"):
                        return True
                    if begin.get("accepted"):
                        accepted = True
                        break
                    await asyncio.sleep(begin.get("retry_after", 0.1) * (1 + attempt * 0.2))
                if not accepted:
                    return False
                try:
                    # Pipelined chunk stream: up to pipeline_depth chunk RPCs
                    # in flight (reference paces by chunks in flight too).
                    inflight = asyncio.Semaphore(self.pipeline_depth)

                    async def send(start: int):
                        async with inflight:
                            length = min(self.chunk, size - start)
                            data = bytes(self.raylet.arena.read(offset + start, length))
                            await peer.acall(
                                "push_chunk",
                                {"object_id": object_id, "start": start, "data": data},
                            )

                    await asyncio.gather(
                        *(asyncio.ensure_future(send(s)) for s in range(0, size, self.chunk))
                    )
                    resp = await peer.acall("push_commit", {"object_id": object_id})
                    return bool(resp.get("ok"))
                except BaseException:
                    try:
                        await peer.acall("push_abort", {"object_id": object_id})
                    except Exception:
                        pass
                    raise
            finally:
                self.raylet.store.release(object_id)
